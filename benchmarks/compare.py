"""Cross-engine comparison harness — the role the reference's Spark
comparison plays (spark/benchmarks/src/main/scala/.../Main.scala:45-195:
run the same TPC-H queries on a second engine for relative measurement).

This image has no Spark/JVM, so the second engine is the strongest
available independent baseline: pyarrow's own compute layer (hash
group_by/join kernels in Arrow C++) driven directly, next to this
framework's host backend and TPU backend. Each engine answers the same
queries over the same parquet files; results are checked against each
other before timings are reported.

Usage:
    python -m benchmarks.compare --data .bench_cache/tpch_sf1.0 \
        --queries q1 q3 q6 [--iterations 3] [--engines tpu host pyarrow]

Prints a markdown table of per-query best times and relative speed.
"""

from __future__ import annotations

import argparse
import pathlib
import sys
import time
from typing import Dict, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

QUERIES_DIR = REPO / "benchmarks" / "tpch" / "queries"


# -- engine: this framework (host or tpu backend) --------------------------


class BallistaEngine:
    def __init__(self, data: str, backend: str) -> None:
        from ballista_tpu.config import BallistaConfig
        from ballista_tpu.engine import ExecutionContext
        from benchmarks.tpch.datagen import register_all

        self.ctx = ExecutionContext(
            BallistaConfig(
                {
                    "ballista.executor.backend": backend,
                    "ballista.batch.size": "16777216",
                }
            )
        )
        register_all(self.ctx, data)

    def run(self, name: str) -> pa.Table:
        sql = (QUERIES_DIR / f"{name}.sql").read_text()
        return self.ctx.sql(sql).collect()


# -- engine: pandas oracles (all 22 queries) -------------------------------


class PandasOracleEngine:
    """The shared pandas oracles (benchmarks/tpch/oracles.py) as a
    comparison engine — covers the full 22-query list, matching the breadth
    of the reference's Spark harness (Main.scala:45-195)."""

    def __init__(self, data: str) -> None:
        self.dir = pathlib.Path(data)
        self._tables = None

    def _load(self):
        if self._tables is None:
            names = ["lineitem", "orders", "customer", "supplier", "nation",
                     "region", "part", "partsupp"]
            self._tables = {}
            for n in names:
                files = sorted((self.dir / n).glob("*.parquet"))
                self._tables[n] = pa.concat_tables(
                    pq.read_table(f) for f in files
                ).to_pandas()
        return self._tables

    def run(self, name: str) -> Optional[pa.Table]:
        from benchmarks.tpch.oracles import ORACLES

        fn = ORACLES.get(name)
        if fn is None:
            return None
        return pa.Table.from_pandas(fn(self._load()), preserve_index=False)


# -- engine: raw pyarrow (independent Arrow C++ baseline) ------------------


class PyArrowEngine:
    """Hand-written pyarrow implementations of the comparison queries —
    independent of this framework's planner/operators, like the reference's
    Spark implementations are independent of DataFusion."""

    def __init__(self, data: str) -> None:
        self.dir = pathlib.Path(data)
        self._cache: Dict[str, pa.Table] = {}

    def _t(self, name: str) -> pa.Table:
        if name not in self._cache:
            files = sorted((self.dir / name).glob("*.parquet"))
            self._cache[name] = pa.concat_tables(pq.read_table(f) for f in files)
        return self._cache[name]

    def run(self, name: str) -> Optional[pa.Table]:
        fn = getattr(self, f"_{name}", None)
        return fn() if fn else None

    def _q1(self) -> pa.Table:
        import datetime

        li = self._t("lineitem")
        m = pc.less_equal(li.column("l_shipdate"), pa.scalar(datetime.date(1998, 9, 2)))
        li = li.filter(m)
        disc_price = pc.multiply(
            li.column("l_extendedprice"), pc.subtract(pa.scalar(1.0), li.column("l_discount"))
        )
        charge = pc.multiply(disc_price, pc.add(pa.scalar(1.0), li.column("l_tax")))
        t = li.append_column("disc_price", disc_price).append_column("charge", charge)
        out = t.group_by(["l_returnflag", "l_linestatus"]).aggregate(
            [
                ("l_quantity", "sum"),
                ("l_extendedprice", "sum"),
                ("disc_price", "sum"),
                ("charge", "sum"),
                ("l_quantity", "mean"),
                ("l_extendedprice", "mean"),
                ("l_discount", "mean"),
                ("l_quantity", "count"),
            ]
        )
        return out.sort_by([("l_returnflag", "ascending"), ("l_linestatus", "ascending")])

    def _q6(self) -> pa.Table:
        import datetime

        li = self._t("lineitem")
        m = pc.and_(
            pc.and_(
                pc.greater_equal(li.column("l_shipdate"), pa.scalar(datetime.date(1994, 1, 1))),
                pc.less(li.column("l_shipdate"), pa.scalar(datetime.date(1995, 1, 1))),
            ),
            pc.and_(
                pc.and_(
                    pc.greater_equal(li.column("l_discount"), pa.scalar(0.05)),
                    pc.less_equal(li.column("l_discount"), pa.scalar(0.07)),
                ),
                pc.less(li.column("l_quantity"), pa.scalar(24.0)),
            ),
        )
        li = li.filter(m)
        rev = pc.sum(pc.multiply(li.column("l_extendedprice"), li.column("l_discount")))
        return pa.table({"revenue": pa.array([rev.as_py()])})

    def _q3(self) -> pa.Table:
        import datetime

        cutoff = datetime.date(1995, 3, 15)
        cust = self._t("customer").filter(
            pc.equal(self._t("customer").column("c_mktsegment"), pa.scalar("BUILDING"))
        ).select(["c_custkey"])
        orders = self._t("orders")
        orders = orders.filter(
            pc.less(orders.column("o_orderdate"), pa.scalar(cutoff))
        ).select(["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"])
        li = self._t("lineitem")
        li = li.filter(pc.greater(li.column("l_shipdate"), pa.scalar(cutoff))).select(
            ["l_orderkey", "l_extendedprice", "l_discount"]
        )
        j = orders.join(cust, keys="o_custkey", right_keys="c_custkey", join_type="inner")
        j = li.join(j, keys="l_orderkey", right_keys="o_orderkey", join_type="inner")
        rev = pc.multiply(
            j.column("l_extendedprice"), pc.subtract(pa.scalar(1.0), j.column("l_discount"))
        )
        j = j.append_column("rev", rev)
        out = j.group_by(["l_orderkey", "o_orderdate", "o_shippriority"]).aggregate(
            [("rev", "sum")]
        )
        out = out.sort_by([("rev_sum", "descending"), ("o_orderdate", "ascending")])
        return out.slice(0, 10)


    def _q5(self) -> pa.Table:
        import datetime

        lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
        orders = self._t("orders")
        orders = orders.filter(
            pc.and_(
                pc.greater_equal(orders.column("o_orderdate"), pa.scalar(lo)),
                pc.less(orders.column("o_orderdate"), pa.scalar(hi)),
            )
        ).select(["o_orderkey", "o_custkey"])
        cust = self._t("customer").select(["c_custkey", "c_nationkey"])
        li = self._t("lineitem").select(
            ["l_orderkey", "l_suppkey", "l_extendedprice", "l_discount"]
        )
        supp = self._t("supplier").select(["s_suppkey", "s_nationkey"])
        nat = self._t("nation").select(["n_nationkey", "n_name", "n_regionkey"])
        reg = self._t("region")
        reg = reg.filter(pc.equal(reg.column("r_name"), pa.scalar("ASIA"))).select(
            ["r_regionkey"]
        )
        j = orders.join(cust, keys="o_custkey", right_keys="c_custkey", join_type="inner")
        j = li.join(j, keys="l_orderkey", right_keys="o_orderkey", join_type="inner")
        j = j.join(supp, keys="l_suppkey", right_keys="s_suppkey", join_type="inner")
        j = j.filter(pc.equal(j.column("c_nationkey"), j.column("s_nationkey")))
        j = j.join(nat, keys="s_nationkey", right_keys="n_nationkey", join_type="inner")
        j = j.join(reg, keys="n_regionkey", right_keys="r_regionkey", join_type="inner")
        rev = pc.multiply(
            j.column("l_extendedprice"),
            pc.subtract(pa.scalar(1.0), j.column("l_discount")),
        )
        out = j.append_column("rev", rev).group_by(["n_name"]).aggregate(
            [("rev", "sum")]
        )
        return out.sort_by([("rev_sum", "descending")])

    def _q10(self) -> pa.Table:
        import datetime

        lo, hi = datetime.date(1993, 10, 1), datetime.date(1994, 1, 1)
        orders = self._t("orders")
        orders = orders.filter(
            pc.and_(
                pc.greater_equal(orders.column("o_orderdate"), pa.scalar(lo)),
                pc.less(orders.column("o_orderdate"), pa.scalar(hi)),
            )
        ).select(["o_orderkey", "o_custkey"])
        li = self._t("lineitem")
        li = li.filter(pc.equal(li.column("l_returnflag"), pa.scalar("R"))).select(
            ["l_orderkey", "l_extendedprice", "l_discount"]
        )
        cust = self._t("customer").select(
            ["c_custkey", "c_name", "c_acctbal", "c_phone", "c_nationkey",
             "c_address", "c_comment"]
        )
        nat = self._t("nation").select(["n_nationkey", "n_name"])
        j = li.join(orders, keys="l_orderkey", right_keys="o_orderkey", join_type="inner")
        j = j.join(cust, keys="o_custkey", right_keys="c_custkey", join_type="inner")
        j = j.join(nat, keys="c_nationkey", right_keys="n_nationkey", join_type="inner")
        rev = pc.multiply(
            j.column("l_extendedprice"),
            pc.subtract(pa.scalar(1.0), j.column("l_discount")),
        )
        out = (
            j.append_column("rev", rev)
            .group_by(["o_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                       "c_address", "c_comment"])
            .aggregate([("rev", "sum")])
        )
        out = out.sort_by([("rev_sum", "descending")]).slice(0, 20)
        # query column order (revenue third), so the cross-check's
        # first-float-column heuristic compares revenue on every engine
        return out.select(
            ["o_custkey", "c_name", "rev_sum", "c_acctbal", "n_name",
             "c_address", "c_phone", "c_comment"]
        )

    def _q12(self) -> pa.Table:
        import datetime

        lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
        li = self._t("lineitem")
        li = li.filter(
            pc.and_(
                pc.and_(
                    pc.is_in(li.column("l_shipmode"),
                             value_set=pa.array(["MAIL", "SHIP"])),
                    pc.less(li.column("l_commitdate"), li.column("l_receiptdate")),
                ),
                pc.and_(
                    pc.less(li.column("l_shipdate"), li.column("l_commitdate")),
                    pc.and_(
                        pc.greater_equal(li.column("l_receiptdate"), pa.scalar(lo)),
                        pc.less(li.column("l_receiptdate"), pa.scalar(hi)),
                    ),
                ),
            )
        ).select(["l_orderkey", "l_shipmode"])
        orders = self._t("orders").select(["o_orderkey", "o_orderpriority"])
        j = li.join(orders, keys="l_orderkey", right_keys="o_orderkey", join_type="inner")
        high = pc.is_in(j.column("o_orderpriority"),
                        value_set=pa.array(["1-URGENT", "2-HIGH"]))
        highf = pc.cast(high, pa.float64())
        j = j.append_column("high", highf).append_column(
            "low", pc.subtract(pa.scalar(1.0), highf)
        )
        out = j.group_by(["l_shipmode"]).aggregate([("high", "sum"), ("low", "sum")])
        return out.sort_by([("l_shipmode", "ascending")])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=str(REPO / ".bench_cache" / "tpch_sf1.0"))
    ap.add_argument("--queries", nargs="+",
                    default=["q1", "q3", "q5", "q6", "q10", "q12"],
                    help="query names, or 'all' for the full 22-query list")
    ap.add_argument("--iterations", type=int, default=3)
    ap.add_argument("--engines", nargs="+",
                    default=["tpu", "host", "pyarrow", "pandas"])
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when engines disagree (CI mode)")
    args = ap.parse_args()
    if args.queries == ["all"]:
        args.queries = [f"q{i}" for i in range(1, 23)]
    mismatches = 0

    engines: Dict[str, object] = {}
    for e in args.engines:
        if e in ("tpu", "host"):
            engines[e] = BallistaEngine(args.data, e)
        elif e == "pyarrow":
            engines[e] = PyArrowEngine(args.data)
        elif e == "pandas":
            engines[e] = PandasOracleEngine(args.data)

    rows = []
    for q in args.queries:
        results, times = {}, {}
        for name, eng in engines.items():
            out = eng.run(q)
            if out is None:
                continue
            best = float("inf")
            for _ in range(args.iterations):
                t0 = time.perf_counter()
                out = eng.run(q)
                best = min(best, time.perf_counter() - t0)
            results[name], times[name] = out, best
        if not times:
            print(f"{q}: no engine produced a result — skipped", file=sys.stderr)
            continue
        # cross-check row count and the first numeric column across engines
        base_name = base_rows = base_vals = None
        for name, out in results.items():
            vals = None
            # first float column (measure columns like revenue — their
            # sorted multiset is tie-invariant); when none exists (q12's
            # int64 counts) fall back to the first integer column so the
            # strict gate still value-checks
            idx = next(
                (i for i, f in enumerate(out.schema)
                 if pa.types.is_floating(f.type)),
                next((i for i, f in enumerate(out.schema)
                      if pa.types.is_integer(f.type)), None),
            )
            if idx is not None:
                vals = np.sort(np.array(out.column(idx).to_pylist(), dtype=float))
            if base_name is None:
                base_name, base_rows, base_vals = name, out.num_rows, vals
                continue
            if out.num_rows != base_rows:
                mismatches += 1
                print(f"WARNING: {q}: {name} rows={out.num_rows} != "
                      f"{base_name} rows={base_rows}", file=sys.stderr)
            elif (
                vals is not None
                and base_vals is not None
                and not np.allclose(vals, base_vals, rtol=1e-3, equal_nan=True)
            ):
                mismatches += 1
                print(f"WARNING: {q}: {name} values disagree with {base_name}",
                      file=sys.stderr)
        ref = times.get("host") or next(iter(times.values()))
        rows.append((q, times, ref))

    names = list(engines)
    print("| query | " + " | ".join(f"{n} (ms)" for n in names) + " | best vs host |")
    print("|" + "---|" * (len(names) + 2))
    for q, times, ref in rows:
        cells = [f"{times[n] * 1e3:.0f}" if n in times else "—" for n in names]
        fastest = min(times, key=times.get)
        print(f"| {q} | " + " | ".join(cells) +
              f" | {fastest} {ref / times[fastest]:.2f}x |")
    if args.strict and mismatches:
        print(f"{mismatches} cross-engine mismatches", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()

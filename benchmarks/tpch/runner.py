"""TPC-H benchmark CLI.

Mirrors the reference harness (rust/benchmarks/tpch/src/main.rs):

  benchmark: register the 8 tables (tbl | csv | parquet), run queries against
             a local context or a remote scheduler, time iterations
  convert:   tbl -> csv/parquet with partitioning
  datagen:   generate data (the reference shells out to dockerized dbgen;
             here the built-in vectorized generator)

Examples:
  python -m benchmarks.tpch.runner benchmark --path /data/tpch --format parquet \
      --query 1 --iterations 3 --backend tpu
  python -m benchmarks.tpch.runner benchmark --path /data/tpch --host localhost --port 50050
  python -m benchmarks.tpch.runner convert --input /data/tbl --output /data/parquet \
      --format parquet --partitions 8
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

from benchmarks.tpch.schema import TPCH_TABLES, get_tpch_schema  # noqa: E402

QUERIES = pathlib.Path(__file__).parent / "queries"


def register_tables(ctx, path: str, fmt: str) -> None:
    import pyarrow as pa

    for t in TPCH_TABLES:
        tpath = os.path.join(path, t)
        if fmt == "parquet":
            ctx.register_parquet(t, tpath)
        elif fmt == "csv":
            ctx.register_csv(t, tpath, schema=get_tpch_schema(t), has_header=True)
        elif fmt == "tbl":
            # dbgen .tbl: '|'-delimited, no header, trailing delimiter makes a
            # ghost column — declare it then project it away via the schema
            schema = get_tpch_schema(t)
            ctx.register_csv(
                t, tpath, schema=schema, has_header=False, delimiter="|",
                file_extension=".tbl",
            )
        else:
            raise SystemExit(f"unknown format {fmt!r}")


def cmd_benchmark(args) -> None:
    from ballista_tpu.config import BallistaConfig

    settings = {
        "ballista.batch.size": str(args.batch_size),
        "ballista.executor.backend": args.backend,
    }
    if args.host:
        from ballista_tpu.client import BallistaContext

        ctx = BallistaContext(args.host, args.port, settings)
    else:
        from ballista_tpu.engine import ExecutionContext

        ctx = ExecutionContext(BallistaConfig(settings))
    register_tables(ctx, args.path, args.format)

    queries = [args.query] if args.query else list(range(1, 23))
    results = {}
    for q in queries:
        sql = (QUERIES / f"q{q}.sql").read_text()
        times = []
        rows = 0
        for i in range(args.iterations):
            t0 = time.perf_counter()
            out = ctx.sql(sql).collect()
            dt = time.perf_counter() - t0
            rows = out.num_rows
            times.append(dt)
            print(f"q{q} iteration {i} took {dt*1000:.1f} ms ({rows} rows)",
                  file=sys.stderr)
            if args.debug:
                print(out.to_pandas().to_string(), file=sys.stderr)
        results[f"q{q}"] = {"min_ms": round(min(times) * 1000, 1), "rows": rows}
    print(json.dumps(results))


def cmd_convert(args) -> None:
    import pyarrow as pa
    import pyarrow.csv as pcsv
    import pyarrow.parquet as pq

    os.makedirs(args.output, exist_ok=True)
    for t in TPCH_TABLES:
        src = os.path.join(args.input, f"{t}.tbl")
        if not os.path.exists(src):
            src = os.path.join(args.input, t)
        schema = get_tpch_schema(t)
        # dbgen rows end with a trailing '|' -> one ghost column
        names = schema.names + ["__dummy"]
        table = pcsv.read_csv(
            src,
            read_options=pcsv.ReadOptions(column_names=names),
            parse_options=pcsv.ParseOptions(delimiter="|"),
            convert_options=pcsv.ConvertOptions(
                column_types={f.name: f.type for f in schema},
                include_columns=schema.names,
            ),
        ).cast(schema)
        out_dir = os.path.join(args.output, t)
        os.makedirs(out_dir, exist_ok=True)
        n = max(1, args.partitions)
        step = (table.num_rows + n - 1) // n
        for p in range(n):
            chunk = table.slice(p * step, step)
            if args.format == "parquet":
                pq.write_table(chunk, os.path.join(out_dir, f"part-{p:03d}.parquet"))
            else:
                pcsv.write_csv(chunk, os.path.join(out_dir, f"part-{p:03d}.csv"))
        print(f"converted {t}: {table.num_rows} rows -> {n} {args.format} files",
              file=sys.stderr)


def cmd_datagen(args) -> None:
    from benchmarks.tpch.datagen import generate

    generate(args.out, args.sf, args.parts, args.seed)
    print(f"TPC-H sf={args.sf} written to {args.out}", file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser(prog="tpch")
    sub = ap.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("benchmark")
    b.add_argument("--path", required=True)
    b.add_argument("--format", default="parquet", choices=["parquet", "csv", "tbl"])
    b.add_argument("--query", type=int)
    b.add_argument("--iterations", type=int, default=3)
    b.add_argument("--batch-size", type=int, default=32768)
    b.add_argument("--backend", default="cpu", choices=["cpu", "tpu"])
    b.add_argument("--host", help="remote scheduler host (distributed mode)")
    b.add_argument("--port", type=int, default=50050)
    b.add_argument("--debug", action="store_true", help="print query results")
    b.set_defaults(fn=cmd_benchmark)

    c = sub.add_parser("convert")
    c.add_argument("--input", required=True)
    c.add_argument("--output", required=True)
    c.add_argument("--format", default="parquet", choices=["parquet", "csv"])
    c.add_argument("--partitions", type=int, default=1)
    c.set_defaults(fn=cmd_convert)

    d = sub.add_parser("datagen")
    d.add_argument("--sf", type=float, default=0.01)
    d.add_argument("--out", required=True)
    d.add_argument("--parts", type=int, default=2)
    d.add_argument("--seed", type=int, default=20260728)
    d.set_defaults(fn=cmd_datagen)

    args = ap.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()

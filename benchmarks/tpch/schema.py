"""TPC-H table schemas.

Mirrors the reference's inline schema definitions
(rust/benchmarks/tpch/src/main.rs:267-360). DECIMAL columns are float64 here:
the engine's numeric tower is TPU-first (bf16/f32/f64), and the reference's
own CSV path reads decimals as floats too.
"""

import pyarrow as pa

TPCH_TABLES = [
    "part", "supplier", "partsupp", "customer", "orders", "lineitem",
    "nation", "region",
]


def get_tpch_schema(table: str) -> pa.Schema:
    f = pa.field
    if table == "part":
        return pa.schema([
            f("p_partkey", pa.int64()),
            f("p_name", pa.string()),
            f("p_mfgr", pa.string()),
            f("p_brand", pa.string()),
            f("p_type", pa.string()),
            f("p_size", pa.int32()),
            f("p_container", pa.string()),
            f("p_retailprice", pa.float64()),
            f("p_comment", pa.string()),
        ])
    if table == "supplier":
        return pa.schema([
            f("s_suppkey", pa.int64()),
            f("s_name", pa.string()),
            f("s_address", pa.string()),
            f("s_nationkey", pa.int64()),
            f("s_phone", pa.string()),
            f("s_acctbal", pa.float64()),
            f("s_comment", pa.string()),
        ])
    if table == "partsupp":
        return pa.schema([
            f("ps_partkey", pa.int64()),
            f("ps_suppkey", pa.int64()),
            f("ps_availqty", pa.int32()),
            f("ps_supplycost", pa.float64()),
            f("ps_comment", pa.string()),
        ])
    if table == "customer":
        return pa.schema([
            f("c_custkey", pa.int64()),
            f("c_name", pa.string()),
            f("c_address", pa.string()),
            f("c_nationkey", pa.int64()),
            f("c_phone", pa.string()),
            f("c_acctbal", pa.float64()),
            f("c_mktsegment", pa.string()),
            f("c_comment", pa.string()),
        ])
    if table == "orders":
        return pa.schema([
            f("o_orderkey", pa.int64()),
            f("o_custkey", pa.int64()),
            f("o_orderstatus", pa.string()),
            f("o_totalprice", pa.float64()),
            f("o_orderdate", pa.date32()),
            f("o_orderpriority", pa.string()),
            f("o_clerk", pa.string()),
            f("o_shippriority", pa.int32()),
            f("o_comment", pa.string()),
        ])
    if table == "lineitem":
        return pa.schema([
            f("l_orderkey", pa.int64()),
            f("l_partkey", pa.int64()),
            f("l_suppkey", pa.int64()),
            f("l_linenumber", pa.int32()),
            f("l_quantity", pa.float64()),
            f("l_extendedprice", pa.float64()),
            f("l_discount", pa.float64()),
            f("l_tax", pa.float64()),
            f("l_returnflag", pa.string()),
            f("l_linestatus", pa.string()),
            f("l_shipdate", pa.date32()),
            f("l_commitdate", pa.date32()),
            f("l_receiptdate", pa.date32()),
            f("l_shipinstruct", pa.string()),
            f("l_shipmode", pa.string()),
            f("l_comment", pa.string()),
        ])
    if table == "nation":
        return pa.schema([
            f("n_nationkey", pa.int64()),
            f("n_name", pa.string()),
            f("n_regionkey", pa.int64()),
            f("n_comment", pa.string()),
        ])
    if table == "region":
        return pa.schema([
            f("r_regionkey", pa.int64()),
            f("r_name", pa.string()),
            f("r_comment", pa.string()),
        ])
    raise ValueError(f"unknown TPC-H table {table!r}")

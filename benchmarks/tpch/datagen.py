"""Deterministic vectorized TPC-H data generator (dbgen-lite).

The reference relies on dockerized dbgen (rust/benchmarks/tpch/tpch-gen.sh,
tpchgen.dockerfile); no network/docker here, so this generates the same table
shapes with dbgen's row counts, key relationships, value domains, and the
string distributions the 22 queries filter on (brands, types, containers,
segments, priorities, ship modes, nations/regions, phone prefixes,
comment keywords). Not bit-identical to dbgen — q outputs differ numerically
from published TPC-H answers, so correctness tests compare against an
independent oracle (pyarrow/pandas) on the same data.

Usage: python -m benchmarks.tpch.datagen --sf 0.01 --out /tmp/tpch --parts 2
"""

from __future__ import annotations

import argparse
import os
from typing import List

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from benchmarks.tpch.schema import get_tpch_schema

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
CONTAINERS = [
    f"{a} {b}"
    for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
    for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
]
TYPE_1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "hyacinth", "indian", "ivory", "khaki", "lace", "lavender", "lawn",
]
COMMENT_WORDS = [
    "carefully", "quickly", "furiously", "slyly", "blithely", "special",
    "requests", "packages", "deposits", "accounts", "instructions", "pending",
    "unusual", "express", "regular", "ironic", "final", "bold", "silent",
    "even", "daring", "brave", "quiet", "complaints", "theodolites",
]

DATE_EPOCH = np.datetime64("1970-01-01")
START = (np.datetime64("1992-01-01") - DATE_EPOCH).astype(np.int32)
END = (np.datetime64("1998-08-02") - DATE_EPOCH).astype(np.int32)


def _take(pool: List[str], idx: np.ndarray) -> pa.Array:
    """Build a string column by dictionary take (vectorized, no python loop)."""
    return pa.DictionaryArray.from_arrays(
        pa.array(idx, type=pa.int32()), pa.array(pool)
    ).cast(pa.string())


def _comments(rng: np.random.Generator, n: int) -> pa.Array:
    import pyarrow.compute as pc

    w = [
        _take(COMMENT_WORDS, rng.integers(0, len(COMMENT_WORDS), n))
        for _ in range(3)
    ]
    return pc.binary_join_element_wise(w[0], w[1], w[2], " ")


def _numbered(prefix: str, keys: np.ndarray) -> pa.Array:
    return pa.array(np.char.mod(prefix + "#%09d", keys))


def gen_region() -> pa.Table:
    return pa.table(
        {
            "r_regionkey": pa.array(np.arange(5, dtype=np.int64)),
            "r_name": pa.array(REGIONS),
            "r_comment": pa.array(["" for _ in REGIONS]),
        },
        schema=get_tpch_schema("region"),
    )


def gen_nation() -> pa.Table:
    return pa.table(
        {
            "n_nationkey": pa.array(np.arange(25, dtype=np.int64)),
            "n_name": pa.array([n for n, _ in NATIONS]),
            "n_regionkey": pa.array(np.array([r for _, r in NATIONS], dtype=np.int64)),
            "n_comment": pa.array(["" for _ in NATIONS]),
        },
        schema=get_tpch_schema("nation"),
    )


def gen_supplier(sf: float, rng: np.random.Generator) -> pa.Table:
    n = max(1, int(10_000 * sf))
    keys = np.arange(1, n + 1, dtype=np.int64)
    nk = rng.integers(0, 25, n).astype(np.int64)
    phone = pa.array(np.char.mod("%02d-989-741-2988", 10 + nk))
    return pa.table(
        {
            "s_suppkey": keys,
            "s_name": _numbered("Supplier", keys),
            "s_address": _numbered("Addr", keys),
            "s_nationkey": nk,
            "s_phone": phone,
            "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n), 2),
            "s_comment": _comments(rng, n),
        },
        schema=get_tpch_schema("supplier"),
    )


def gen_part(sf: float, rng: np.random.Generator, lo: int = 0,
             n: int = None) -> pa.Table:
    import pyarrow.compute as pc

    if n is None:
        n = max(1, int(200_000 * sf))
    keys = np.arange(lo + 1, lo + n + 1, dtype=np.int64)
    name = pc.binary_join_element_wise(
        _take(COLORS, rng.integers(0, len(COLORS), n)),
        _take(COLORS, rng.integers(0, len(COLORS), n)),
        " ",
    )
    # Brand#MN with M,N in 1..5
    m = rng.integers(1, 6, n)
    nn = rng.integers(1, 6, n)
    brand = pa.array(np.char.mod("Brand#%d", m * 10 + nn))
    ptype = pc.binary_join_element_wise(
        _take(TYPE_1, rng.integers(0, len(TYPE_1), n)),
        _take(TYPE_2, rng.integers(0, len(TYPE_2), n)),
        _take(TYPE_3, rng.integers(0, len(TYPE_3), n)),
        " ",
    )
    return pa.table(
        {
            "p_partkey": keys,
            "p_name": name,
            "p_mfgr": pa.array(np.char.mod("Manufacturer#%d", rng.integers(1, 6, n))),
            "p_brand": brand,
            "p_type": ptype,
            "p_size": rng.integers(1, 51, n).astype(np.int32),
            "p_container": _take(CONTAINERS, rng.integers(0, len(CONTAINERS), n)),
            "p_retailprice": np.round(
                900 + (keys % 1000) / 10 + 100 * (keys % 10), 2
            ).astype(np.float64),
            "p_comment": _comments(rng, n),
        },
        schema=get_tpch_schema("part"),
    )


def gen_partsupp(sf: float, rng: np.random.Generator, lo: int = 0,
                 n: int = None) -> pa.Table:
    # lo/n are in PART-key space (4 rows per part)
    n_part = max(1, int(200_000 * sf))
    n_supp = max(1, int(10_000 * sf))
    if n is None:
        lo, n = 0, n_part
    pk = np.repeat(np.arange(lo + 1, lo + n + 1, dtype=np.int64), 4)
    i = np.tile(np.arange(4, dtype=np.int64), n)
    sk = ((pk + i * (n_supp // 4 + 1)) % n_supp) + 1
    n = len(pk)
    return pa.table(
        {
            "ps_partkey": pk,
            "ps_suppkey": sk,
            "ps_availqty": rng.integers(1, 10_000, n).astype(np.int32),
            "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, n), 2),
            "ps_comment": _comments(rng, n),
        },
        schema=get_tpch_schema("partsupp"),
    )


def gen_customer(sf: float, rng: np.random.Generator, lo: int = 0,
                 n: int = None) -> pa.Table:
    if n is None:
        n = max(1, int(150_000 * sf))
    keys = np.arange(lo + 1, lo + n + 1, dtype=np.int64)
    nk = rng.integers(0, 25, n).astype(np.int64)
    return pa.table(
        {
            "c_custkey": keys,
            "c_name": _numbered("Customer", keys),
            "c_address": _numbered("Addr", keys),
            "c_nationkey": nk,
            "c_phone": pa.array(np.char.mod("%02d-467-109-8538", 10 + nk)),
            "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n), 2),
            "c_mktsegment": _take(SEGMENTS, rng.integers(0, len(SEGMENTS), n)),
            "c_comment": _comments(rng, n),
        },
        schema=get_tpch_schema("customer"),
    )


def gen_orders(sf: float, rng: np.random.Generator, lo: int = 0,
               n: int = None) -> pa.Table:
    if n is None:
        n = max(1, int(1_500_000 * sf))
    n_cust = max(1, int(150_000 * sf))
    keys = np.arange(lo + 1, lo + n + 1, dtype=np.int64)
    # dbgen: only 2/3 of customers have orders
    ck = (rng.integers(0, max(1, n_cust * 2 // 3), n) * 3 % n_cust) + 1
    odate = rng.integers(START, END - 121, n).astype(np.int32)
    return pa.table(
        {
            "o_orderkey": keys,
            "o_custkey": ck.astype(np.int64),
            "o_orderstatus": _take(["O", "F", "P"], rng.integers(0, 3, n)),
            "o_totalprice": np.round(rng.uniform(850.0, 560_000.0, n), 2),
            "o_orderdate": pa.array(odate, type=pa.date32()),
            "o_orderpriority": _take(PRIORITIES, rng.integers(0, 5, n)),
            "o_clerk": _numbered("Clerk", rng.integers(1, max(2, int(1000 * sf) + 1), n).astype(np.int64)),
            "o_shippriority": np.zeros(n, dtype=np.int32),
            "o_comment": _comments(rng, n),
        },
        schema=get_tpch_schema("orders"),
    )


def gen_lineitem(sf: float, rng: np.random.Generator, orders: pa.Table) -> pa.Table:
    n_part = max(1, int(200_000 * sf))
    n_supp = max(1, int(10_000 * sf))
    okeys = orders.column("o_orderkey").to_numpy()
    odates = orders.column("o_orderdate").cast(pa.int32()).to_numpy()
    lines_per = rng.integers(1, 8, len(okeys))
    lok = np.repeat(okeys, lines_per)
    lod = np.repeat(odates, lines_per)
    n = len(lok)
    linenumber = (
        np.arange(n, dtype=np.int64)
        - np.repeat(np.concatenate(([0], np.cumsum(lines_per)[:-1])), lines_per)
        + 1
    )
    pk = rng.integers(1, n_part + 1, n).astype(np.int64)
    # dbgen supplier selection: one of 4 suppliers for the part
    i = rng.integers(0, 4, n)
    sk = ((pk + i * (n_supp // 4 + 1)) % n_supp) + 1
    qty = rng.integers(1, 51, n).astype(np.float64)
    extprice = np.round(qty * (900 + (pk % 1000) / 10 + 100 * (pk % 10)), 2)
    ship = lod + rng.integers(1, 122, n).astype(np.int32)
    commit = lod + rng.integers(30, 91, n).astype(np.int32)
    receipt = ship + rng.integers(1, 31, n).astype(np.int32)
    returnflag = np.where(
        receipt <= (np.datetime64("1995-06-17") - DATE_EPOCH).astype(np.int32),
        rng.choice(["R", "A"], n),
        "N",
    )
    linestatus = np.where(
        ship > (np.datetime64("1995-06-17") - DATE_EPOCH).astype(np.int32), "O", "F"
    )
    return pa.table(
        {
            "l_orderkey": lok,
            "l_partkey": pk,
            "l_suppkey": sk,
            "l_linenumber": linenumber.astype(np.int32),
            "l_quantity": qty,
            "l_extendedprice": extprice,
            "l_discount": np.round(rng.integers(0, 11, n) / 100.0, 2),
            "l_tax": np.round(rng.integers(0, 9, n) / 100.0, 2),
            "l_returnflag": pa.array(returnflag),
            "l_linestatus": pa.array(linestatus),
            "l_shipdate": pa.array(ship, type=pa.date32()),
            "l_commitdate": pa.array(commit, type=pa.date32()),
            "l_receiptdate": pa.array(receipt, type=pa.date32()),
            "l_shipinstruct": _take(INSTRUCTIONS, rng.integers(0, 4, n)),
            "l_shipmode": _take(SHIPMODES, rng.integers(0, 7, n)),
            "l_comment": _comments(rng, n),
        },
        schema=get_tpch_schema("lineitem"),
    )


def write_partitioned(table: pa.Table, out_dir: str, name: str, parts: int) -> None:
    d = os.path.join(out_dir, name)
    os.makedirs(d, exist_ok=True)
    n = table.num_rows
    parts = max(1, min(parts, n))
    step = (n + parts - 1) // parts
    for p in range(parts):
        chunk = table.slice(p * step, step)
        pq.write_table(chunk, os.path.join(d, f"part-{p:03d}.parquet"))


# per-chunk generation caps (keys per chunk): bound peak memory so SF=100
# streams to parquet instead of materializing ~600M lineitem rows at once
# (the reference's dbgen also streams, rust/benchmarks/tpch/tpch-gen.sh)
_CHUNK_KEYS = {
    "part": 4_000_000,
    "partsupp": 1_000_000,  # part-key space: 4 rows per key
    "customer": 4_000_000,
    "orders": 2_000_000,  # ~4x lineitem rows ride along per chunk
}


def _chunked_write(out_dir, name, total, parts, seed, gen_chunk) -> None:
    """Write `total` keys of table `name`, generated in fixed-size chunks
    seeded from rng([seed, tag, k]). Chunking depends ONLY on the table's
    cap — never on `parts` — so the DATA is deterministic for a given
    (seed, sf); `parts` only controls the file layout (generated chunks are
    sliced into sub-files when fewer chunks than parts exist)."""
    import zlib

    d = os.path.join(out_dir, name)
    os.makedirs(d, exist_ok=True)
    cap = _CHUNK_KEYS[name]
    n_chunks = max(1, -(-total // cap))
    step = -(-total // n_chunks)
    subsplit = max(1, -(-max(1, parts) // n_chunks))
    tag = zlib.crc32(name.encode())  # stable across processes (hash() is not)
    for k in range(n_chunks):
        lo = k * step
        n = min(step, total - lo)
        if n <= 0:
            break
        rng = np.random.default_rng([seed, tag, k])
        gen_chunk(rng, lo, n, d, k, subsplit)


def _write_split(table: pa.Table, d: str, k: int, subsplit: int) -> None:
    rows = table.num_rows
    ss = min(subsplit, max(1, rows))
    sstep = -(-rows // ss)
    for s in range(ss):
        chunk = table.slice(s * sstep, sstep)
        if chunk.num_rows:
            pq.write_table(chunk, os.path.join(d, f"part-{k:03d}-{s:02d}.parquet"))


def generate(out_dir: str, sf: float = 0.01, parts: int = 2, seed: int = 20260728) -> None:
    import shutil

    os.makedirs(out_dir, exist_ok=True)
    # start clean: scans glob every *.parquet under a table dir, so files
    # surviving from an interrupted or older-layout run would silently
    # duplicate rows in the regenerated dataset
    marker = os.path.join(out_dir, "_SUCCESS")
    if os.path.exists(marker):
        os.remove(marker)
    for t in ("region", "nation", "supplier", "part", "partsupp",
              "customer", "orders", "lineitem"):
        shutil.rmtree(os.path.join(out_dir, t), ignore_errors=True)
    rng = np.random.default_rng(seed)
    write_partitioned(gen_region(), out_dir, "region", 1)
    write_partitioned(gen_nation(), out_dir, "nation", 1)
    write_partitioned(gen_supplier(sf, rng), out_dir, "supplier", 1)

    _chunked_write(
        out_dir, "part", max(1, int(200_000 * sf)), parts, seed,
        lambda r, lo, n, d, k, ss: _write_split(gen_part(sf, r, lo, n), d, k, ss),
    )
    _chunked_write(
        out_dir, "partsupp", max(1, int(200_000 * sf)), parts, seed,
        lambda r, lo, n, d, k, ss: _write_split(gen_partsupp(sf, r, lo, n), d, k, ss),
    )
    _chunked_write(
        out_dir, "customer", max(1, int(150_000 * sf)), parts, seed,
        lambda r, lo, n, d, k, ss: _write_split(gen_customer(sf, r, lo, n), d, k, ss),
    )

    # orders + lineitem ride the same chunk (lineitem rows derive from the
    # chunk's orders)
    li_dir = os.path.join(out_dir, "lineitem")
    os.makedirs(li_dir, exist_ok=True)

    def orders_chunk(r, lo, n, d, k, ss):
        o = gen_orders(sf, r, lo, n)
        _write_split(o, d, k, ss)
        _write_split(gen_lineitem(sf, r, o), li_dir, k, ss)

    _chunked_write(
        out_dir, "orders", max(1, int(1_500_000 * sf)), parts, seed, orders_chunk
    )
    # completeness marker: generation streams for hours at SF=100; consumers
    # (bench.py ensure_data) must not mistake an interrupted run for a dataset
    with open(os.path.join(out_dir, "_SUCCESS"), "w") as f:
        f.write(f"sf={sf} parts={parts} seed={seed}\n")


def is_complete(out_dir: str) -> bool:
    return os.path.exists(os.path.join(out_dir, "_SUCCESS"))


def register_all(ctx, data_dir: str) -> None:
    from benchmarks.tpch.schema import TPCH_TABLES

    for t in TPCH_TABLES:
        ctx.register_parquet(t, os.path.join(data_dir, t))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--out", required=True)
    ap.add_argument("--parts", type=int, default=2)
    ap.add_argument("--seed", type=int, default=20260728)
    a = ap.parse_args()
    generate(a.out, a.sf, a.parts, a.seed)
    print(f"TPC-H sf={a.sf} written to {a.out}")

"""Independent pandas oracles for all 22 TPC-H queries.

One function per query, `qN(tables) -> pd.DataFrame`, where `tables` maps
table name -> pandas DataFrame (dates as python `datetime.date`). These are
hand-derived from the TPC-H specification text, independent of this
framework's planner/operators — the correctness role the reference assigns
to its Spark comparison harness (spark/benchmarks/.../Main.scala:45-195)
and to the expected-q1 table in rust/benchmarks/tpch/README.md:73-84,
extended here to the full query list with programmatic assertions.

Scalar aggregate queries (q6, q14, q17, q19) return a one-row frame whose
value is NaN when the SQL result would be NULL (aggregate over zero rows).

Shared by tests/test_tpch.py (tiny-SF assertions) and benchmarks/compare.py
(cross-engine validation at benchmark SF).
"""

from __future__ import annotations

from typing import Dict

import numpy as np
import pandas as pd


def _date(s: str):
    return pd.Timestamp(s).date()


def _years(col):
    return pd.to_datetime(col).dt.year


def q1(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    li = t["lineitem"]
    d = li[li.l_shipdate <= _date("1998-09-02")]
    disc = d.l_extendedprice * (1 - d.l_discount)
    return (
        d.assign(disc_price=disc, charge=disc * (1 + d.l_tax))
        .groupby(["l_returnflag", "l_linestatus"], as_index=False)
        .agg(
            sum_qty=("l_quantity", "sum"),
            sum_base_price=("l_extendedprice", "sum"),
            sum_disc_price=("disc_price", "sum"),
            sum_charge=("charge", "sum"),
            avg_qty=("l_quantity", "mean"),
            avg_price=("l_extendedprice", "mean"),
            avg_disc=("l_discount", "mean"),
            count_order=("l_quantity", "size"),
        )
        .sort_values(["l_returnflag", "l_linestatus"])
        .reset_index(drop=True)
    )


def q2(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    eu_n = t["nation"].merge(
        t["region"][t["region"].r_name == "EUROPE"],
        left_on="n_regionkey", right_on="r_regionkey",
    )
    eu_s = t["supplier"].merge(eu_n, left_on="s_nationkey", right_on="n_nationkey")
    eu_ps = t["partsupp"].merge(eu_s, left_on="ps_suppkey", right_on="s_suppkey")
    min_cost = eu_ps.groupby("ps_partkey").ps_supplycost.min()
    p = t["part"]
    sel = p[(p.p_size == 15) & p.p_type.str.endswith("BRASS")]
    j = eu_ps.merge(sel, left_on="ps_partkey", right_on="p_partkey")
    j = j[j.ps_supplycost == j.ps_partkey.map(min_cost)]
    return (
        j[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
           "s_address", "s_phone", "s_comment"]]
        .sort_values(
            ["s_acctbal", "n_name", "s_name", "p_partkey"],
            ascending=[False, True, True, True],
        )
        .head(100)
        .reset_index(drop=True)
    )


def q3(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    c, o, li = t["customer"], t["orders"], t["lineitem"]
    cut = _date("1995-03-15")
    j = (
        c[c.c_mktsegment == "BUILDING"]
        .merge(o[o.o_orderdate < cut], left_on="c_custkey", right_on="o_custkey")
        .merge(li[li.l_shipdate > cut], left_on="o_orderkey", right_on="l_orderkey")
    )
    j = j.assign(rev=j.l_extendedprice * (1 - j.l_discount))
    return (
        j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"], as_index=False)
        .agg(revenue=("rev", "sum"))
        [["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]]
        .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
        .head(10)
        .reset_index(drop=True)
    )


def q4(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    o, li = t["orders"], t["lineitem"]
    lo, hi = _date("1993-07-01"), _date("1993-10-01")
    ok = li[li.l_commitdate < li.l_receiptdate].l_orderkey.unique()
    d = o[(o.o_orderdate >= lo) & (o.o_orderdate < hi) & o.o_orderkey.isin(ok)]
    return (
        d.groupby("o_orderpriority", as_index=False)
        .agg(order_count=("o_orderkey", "size"))
        .sort_values("o_orderpriority")
        .reset_index(drop=True)
    )


def q5(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    lo, hi = _date("1994-01-01"), _date("1995-01-01")
    j = (
        t["customer"]
        .merge(t["orders"], left_on="c_custkey", right_on="o_custkey")
        .merge(t["lineitem"], left_on="o_orderkey", right_on="l_orderkey")
        .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey")
        .merge(t["region"], left_on="n_regionkey", right_on="r_regionkey")
    )
    j = j[
        (j.c_nationkey == j.s_nationkey)
        & (j.r_name == "ASIA")
        & (j.o_orderdate >= lo)
        & (j.o_orderdate < hi)
    ]
    j = j.assign(rev=j.l_extendedprice * (1 - j.l_discount))
    return (
        j.groupby("n_name", as_index=False)
        .agg(revenue=("rev", "sum"))
        .sort_values("revenue", ascending=False)
        .reset_index(drop=True)
    )


def q6(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    li = t["lineitem"]
    lo, hi = _date("1994-01-01"), _date("1995-01-01")
    d = li[
        (li.l_shipdate >= lo)
        & (li.l_shipdate < hi)
        & (li.l_discount >= 0.05)
        & (li.l_discount <= 0.07)
        & (li.l_quantity < 24)
    ]
    rev = np.nan if d.empty else float((d.l_extendedprice * d.l_discount).sum())
    return pd.DataFrame({"revenue": [rev]})


def q7(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    lo, hi = _date("1995-01-01"), _date("1996-12-31")
    li = t["lineitem"]
    j = (
        t["supplier"]
        .merge(li[(li.l_shipdate >= lo) & (li.l_shipdate <= hi)],
               left_on="s_suppkey", right_on="l_suppkey")
        .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
        .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
        .merge(t["nation"].add_prefix("n1_"), left_on="s_nationkey",
               right_on="n1_n_nationkey")
        .merge(t["nation"].add_prefix("n2_"), left_on="c_nationkey",
               right_on="n2_n_nationkey")
    )
    pair = (
        ((j.n1_n_name == "FRANCE") & (j.n2_n_name == "GERMANY"))
        | ((j.n1_n_name == "GERMANY") & (j.n2_n_name == "FRANCE"))
    )
    j = j[pair]
    return (
        j.assign(
            supp_nation=j.n1_n_name,
            cust_nation=j.n2_n_name,
            l_year=_years(j.l_shipdate),
            volume=j.l_extendedprice * (1 - j.l_discount),
        )
        .groupby(["supp_nation", "cust_nation", "l_year"], as_index=False)
        .agg(revenue=("volume", "sum"))
        .sort_values(["supp_nation", "cust_nation", "l_year"])
        .reset_index(drop=True)
    )


def q8(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    lo, hi = _date("1995-01-01"), _date("1996-12-31")
    o, p = t["orders"], t["part"]
    j = (
        p[p.p_type == "ECONOMY ANODIZED STEEL"]
        .merge(t["lineitem"], left_on="p_partkey", right_on="l_partkey")
        .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .merge(o[(o.o_orderdate >= lo) & (o.o_orderdate <= hi)],
               left_on="l_orderkey", right_on="o_orderkey")
        .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
        .merge(t["nation"].add_prefix("n1_"), left_on="c_nationkey",
               right_on="n1_n_nationkey")
        .merge(t["region"][t["region"].r_name == "AMERICA"],
               left_on="n1_n_regionkey", right_on="r_regionkey")
        .merge(t["nation"].add_prefix("n2_"), left_on="s_nationkey",
               right_on="n2_n_nationkey")
    )
    j = j.assign(
        o_year=_years(j.o_orderdate),
        volume=j.l_extendedprice * (1 - j.l_discount),
    )
    j = j.assign(bra=j.volume.where(j.n2_n_name == "BRAZIL", 0.0))
    return (
        j.groupby("o_year", as_index=False)
        .agg(bra=("bra", "sum"), vol=("volume", "sum"))
        .assign(mkt_share=lambda d: d.bra / d.vol)
        [["o_year", "mkt_share"]]
        .sort_values("o_year")
        .reset_index(drop=True)
    )


def q9(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    p = t["part"]
    j = (
        p[p.p_name.str.contains("green")]
        .merge(t["lineitem"], left_on="p_partkey", right_on="l_partkey")
        .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .merge(
            t["partsupp"],
            left_on=["l_suppkey", "l_partkey"],
            right_on=["ps_suppkey", "ps_partkey"],
        )
        .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
        .merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey")
    )
    j = j.assign(
        nation=j.n_name,
        o_year=_years(j.o_orderdate),
        amount=j.l_extendedprice * (1 - j.l_discount)
        - j.ps_supplycost * j.l_quantity,
    )
    return (
        j.groupby(["nation", "o_year"], as_index=False)
        .agg(sum_profit=("amount", "sum"))
        .sort_values(["nation", "o_year"], ascending=[True, False])
        .reset_index(drop=True)
    )


def q10(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    lo, hi = _date("1993-10-01"), _date("1994-01-01")
    j = (
        t["customer"]
        .merge(t["orders"], left_on="c_custkey", right_on="o_custkey")
        .merge(t["lineitem"], left_on="o_orderkey", right_on="l_orderkey")
        .merge(t["nation"], left_on="c_nationkey", right_on="n_nationkey")
    )
    j = j[(j.o_orderdate >= lo) & (j.o_orderdate < hi) & (j.l_returnflag == "R")]
    j = j.assign(rev=j.l_extendedprice * (1 - j.l_discount))
    return (
        j.groupby(
            ["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
             "c_address", "c_comment"],
            as_index=False,
        )
        .agg(revenue=("rev", "sum"))
        [["c_custkey", "c_name", "revenue", "c_acctbal", "n_name", "c_address",
          "c_phone", "c_comment"]]
        .sort_values("revenue", ascending=False)
        .head(20)
        .reset_index(drop=True)
    )


def q11(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    de = (
        t["partsupp"]
        .merge(t["supplier"], left_on="ps_suppkey", right_on="s_suppkey")
        .merge(t["nation"][t["nation"].n_name == "GERMANY"],
               left_on="s_nationkey", right_on="n_nationkey")
    )
    de = de.assign(v=de.ps_supplycost * de.ps_availqty)
    per_part = de.groupby("ps_partkey", as_index=False).agg(value=("v", "sum"))
    w = per_part[per_part.value > de.v.sum() * 0.0001]
    # ORDER BY value desc leaves ties unordered; break them on the key so the
    # oracle is deterministic (callers re-sort `got` the same way)
    return (
        w.sort_values(["value", "ps_partkey"], ascending=[False, True])
        .reset_index(drop=True)
    )


def q12(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    o, li = t["orders"], t["lineitem"]
    lo, hi = _date("1994-01-01"), _date("1995-01-01")
    j = o.merge(li, left_on="o_orderkey", right_on="l_orderkey")
    j = j[
        j.l_shipmode.isin(["MAIL", "SHIP"])
        & (j.l_commitdate < j.l_receiptdate)
        & (j.l_shipdate < j.l_commitdate)
        & (j.l_receiptdate >= lo)
        & (j.l_receiptdate < hi)
    ]
    high = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).astype(int)
    return (
        j.assign(h=high, l=1 - high)
        .groupby("l_shipmode", as_index=False)
        .agg(high_line_count=("h", "sum"), low_line_count=("l", "sum"))
        .sort_values("l_shipmode")
        .reset_index(drop=True)
    )


def q13(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    c, o = t["customer"], t["orders"]
    o_sel = o[~o.o_comment.str.contains("special.*requests", regex=True)]
    j = c.merge(o_sel, left_on="c_custkey", right_on="o_custkey", how="left")
    per_cust = j.groupby("c_custkey", as_index=False).agg(
        c_count=("o_orderkey", "count")
    )
    return (
        per_cust.groupby("c_count", as_index=False)
        .agg(custdist=("c_count", "size"))
        [["c_count", "custdist"]]
        .sort_values(["custdist", "c_count"], ascending=[False, False])
        .reset_index(drop=True)
    )


def q14(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    li, p = t["lineitem"], t["part"]
    lo, hi = _date("1995-09-01"), _date("1995-10-01")
    j = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)].merge(
        p, left_on="l_partkey", right_on="p_partkey"
    )
    rev = j.l_extendedprice * (1 - j.l_discount)
    total = float(rev.sum())
    if j.empty or total == 0.0:
        return pd.DataFrame({"promo_revenue": [np.nan]})
    promo = float(rev.where(j.p_type.str.startswith("PROMO"), 0.0).sum())
    return pd.DataFrame({"promo_revenue": [100.0 * promo / total]})


def q15(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    li, s = t["lineitem"], t["supplier"]
    lo, hi = _date("1996-01-01"), _date("1996-04-01")
    d = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)]
    rev = (
        d.assign(r=d.l_extendedprice * (1 - d.l_discount))
        .groupby("l_suppkey", as_index=False)
        .agg(total_revenue=("r", "sum"))
    )
    top = rev[rev.total_revenue == rev.total_revenue.max()]
    return (
        s.merge(top, left_on="s_suppkey", right_on="l_suppkey")
        [["s_suppkey", "s_name", "s_address", "s_phone", "total_revenue"]]
        .sort_values("s_suppkey")
        .reset_index(drop=True)
    )


def q16(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    bad = t["supplier"][
        t["supplier"].s_comment.str.contains("Customer.*Complaints", regex=True)
    ].s_suppkey
    p = t["part"]
    sel = p[
        (p.p_brand != "Brand#45")
        & ~p.p_type.str.startswith("MEDIUM POLISHED")
        & p.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])
    ]
    j = t["partsupp"].merge(sel, left_on="ps_partkey", right_on="p_partkey")
    j = j[~j.ps_suppkey.isin(bad)]
    return (
        j.groupby(["p_brand", "p_type", "p_size"], as_index=False)
        .agg(supplier_cnt=("ps_suppkey", "nunique"))
        .sort_values(
            ["supplier_cnt", "p_brand", "p_type", "p_size"],
            ascending=[False, True, True, True],
        )
        .reset_index(drop=True)
    )


def q17(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    li, p = t["lineitem"], t["part"]
    sel = p[(p.p_brand == "Brand#23") & (p.p_container == "MED BOX")]
    j = li.merge(sel, left_on="l_partkey", right_on="p_partkey")
    avg_by_part = li.groupby("l_partkey").l_quantity.mean()
    thresh = j.l_partkey.map(avg_by_part) * 0.2
    d = j[j.l_quantity < thresh]
    val = np.nan if d.empty else float(d.l_extendedprice.sum()) / 7.0
    return pd.DataFrame({"avg_yearly": [val]})


def q18(t: Dict[str, pd.DataFrame], threshold: float = 300) -> pd.DataFrame:
    qty = t["lineitem"].groupby("l_orderkey").l_quantity.sum()
    big = qty[qty > threshold].index
    o = t["orders"]
    j = (
        t["customer"]
        .merge(o[o.o_orderkey.isin(big)], left_on="c_custkey", right_on="o_custkey")
        .merge(t["lineitem"], left_on="o_orderkey", right_on="l_orderkey")
    )
    return (
        j.groupby(
            ["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"],
            as_index=False,
        )
        .agg(sum_qty=("l_quantity", "sum"))
        .sort_values(["o_totalprice", "o_orderdate"], ascending=[False, True])
        .head(100)
        .reset_index(drop=True)
    )


def q19(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    li, p = t["lineitem"], t["part"]
    j = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    c1 = (
        (j.p_brand == "Brand#12")
        & j.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
        & (j.l_quantity >= 1) & (j.l_quantity <= 11)
        & (j.p_size >= 1) & (j.p_size <= 5)
    )
    c2 = (
        (j.p_brand == "Brand#23")
        & j.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
        & (j.l_quantity >= 10) & (j.l_quantity <= 20)
        & (j.p_size >= 1) & (j.p_size <= 10)
    )
    c3 = (
        (j.p_brand == "Brand#34")
        & j.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
        & (j.l_quantity >= 20) & (j.l_quantity <= 30)
        & (j.p_size >= 1) & (j.p_size <= 15)
    )
    common = j.l_shipmode.isin(["AIR", "AIR REG"]) & (
        j.l_shipinstruct == "DELIVER IN PERSON"
    )
    d = j[(c1 | c2 | c3) & common]
    val = np.nan if d.empty else float((d.l_extendedprice * (1 - d.l_discount)).sum())
    return pd.DataFrame({"revenue": [val]})


def q20(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    lo, hi = _date("1994-01-01"), _date("1995-01-01")
    li = t["lineitem"]
    d = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)]
    half = d.groupby(["l_partkey", "l_suppkey"]).l_quantity.sum() * 0.5
    forest = t["part"][t["part"].p_name.str.startswith("forest")].p_partkey
    ps = t["partsupp"][t["partsupp"].ps_partkey.isin(forest)]
    key = list(zip(ps.ps_partkey, ps.ps_suppkey))
    thresh = pd.Series([half.get(k, np.nan) for k in key], index=ps.index)
    ok = ps[ps.ps_availqty > thresh]  # NaN threshold -> row drops, like SQL NULL
    s = t["supplier"].merge(
        t["nation"][t["nation"].n_name == "CANADA"],
        left_on="s_nationkey", right_on="n_nationkey",
    )
    return (
        s[s.s_suppkey.isin(ok.ps_suppkey)][["s_name", "s_address"]]
        .sort_values("s_name")
        .reset_index(drop=True)
    )


def q21(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    li = t["lineitem"]
    l1 = li[li.l_receiptdate > li.l_commitdate]
    suppliers_per_order = li.groupby("l_orderkey").l_suppkey.nunique()
    late_suppliers_per_order = l1.groupby("l_orderkey").l_suppkey.nunique()
    j = (
        t["supplier"]
        .merge(t["nation"][t["nation"].n_name == "SAUDI ARABIA"],
               left_on="s_nationkey", right_on="n_nationkey")
        .merge(l1, left_on="s_suppkey", right_on="l_suppkey")
        .merge(t["orders"][t["orders"].o_orderstatus == "F"],
               left_on="l_orderkey", right_on="o_orderkey")
    )
    multi = j.l_orderkey.map(suppliers_per_order) > 1
    only_late = j.l_orderkey.map(late_suppliers_per_order) == 1
    j = j[multi & only_late]
    return (
        j.groupby("s_name", as_index=False)
        .agg(numwait=("s_name", "size"))
        .sort_values(["numwait", "s_name"], ascending=[False, True])
        .head(100)
        .reset_index(drop=True)
    )


def q22(t: Dict[str, pd.DataFrame]) -> pd.DataFrame:
    c, o = t["customer"], t["orders"]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cc = c.assign(cntrycode=c.c_phone.str[:2])
    sel = cc[cc.cntrycode.isin(codes)]
    avg_bal = sel[sel.c_acctbal > 0.0].c_acctbal.mean()
    no_orders = ~sel.c_custkey.isin(o.o_custkey.unique())
    d = sel[(sel.c_acctbal > avg_bal) & no_orders]
    return (
        d.groupby("cntrycode", as_index=False)
        .agg(numcust=("c_custkey", "size"), totacctbal=("c_acctbal", "sum"))
        .sort_values("cntrycode")
        .reset_index(drop=True)
    )


ORACLES = {f"q{i}": globals()[f"q{i}"] for i in range(1, 23)}

"""NYC-taxi-shaped trip data generator (BASELINE.md config #4:
high-cardinality group-by over a Parquet scan).

Schema follows the TLC yellow-cab trip records: 265 location zones, vendor
ids, timestamps, distances, fares. Deterministic and SF-scalable
(sf=1 -> ~10M trips, roughly a month of NYC volume).

Usage: python -m benchmarks.taxi.datagen --sf 0.1 --out /tmp/taxi
"""

from __future__ import annotations

import argparse
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

N_ZONES = 265


def gen_trips(sf: float, seed: int = 20260728, n_zones: int = N_ZONES) -> pa.Table:
    """n_zones=265 matches the TLC zone map; larger values emulate finer
    geo granularity (e.g. block-level ids) for the high-cardinality
    group-by configuration."""
    n = max(1, int(10_000_000 * sf))
    rng = np.random.default_rng(seed)
    # zone popularity follows a heavy tail like the real data
    zone_weights = rng.pareto(1.2, n_zones) + 1
    zone_weights /= zone_weights.sum()
    pu = rng.choice(n_zones, n, p=zone_weights).astype(np.int64) + 1
    do = rng.choice(n_zones, n, p=zone_weights).astype(np.int64) + 1
    start = np.datetime64("2024-01-01").astype("datetime64[s]").astype(np.int64)
    pickup_ts = start + rng.integers(0, 31 * 24 * 3600, n)
    duration = rng.gamma(2.0, 420.0, n).astype(np.int64) + 60
    distance = np.round(rng.gamma(2.0, 1.6, n), 2)
    fare = np.round(3.0 + distance * 2.5 + duration / 60 * 0.5, 2)
    tip = np.round(fare * rng.beta(2, 8, n), 2)
    return pa.table(
        {
            "vendor_id": rng.integers(1, 3, n),
            "pickup_datetime": pa.array(pickup_ts, type=pa.timestamp("s")),
            "pickup_location_id": pu,
            "dropoff_location_id": do,
            "passenger_count": rng.integers(1, 7, n),
            "trip_distance": distance,
            "fare_amount": fare,
            "tip_amount": tip,
            "total_amount": np.round(fare + tip, 2),
        }
    )


# the benchmark query: high-cardinality group-by + multiple aggregates
TRIP_AGG_QUERY = """
    select pickup_location_id,
           count(*) as trips,
           sum(total_amount) as revenue,
           avg(trip_distance) as avg_distance,
           avg(tip_amount) as avg_tip
    from trips
    group by pickup_location_id
    order by revenue desc
    limit 20
"""


def generate(out_dir: str, sf: float = 0.1, parts: int = 1, seed: int = 20260728,
             n_zones: int = N_ZONES) -> None:
    table = gen_trips(sf, seed, n_zones)
    d = os.path.join(out_dir, "trips")
    os.makedirs(d, exist_ok=True)
    n = table.num_rows
    step = (n + parts - 1) // parts
    for p in range(parts):
        pq.write_table(table.slice(p * step, step), os.path.join(d, f"part-{p:03d}.parquet"))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.1)
    ap.add_argument("--out", required=True)
    ap.add_argument("--parts", type=int, default=1)
    a = ap.parse_args()
    generate(a.out, a.sf, a.parts)
    print(f"taxi sf={a.sf} written to {a.out}")

"""Benchmark: TPC-H through the engine, TPU backend vs host Arrow backend
on the same machine.

Prints ONE JSON line:
  {"metric": ..., "value": rows/s on the device backend,
   "unit": "rows/s/chip", "vs_baseline": speedup over the host backend,
   "configs": [per-query rows for q1/q3/q5/q6/q10 at SF=1, q1/q3/q5/q6 at
               SF=10, the two taxi shapes, and q1/q3/q5/q6 at SF=100 when
               the dataset is on disk — each {"name", "sf", "tpu_ms",
               "cpu_ms", "speedup"} plus optional "ingest"/"readback"
               accounting, "join_paths" (device / step_aside /
               host_fallback counts with decline reasons), and "recovery"
               (retry / lineage-recompute / rpc-retry / chaos-injection
               event totals — nonzero under ballista.chaos.* or real
               faults), and "routing" (adaptive-execution decisions:
               engine choice counts, predicted vs observed seconds,
               mispredict rate, partial-offload splits, skew re-plans —
               ops/costmodel.py), and "speculation" (ISSUE 11 duplicate-
               attempt events: launched/won/lost/wasted_seconds plus the
               per-tenant SLO outcomes — zero on fault-free runs with the
               default thresholds)]}

Reference baseline context: the reference publishes no numbers
(BASELINE.md); the denominator here is this repo's own host Arrow path —
the same role the reference's Rust CPU executor plays in BASELINE.json's
target ("N x the CPU executor's rows/sec").

The headline metric matches `rust/benchmarks/tpch/src/main.rs:117-183`
(timed iterations against a persistent context); per-config rows cover
BASELINE.md configs 1-4.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

SF = float(os.environ.get("BENCH_SF", "1"))
QUERIES_DIR = REPO / "benchmarks" / "tpch" / "queries"
BATCH = "16777216"
# per-config rows reported in the JSON (BASELINE.md configs 1-3 + q5 from
# the headline q1/q3/q5 latency target + the high-cardinality
# aggregate-over-join shape); SF=10 and SF=100 cover the "beyond SF=1"
# requirement with the cached oracle-verified datasets.
CONFIGS = [(1.0, "q1"), (1.0, "q6"), (1.0, "q3"), (1.0, "q5"), (1.0, "q10"),
           (1.0, "q7"), (1.0, "q12"),
           (10.0, "q1"), (10.0, "q6"), (10.0, "q3"), (10.0, "q5"),
           (10.0, "q7"), (10.0, "q12"),
           (100.0, "q1"), (100.0, "q6"), (100.0, "q3"), (100.0, "q5"),
           (100.0, "q12")]
# SF>=this only runs when the dataset is already on disk: generating SF=100
# (~16GB parquet, hours on one core) must never eat the capture window
_NO_GEN_ABOVE_SF = float(os.environ.get("BENCH_NO_GEN_ABOVE_SF", "10"))
if os.environ.get("BENCH_CONFIGS"):  # e.g. "1.0:q1,10.0:q3"; "" keeps default
    CONFIGS = []
    for entry in os.environ["BENCH_CONFIGS"].split(","):
        if not entry.strip():
            continue
        sf_s, sep, q = entry.partition(":")
        if not sep or not q:
            raise SystemExit(f"BENCH_CONFIGS entry {entry!r}: expected 'sf:query'")
        CONFIGS.append((float(sf_s), q.strip()))
# soft deadline: stop adding per-config rows once elapsed wall time passes
# this, so the final JSON line always prints even on a degraded relay
MAX_SECONDS = float(os.environ.get("BENCH_MAX_SECONDS", "2400"))
_T_START = time.monotonic()


def data_dir(sf: float) -> pathlib.Path:
    return REPO / ".bench_cache" / f"tpch_sf{sf}"


def ensure_data(sf: float) -> None:
    from benchmarks.tpch.datagen import generate, is_complete

    if is_complete(str(data_dir(sf))):
        return
    data_dir(sf).parent.mkdir(exist_ok=True)
    generate(str(data_dir(sf)), sf=sf, parts=1)


_CTX = {}


def _context(backend: str, sf: float):
    """One session per (backend, SF) — TPC-style steady state: the context
    (catalog, caches, compiled artifacts) persists across queries."""
    key = (backend, sf)
    if key not in _CTX:
        from ballista_tpu.config import BallistaConfig
        from ballista_tpu.engine import ExecutionContext
        from benchmarks.tpch.datagen import register_all

        ctx = ExecutionContext(
            BallistaConfig(
                {
                    "ballista.executor.backend": backend,
                    "ballista.batch.size": BATCH,
                }
            )
        )
        register_all(ctx, str(data_dir(sf)))
        _CTX[key] = ctx
    return _CTX[key]


def run_once(backend: str, sql: str, sf: float = SF) -> float:
    ctx = _context(backend, sf)
    t0 = time.perf_counter()
    out = ctx.sql(sql).collect()
    dt = time.perf_counter() - t0
    assert out.num_rows >= 1
    return dt


def _probe_device_once(timeout_s: int) -> dict | None:
    """Returns None when the device backend answered, else a structured
    failure record: {"reason": "timeout"|"error", "timeout_s": <budget>,
    "detail": <stderr tail>} — a jax.devices() hang and a crashed probe are
    different operational problems and the BENCH JSON must say which."""
    import subprocess

    code = "import jax; print(jax.devices())"
    try:
        subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s, check=True,
            capture_output=True,
        )
        return None
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as e:
        tail = (e.stderr or b"").decode(errors="replace").strip().splitlines()[-3:]
        return {
            "reason": "timeout" if isinstance(e, subprocess.TimeoutExpired)
            else "error",
            "timeout_s": timeout_s,
            "detail": " | ".join(t.strip() for t in tail if t.strip())[:500],
        }


def _probe_device() -> None:
    """Wait for the TPU relay within a bounded budget before giving up.

    A transient relay outage at capture time must not void a round's
    evidence: retry the probe for BENCH_PROBE_BUDGET seconds (default 1200)
    before falling back.  jax.devices() otherwise blocks forever and the
    whole bench run hangs silently.  On exhaustion, if any persisted session
    capture exists under benchmarks/results/, emit it as the JSON line with
    ``"stale": true`` plus the capture timestamp and the probe-failure tail
    (exit 0) — the driver record must never be null while a capture exists.
    Only when there is no capture at all does the run exit 3.
    """
    budget = float(os.environ.get("BENCH_PROBE_BUDGET", "1200"))
    deadline = time.monotonic() + budget
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        err = _probe_device_once(timeout_s=int(min(120, max(30, remaining))))
        if err is None:
            if attempt > 1:
                print(f"device probe succeeded on attempt {attempt}",
                      file=sys.stderr)
            return
        if time.monotonic() >= deadline:
            print(
                f"device backend unreachable after {attempt} probes over "
                f"{budget:.0f}s ({err['reason']}: {err['detail']}); falling "
                f"back to persisted capture",
                file=sys.stderr,
            )
            _emit_stale_capture(probe={**err, "attempts": attempt,
                                       "budget_s": budget})
            raise SystemExit(3)  # only reached when no capture exists
        print(f"device probe {attempt} failed; retrying "
              f"({remaining:.0f}s left in budget)", file=sys.stderr)
        time.sleep(min(30, max(5, remaining / 10)))


RESULTS_DIR = REPO / "benchmarks" / "results"


def _latest_session_capture() -> tuple[pathlib.Path, dict] | None:
    """Most recent parseable session_*.json under benchmarks/results/."""
    best = None
    for p in sorted(RESULTS_DIR.glob("session_*.json"),
                    key=lambda p: p.stat().st_mtime, reverse=True):
        try:
            d = json.loads(p.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if not (isinstance(d, dict) and "metric" in d and "value" in d):
            continue
        # a CPU-jax capture (dev runs with JAX_PLATFORMS=cpu) must never
        # stand in for device evidence; legacy captures carry no platform
        # key and are device runs
        if d.get("platform") == "cpu":
            continue
        best = (p, d)
        break
    return best


def _emit_stale_capture(probe: dict) -> None:
    """Degrade to the last persisted capture instead of a null record.

    Matches the reference harness's contract that a bench invocation always
    yields a record (`rust/benchmarks/tpch/src/main.rs:117-183`); the
    ``stale`` marker plus the structured ``probe`` record (reason/timeout_s/
    detail/attempts/budget_s) keep provenance honest and machine-readable —
    a raw exception string forced every consumer to regex out WHY the
    capture went stale.
    """
    found = _latest_session_capture()
    if found is None:
        return
    path, d = found
    out = {
        "metric": d["metric"],
        "value": d["value"],
        "unit": d.get("unit", "rows/s/chip"),
        "vs_baseline": d.get("vs_baseline"),
        "configs": d.get("configs", []),
        "stale": True,
        "captured_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(path.stat().st_mtime)),
        "capture_file": str(path.relative_to(REPO)) if path.is_relative_to(REPO)
        else str(path),
        "probe": probe,
    }
    print(json.dumps(out))
    raise SystemExit(0)


def _persist_capture(result: dict) -> None:
    """Auto-persist every successful run so a later relay outage can fall
    back to it; failure to persist must never fail the run."""
    try:
        RESULTS_DIR.mkdir(parents=True, exist_ok=True)
        ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        payload = dict(result)
        payload["provenance"] = (
            f"auto-persisted by bench.py at {ts} (relay live); "
            "fallback source if the relay is down at a later round close")
        (RESULTS_DIR / f"session_auto_{ts}.json").write_text(
            json.dumps(payload, indent=1) + "\n")
    except OSError as e:
        print(f"[persist] failed: {e}", file=sys.stderr)


def _per_query(rb: dict | None, iters: int) -> dict | None:
    """Normalize a timed-loop readback snapshot to per-query numbers (every
    iteration does identical work, so the totals divide evenly). When they
    ever don't (a mid-loop decline or cache eviction changed the work),
    report the RAW totals flagged per_query=false so a consumer comparing
    readback_rows against `limit` can tell the difference."""
    if rb is None:
        return rb
    if iters > 1 and any(v % iters for v in rb.values()):
        return {**rb, "per_query": False}
    return {**{k: v // max(iters, 1) for k, v in rb.items()},
            "per_query": True}


def _readback_snapshot() -> dict | None:
    """Drain the result-readback accumulator (ops/runtime.py): rows/bytes
    transferred device->host for aggregate results since the last drain.
    The fused Sort+Limit epilogue shrinks these to O(limit); the pre-fusion
    full-column readback reports every group. None when no device readback
    ran (declined or host backend)."""
    try:
        from ballista_tpu.ops.runtime import readback_stats

        s = readback_stats(reset=True)
    except Exception:
        return None
    if not s.get("readbacks"):
        return None
    return {
        "readbacks": s["readbacks"],
        "readback_rows": s["rows"],
        "readback_bytes": s["bytes"],
    }


def _join_snapshot(iters: int = 1) -> dict | None:
    """Drain the join-path accumulator (ops/runtime.py): how many joins ran
    on the device path vs stepped aside at the multiplicity/gather admission
    tiers vs fell back to the host join, with decline reasons, since the
    last drain. Counts normalize to per-query numbers under the same
    contract as _per_query (raw totals flagged per_query=false when the
    timed loop was uneven). None when no join attempt touched the device
    path (joinless query, or the host backend)."""
    try:
        from ballista_tpu.ops.runtime import join_path_stats

        s = join_path_stats(reset=True)
    except Exception:
        return None
    if not s.get("paths"):
        return None
    # ONE normalization contract with the readback fields: flatten the
    # nested reasons map, run _per_query's divide-evenly-or-flag logic over
    # paths + reasons jointly, then unflatten
    prefix = "reasons\t"  # \t cannot occur in a path name
    flat = dict(s["paths"])
    for k, v in (s.get("reasons") or {}).items():
        flat[prefix + k] = v
    norm = _per_query(flat, iters)
    out = {
        k: v for k, v in norm.items()
        if not k.startswith(prefix) and k != "per_query"
    }
    reasons = {
        k[len(prefix):]: v for k, v in norm.items() if k.startswith(prefix)
    }
    if reasons:
        out["reasons"] = reasons
    out["per_query"] = norm["per_query"]
    return out


def _recovery_snapshot() -> dict | None:
    """Drain the failure-recovery accumulator (ops/runtime.py): task
    retries, lineage recomputes (fetch_failed/map_recomputed), lost-task
    resets, transient-RPC retries, chaos injections, and the ISSUE 6
    scheduler-restart events (scheduler_restart, restart_job_resumed,
    restart_assignment_restored, restart_readopted, torn_job_discarded,
    plan_retry, result_partition_restarted, completed_job_restarted)
    since the last drain. Raw event TOTALS, never per-query — recovery
    work is driven by faults, not by the query loop shape. None on a
    fault-free run (the common case: every counter zero)."""
    try:
        from ballista_tpu.ops.runtime import recovery_stats

        s = recovery_stats(reset=True)
    except Exception:
        return None
    s = {k: v for k, v in s.items() if v}
    return s or None


def _routing_snapshot() -> dict | None:
    """Drain the adaptive-routing accumulator (ops/runtime.py): every
    engine decision the cost-model-aware ladder made (device / host /
    split), predicted-vs-observed seconds over the decisions that carried
    a prediction, the derived mispredict rate, and the named re-planning
    events (partial-offload splits, skew re-plans, build-side swaps,
    re-tiers, cost-store health). Raw decision TOTALS like the recovery
    block — routing is driven by shapes and store warmth, not the query
    loop. None when no routing decision was made (host backend)."""
    try:
        from ballista_tpu.ops.runtime import routing_stats

        s = routing_stats(reset=True)
    except Exception:
        return None
    if not s["engines"] and not s["events"]:
        return None
    events = s["events"]
    return {
        "engines": s["engines"],
        "predictions": s["predictions"],
        "mispredicts": s["mispredicts"],
        "mispredict_rate": round(s["mispredict_rate"], 4),
        "predicted_s": round(s["predicted_s"], 4),
        "observed_s": round(s["observed_s"], 4),
        "splits": events.get("split", 0),
        "skew_replans": events.get("skew_replan", 0),
        "events": events,
    }


def _speculation_snapshot() -> dict | None:
    """Drain the speculative-execution accumulator (ops/runtime.py):
    duplicate-attempt launches and their outcomes (won/lost/failed/
    promoted/orphaned), the duplicated compute discarded when a pair
    resolves (wasted_seconds), and per-tenant SLO outcomes (slo_misses /
    slo_met) since the last drain. Raw event TOTALS like the recovery
    block — speculation is driven by stragglers, not the query loop. None
    on a fault-free run (the acceptance default: every counter zero)."""
    try:
        from ballista_tpu.ops.runtime import speculation_stats

        s = speculation_stats(reset=True)
    except Exception:
        return None
    s = {
        k: (round(v, 4) if k == "wasted_seconds" else int(v))
        for k, v in s.items() if v
    }
    return s or None


def _ingest_snapshot() -> dict | None:
    """Drain the ingest-timing accumulator (ops/runtime.py): scan/encode/
    upload seconds and the overlap fraction of the stage prepares since the
    last drain. None when no fresh prepare ran (fully cached)."""
    try:
        from ballista_tpu.ops.runtime import ingest_stats

        s = ingest_stats(reset=True)
    except Exception:
        return None
    if not s.get("prepares"):
        return None
    return {
        "prepares": s["prepares"],
        "scan_s": round(s["scan_s"], 3),
        "encode_s": round(s["encode_s"], 3),
        "upload_s": round(s["upload_s"], 3),
        "wall_s": round(s["wall_s"], 3),
        "overlap_frac": round(s["overlap_frac"], 3),
    }


def bench_config(sf: float, name: str, iters: int = 3) -> dict | None:
    try:
        sql = (QUERIES_DIR / f"{name}.sql").read_text()
        from benchmarks.tpch.datagen import is_complete

        if sf > _NO_GEN_ABOVE_SF and not is_complete(str(data_dir(sf))):
            print(f"[config] {name} sf={sf}: skipped (dataset absent or "
                  f"incomplete; run benchmarks.tpch.datagen --sf {sf} first)",
                  file=sys.stderr)
            return None
        ensure_data(sf)
        _ingest_snapshot()  # drain: attribute prepares to THIS config
        run_once("tpu", sql, sf)  # warmup: compile + caches
        ingest = _ingest_snapshot()  # fresh prepares happen at warmup
        _readback_snapshot()  # drain: attribute readbacks to the timed runs
        _join_snapshot()  # drain: attribute join paths to the timed runs
        _recovery_snapshot()  # drain: attribute recovery events likewise
        _routing_snapshot()  # drain: attribute routing decisions likewise
        _speculation_snapshot()  # drain: attribute speculation likewise
        t = min(run_once("tpu", sql, sf) for _ in range(iters))
        readback = _per_query(_readback_snapshot(), iters)
        join_paths = _join_snapshot(iters)
        recovery = _recovery_snapshot()
        routing = _routing_snapshot()
        speculation = _speculation_snapshot()
        run_once("cpu", sql, sf)
        c = min(run_once("cpu", sql, sf) for _ in range(iters))
    except Exception as e:
        print(f"[config] {name} sf={sf}: failed: {e}", file=sys.stderr)
        return None
    row = {
        "name": name,
        "sf": sf,
        "tpu_ms": round(t * 1000, 1),
        "cpu_ms": round(c * 1000, 1),
        "speedup": round(c / t, 2),
    }
    if ingest is not None:
        row["ingest"] = ingest
        print(f"[ingest] {name} sf={sf}: scan={ingest['scan_s']}s "
              f"encode={ingest['encode_s']}s upload={ingest['upload_s']}s "
              f"wall={ingest['wall_s']}s overlap={ingest['overlap_frac']}",
              file=sys.stderr)
    if readback is not None:
        row["readback"] = readback
        unit = "per query" if readback.get("per_query") else "TOTALS (uneven loop)"
        print(f"[readback] {name} sf={sf}: rows={readback['readback_rows']} "
              f"bytes={readback['readback_bytes']} "
              f"transfers={readback['readbacks']} ({unit})",
              file=sys.stderr)
    if join_paths is not None:
        row["join_paths"] = join_paths
        counts = {k: v for k, v in join_paths.items()
                  if k not in ("reasons", "per_query")}
        unit = ("per query" if join_paths.get("per_query")
                else "TOTALS (uneven loop)")
        print(f"[join] {name} sf={sf}: {counts} "
              f"reasons={join_paths.get('reasons', {})} ({unit})",
              file=sys.stderr)
    if recovery is not None:
        row["recovery"] = recovery
        print(f"[recovery] {name} sf={sf}: {recovery} (event totals)",
              file=sys.stderr)
    if routing is not None:
        row["routing"] = routing
        print(f"[routing] {name} sf={sf}: engines={routing['engines']} "
              f"mispredict_rate={routing['mispredict_rate']} "
              f"splits={routing['splits']} "
              f"skew_replans={routing['skew_replans']} (decision totals)",
              file=sys.stderr)
    if speculation is not None:
        row["speculation"] = speculation
        print(f"[speculation] {name} sf={sf}: {speculation} (event totals)",
              file=sys.stderr)
    print(f"[config] {name} sf={sf}: tpu={row['tpu_ms']}ms "
          f"cpu={row['cpu_ms']}ms speedup={row['speedup']}x", file=sys.stderr)
    return row


def _taxi_rows() -> list[dict]:
    """NYC-taxi-shaped aggregation (BASELINE.md config 4), both zone
    cardinalities."""
    out = []
    try:
        from benchmarks.taxi.datagen import TRIP_AGG_QUERY, generate as taxi_gen
    except Exception as e:
        print(f"[config] taxi: unavailable: {e}", file=sys.stderr)
        return out
    for label, subdir, zones in (
        ("taxi_10M_265groups", "taxi_sf1", None),
        ("taxi_10M_10kgroups", "taxi_hc_sf1", 10_000),
    ):
        try:
            ensure_data(1.0)  # _context(_, 1.0) registers the SF=1 catalog
            d = REPO / ".bench_cache" / subdir
            if not (d / "trips").exists():
                kw = {"n_zones": zones} if zones else {}
                taxi_gen(str(d), sf=1.0, parts=1, **kw)
            table = "trips" if zones is None else "trips_hc"
            sql = TRIP_AGG_QUERY.replace("from trips", f"from {table}")
            for backend in ("tpu", "cpu"):
                ctx = _context(backend, 1.0)
                if table not in ctx.tables:
                    ctx.register_parquet(table, str(d / "trips"))
            run_once("tpu", sql, 1.0)
            _readback_snapshot()  # drain: attribute to the timed runs
            t = min(run_once("tpu", sql, 1.0) for _ in range(2))
            readback = _per_query(_readback_snapshot(), 2)
            run_once("cpu", sql, 1.0)
            c = min(run_once("cpu", sql, 1.0) for _ in range(2))
            row = {"name": label, "sf": 1.0, "tpu_ms": round(t * 1000, 1),
                   "cpu_ms": round(c * 1000, 1), "speedup": round(c / t, 2)}
            if readback is not None:
                row["readback"] = readback
                unit = ("per query" if readback.get("per_query")
                        else "TOTALS (uneven loop)")
                print(f"[readback] {label}: rows={readback['readback_rows']} "
                      f"bytes={readback['readback_bytes']} "
                      f"transfers={readback['readbacks']} ({unit})",
                      file=sys.stderr)
            print(f"[config] {label}: tpu={row['tpu_ms']}ms "
                  f"cpu={row['cpu_ms']}ms speedup={row['speedup']}x",
                  file=sys.stderr)
            out.append(row)
        except Exception as e:
            print(f"[config] {label}: failed: {e}", file=sys.stderr)
    return out


def _multitenant_scenario() -> dict | None:
    """Multi-tenant serving scenario (ISSUE 7): N concurrent tenant clients
    replay a Zipf-repeated dashboard query mix against ONE standalone
    cluster (real scheduler gRPC + executors + Flight), reporting p50/p99
    client latency split by cache hit/miss, the result-cache hit rate, and
    the per-tenant task-share fairness ratio. Control-plane numbers: the
    host backend serves the kernels, so this runs (and means the same
    thing) with or without a reachable device."""
    import threading

    import numpy as np

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import tenancy_stats
    from benchmarks.tpch.datagen import generate, is_complete

    n_tenants = int(os.environ.get("BENCH_MT_TENANTS", "4"))
    replays = int(os.environ.get("BENCH_MT_REPLAYS", "24"))
    d = REPO / ".bench_cache" / "tpch_mt001"
    if not is_complete(str(d)):
        d.parent.mkdir(exist_ok=True)
        generate(str(d), sf=0.01, parts=2)
    # the dashboard mix: two real TPC-H shapes + two point-ish aggregates
    queries = [
        (QUERIES_DIR / "q1.sql").read_text(),
        (QUERIES_DIR / "q6.sql").read_text(),
        "select l_returnflag, count(*) as n from lineitem group by "
        "l_returnflag order by l_returnflag",
        "select max(l_extendedprice) as m, min(l_shipdate) as d from lineitem",
    ]
    cluster = StandaloneCluster(
        n_executors=2,
        config=BallistaConfig({"ballista.tenant.max_inflight": "8"}),
    )
    try:
        tenancy_stats(reset=True)
        rng = np.random.default_rng(7)
        schedules = [
            [int(z - 1) % len(queries) for z in rng.zipf(1.5, size=replays)]
            for _ in range(n_tenants)
        ]
        lat: list[tuple[int, float]] = []  # (query index, seconds)
        lat_lock = threading.Lock()
        errors: list = []

        def replay(i: int) -> None:
            try:
                from benchmarks.tpch.datagen import register_all

                ctx = BallistaContext(
                    *cluster.scheduler_addr,
                    settings={"ballista.tenant.name": f"tenant{i}"},
                )
                register_all(ctx, str(d))
                for qi in schedules[i]:
                    t0 = time.perf_counter()
                    out = ctx.sql(queries[qi]).collect()
                    dt = time.perf_counter() - t0
                    assert out.num_rows >= 1
                    with lat_lock:
                        lat.append((qi, dt))
                ctx.close()
            except Exception as e:
                errors.append(f"tenant{i}: {e}")

        threads = [
            threading.Thread(target=replay, args=(i,))
            for i in range(n_tenants)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        wall = time.perf_counter() - t0
        for i, t in enumerate(threads):
            if t.is_alive():
                # a hung tenant is a scenario failure: shutting the cluster
                # down under live submitters (and dividing into an empty
                # latency list) must not masquerade as a result
                errors.append(f"tenant{i}: still running after 600s")
        if errors or not lat:
            print(f"[multitenant] errors: {errors or ['no latencies']}",
                  file=sys.stderr)
            return None
        stats = tenancy_stats(reset=True)
        shares = cluster.scheduler_impl.state.tenant_task_shares()
        secs = sorted(s for _qi, s in lat)
        hits = stats.get("cache_hit", 0)
        # every non-hit lookup outcome counts in the denominator, incl.
        # found-but-invalidated entries (dead executor) and unkeyable plans
        misses = (stats.get("cache_miss", 0) + stats.get("cache_unkeyable", 0)
                  + stats.get("cache_invalidated", 0))
        row = {
            "tenants": n_tenants,
            "queries": len(lat),
            "wall_s": round(wall, 3),
            "qps": round(len(lat) / wall, 1),
            "p50_ms": round(1000 * secs[len(secs) // 2], 1),
            "p99_ms": round(1000 * secs[min(len(secs) - 1,
                                            int(len(secs) * 0.99))], 1),
            "cache_hit_rate": round(hits / max(1, hits + misses), 3),
            "plan_cache_hits": stats.get("plan_cache_hit", 0),
            "task_share": shares,
            # fairness: min/max assigned-task share across tenants that got
            # any (1.0 = perfectly even); cache hits run zero tasks, so
            # this measures the EXECUTED remainder
            "fairness_ratio": round(
                min(shares.values()) / max(shares.values()), 3
            ) if shares else None,
        }
        print(f"[multitenant] {row}", file=sys.stderr)
        return row
    finally:
        cluster.shutdown()


# -- multi-process closed-loop client driver (ISSUE 11 satellite) ------------
# the thread driver saturates CPU images at ~2 workers (client-side Arrow +
# Flight decode competes with the in-process executors for the GIL and the
# cores), making high-concurrency p99 numbers client-bound. Workers here are
# real processes talking to the parent's cluster over gRPC/Flight; each
# times its own loop, so spawn/import overhead never lands in a latency
# sample. Module-level on purpose: spawned children pickle these by
# reference.


def _timed_stream_query(ctx, sql: str):
    """(total_s, ttfb_s) for one streamed query; None on no rows."""
    plan = ctx.sql(sql).logical_plan()
    t0 = time.perf_counter()
    ttfb = None
    rows = 0
    for b in ctx.collect_stream(plan, timeout=120):
        if ttfb is None:
            ttfb = time.perf_counter() - t0
        rows += b.num_rows
    total = time.perf_counter() - t0
    return (total, ttfb if ttfb is not None else total) if rows else None


def _client_proc(host, port, data, settings, qlist, idx, duration, out_q,
                 digest) -> None:
    """One closed-loop client process. With digest=True results are
    buffered-collected and content-hashed so the parent can assert
    bit-identity across the process boundary without shipping tables."""
    try:
        import hashlib

        from ballista_tpu.client import BallistaContext
        from benchmarks.tpch.datagen import register_all

        ctx = BallistaContext(host, port, settings=settings)
        register_all(ctx, data)
        lats, ttfbs, digests = [], [], set()
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < duration:
            sql = qlist[(idx + n) % len(qlist)]
            n += 1
            if digest:
                q0 = time.perf_counter()
                tbl = ctx.sql(sql).collect()
                dt = time.perf_counter() - q0
                if tbl.num_rows == 0:
                    out_q.put(("error", idx, "empty result"))
                    return
                lats.append(dt)
                ttfbs.append(dt)
                digests.add(
                    hashlib.sha256(repr(tbl.to_pydict()).encode()).hexdigest()
                )
            else:
                r = _timed_stream_query(ctx, sql)
                if r is None:
                    out_q.put(("error", idx, "empty result"))
                    return
                lats.append(r[0])
                ttfbs.append(r[1])
        wall = time.perf_counter() - t0
        ctx.close()
        out_q.put(("ok", idx, lats, ttfbs, wall, sorted(digests)))
    except Exception as e:
        out_q.put(("error", idx, repr(e)))


def _drive_clients(host, port, data, settings, qlist, clients, duration,
                   digest=False):
    """Run `clients` closed-loop client processes against the scheduler at
    (host, port); returns (lats, ttfbs, qps, digests) or raises
    RuntimeError naming the failures. qps sums each worker's own
    samples/wall (workers start staggered by spawn cost; a shared parent
    clock would undercount)."""
    import multiprocessing as mp

    mpctx = mp.get_context("spawn")  # never fork a process running grpc/jax
    out_q = mpctx.Queue()
    procs = [
        mpctx.Process(
            target=_client_proc,
            args=(host, port, data, settings, qlist, i, duration, out_q,
                  digest),
            daemon=True,
        )
        for i in range(clients)
    ]
    for p in procs:
        p.start()
    lats, ttfbs, qps, digests, errors = [], [], 0.0, set(), []
    got = 0
    deadline = time.monotonic() + duration + 240
    while got < clients and time.monotonic() < deadline:
        try:
            msg = out_q.get(timeout=max(0.1, deadline - time.monotonic()))
        except Exception:
            break
        got += 1
        if msg[0] == "error":
            errors.append(f"client{msg[1]}: {msg[2]}")
            continue
        _tag, _idx, ls, ts, wall, ds = msg
        lats.extend(ls)
        ttfbs.extend(ts)
        qps += len(ls) / max(wall, 1e-9)
        digests.update(ds)
    for p in procs:
        p.join(10)
        if p.is_alive():
            errors.append("client process still running; terminated")
            p.terminate()
    if got < clients and not errors:
        errors.append(f"only {got}/{clients} clients reported")
    if errors or not lats:
        raise RuntimeError(str(errors or ["no samples"]))
    return lats, ttfbs, qps, digests


def _latency_scenario() -> dict | None:
    """Low-latency serving-tier scenario (ISSUE 8): closed-loop QPS sweep
    of SF=0.01-0.1 point-lookup/filter queries against ONE standalone
    cluster with push dispatch, the persistent AOT program cache (prewarm
    on), and streaming result collect. Reports per-concurrency p50/p95/p99
    latency, time-to-first-batch, and the serving counters that prove the
    fast path engaged: push-vs-poll dispatch counts and the compile-hit
    rate (a warm tier answers with ZERO fresh traces). The result cache is
    disabled on purpose — this scenario measures the EXECUTION path, not
    cache short-circuits (the multitenant scenario covers those).

    Knobs: BENCH_LAT_SF (default 0.01), BENCH_LAT_DURATION seconds per
    concurrency level (default 10; the CI smoke uses 2), BENCH_LAT_CLIENTS
    (default "1,4"), BENCH_LAT_BACKEND (default tpu — the compile counters
    only mean something where stage programs compile; runs under
    JAX_PLATFORMS=cpu too), BENCH_LAT_DRIVER ("process" default — each
    client is its own OS process so the load generator is never
    client-bound; "thread" keeps the pre-ISSUE-11 in-process driver)."""
    import threading

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import serving_stats
    from benchmarks.tpch.datagen import generate, is_complete, register_all

    sf = float(os.environ.get("BENCH_LAT_SF", "0.01"))
    duration = float(os.environ.get("BENCH_LAT_DURATION", "10"))
    levels = [
        int(c) for c in os.environ.get("BENCH_LAT_CLIENTS", "1,4").split(",")
        if c.strip()
    ]
    backend = os.environ.get("BENCH_LAT_BACKEND", "tpu")
    d = REPO / ".bench_cache" / f"tpch_lat{sf}"
    if not is_complete(str(d)):
        d.parent.mkdir(exist_ok=True)
        generate(str(d), sf=sf, parts=2)
    queries = {
        "point": (
            "select count(*) as n, sum(l_extendedprice) as s from lineitem "
            "where l_orderkey = 1"
        ),
        "filter": (
            "select sum(l_extendedprice) as revenue, count(*) as n "
            "from lineitem where l_shipdate >= date '1994-01-01' and "
            "l_shipdate < date '1995-01-01' and l_quantity < 24"
        ),
        "group": (
            "select l_returnflag, count(*) as n from lineitem "
            "group by l_returnflag order by l_returnflag"
        ),
    }
    cluster = StandaloneCluster(
        n_executors=2,
        config=BallistaConfig({
            "ballista.executor.backend": backend,
            "ballista.tpu.aot_cache": str(REPO / ".bench_cache" / "aot_lat"),
            "ballista.tpu.prewarm": "true",
            "ballista.tpu.layout_cache_dir":
                str(REPO / ".bench_cache" / "layouts_lat"),
            "ballista.cache.results": "false",
        }),
    )
    client_settings = {
        "ballista.executor.backend": backend,
        "ballista.cache.results": "false",
        "ballista.client.stream_results": "true",
        # serving-tier plan shape: a 16-way shuffle is pure overhead for
        # point queries (16 final-stage tasks per query, each with its own
        # dispatch + status + fetch)
        "ballista.shuffle.partitions": "2",
    }
    driver = os.environ.get("BENCH_LAT_DRIVER", "process")
    try:
        def mk_ctx() -> BallistaContext:
            ctx = BallistaContext(
                *cluster.scheduler_addr, settings=client_settings
            )
            register_all(ctx, str(d))
            return ctx

        warm_ctx = mk_ctx()
        for sql in queries.values():  # warmup: trace/compile + caches
            _timed_stream_query(warm_ctx, sql)
        warm_ctx.close()
        warm = serving_stats(reset=True)  # drain: attribute to timed sweep

        sweep = []
        qlist = list(queries.values())
        host, port = cluster.scheduler_addr
        for clients in levels:
            lat: list = []
            ttfbs: list = []
            errors: list = []
            qps = 0.0
            if driver == "process":
                try:
                    lat, ttfbs, qps, _digests = _drive_clients(
                        host, port, str(d), client_settings, qlist,
                        clients, duration,
                    )
                except RuntimeError as e:
                    print(f"[latency] clients={clients}: {e}", file=sys.stderr)
                    return None
            else:
                lock = threading.Lock()

                def worker(i: int) -> None:
                    try:
                        ctx = mk_ctx()
                        n = 0
                        while time.perf_counter() - t0 < duration:
                            r = _timed_stream_query(
                                ctx, qlist[(i + n) % len(qlist)]
                            )
                            n += 1
                            if r is None:
                                errors.append(f"client{i}: empty result")
                                return
                            with lock:
                                lat.append(r[0])
                                ttfbs.append(r[1])
                        ctx.close()
                    except Exception as e:
                        errors.append(f"client{i}: {e}")

                threads = [
                    threading.Thread(target=worker, args=(i,))
                    for i in range(clients)
                ]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(duration + 240)
                wall = time.perf_counter() - t0
                qps = len(lat) / max(wall, 1e-9)
                if errors or not lat:
                    print(f"[latency] clients={clients}: "
                          f"{errors or ['no samples']}", file=sys.stderr)
                    return None
            lat.sort()
            ttfbs.sort()

            def pct(xs, q):
                return round(1000 * xs[min(len(xs) - 1, int(len(xs) * q))], 1)

            row = {
                "clients": clients,
                "queries": len(lat),
                "qps": round(qps, 1),
                "p50_ms": pct(lat, 0.50),
                "p95_ms": pct(lat, 0.95),
                "p99_ms": pct(lat, 0.99),
                "ttfb_p50_ms": pct(ttfbs, 0.50),
            }
            print(f"[latency] {row}", file=sys.stderr)
            sweep.append(row)

        s = serving_stats(reset=True)
        hits = (s.get("compile_hit_memory", 0) + s.get("compile_hit_disk", 0)
                + s.get("compile_prewarmed", 0))
        traces = s.get("compile_trace", 0)
        result = {
            "sf": sf,
            "duration_s": duration,
            "driver": driver,
            "sweep": sweep,
            "dispatch_push": s.get("dispatch_push", 0),
            "dispatch_poll": s.get("dispatch_poll", 0),
            "compile_trace": traces,
            "compile_hits": hits,
            "compile_hit_rate": round(hits / max(1, hits + traces), 3),
            "stream_partitions_early": s.get("stream_partition_early", 0),
            "warmup": {k: v for k, v in warm.items() if v},
        }
        print(f"[latency] serving counters: {result['dispatch_push']} push / "
              f"{result['dispatch_poll']} poll dispatches, compile hit rate "
              f"{result['compile_hit_rate']}", file=sys.stderr)
        return result
    finally:
        cluster.shutdown()


def _speculation_scenario() -> dict | None:
    """Straggler-tail scenario (ISSUE 11): p99-under-chaos with speculation
    ON vs OFF. One query shape replays closed-loop (multi-process clients)
    against a 2-executor cluster whose tasks inject a seeded `task.slow`
    straggler. Chaos verdicts are keyed on plan coordinates — never job
    ids — so the chosen seed makes the straggler recur every repetition
    (and makes the duplicate attempt, keyed on attempt 1, draw fast): with
    speculation OFF every hit query eats the full injected delay; ON, the
    duplicate rescues the tail and p99 must land strictly below OFF. Both
    modes must stay bit-identical to the fault-free baseline — the rescue
    changes when a query finishes, never what it returns. Also reports the
    per-tenant SLO outcomes (ballista.tenant.slo_ms armed at ~0.8x the
    injected delay) and asserts-by-counter that the fault-free warm pass
    launched nothing.

    Knobs: BENCH_SPEC_SF (default 0.01), BENCH_SPEC_DURATION seconds per
    mode (default 8; the CI smoke uses 4), BENCH_SPEC_CLIENTS (default 2),
    BENCH_SPEC_SLOW_MS (default 1200)."""
    import hashlib

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops import costmodel
    from ballista_tpu.ops.runtime import speculation_stats
    from ballista_tpu.utils.chaos import ChaosInjector
    from benchmarks.tpch.datagen import generate, is_complete, register_all

    sf = float(os.environ.get("BENCH_SPEC_SF", "0.01"))
    duration = float(os.environ.get("BENCH_SPEC_DURATION", "8"))
    clients = int(os.environ.get("BENCH_SPEC_CLIENTS", "2"))
    slow_ms = float(os.environ.get("BENCH_SPEC_SLOW_MS", "1200"))
    rate = 0.12
    d = REPO / ".bench_cache" / f"tpch_lat{sf}"  # share the latency dataset
    if not is_complete(str(d)):
        d.parent.mkdir(exist_ok=True)
        generate(str(d), sf=sf, parts=2)
    sql = ("select l_returnflag, count(*) as n, sum(l_extendedprice) as s "
           "from lineitem group by l_returnflag order by l_returnflag")
    # every config (cluster AND per-job) pins the in-memory cost store so
    # no configure() rebind drops the task.run rates between passes
    client_base = {
        "ballista.cache.results": "false",
        "ballista.shuffle.partitions": "2",
        "ballista.tpu.cost_model_dir": "",
        "ballista.tenant.name": "bench",
    }

    def run_mode(spec_on: bool, seed: int | None):
        cluster = StandaloneCluster(
            n_executors=2,
            config=BallistaConfig({
                "ballista.tpu.cost_model_dir": "",
                "ballista.speculation": "true" if spec_on else "false",
                "ballista.speculation.min_runtime_ms": "150",
                "ballista.speculation.multiplier": "3",
                "ballista.tenant.slo_ms":
                    f"bench:{max(200.0, slow_ms * 0.8):.0f}",
            }),
        )
        try:
            host, port = cluster.scheduler_addr
            speculation_stats(reset=True)
            ctx = BallistaContext(host, port, settings=client_base)
            register_all(ctx, str(d))
            # fault-free warm pass: compiles, caches, and the
            # job-independent task.run rates the straggler monitor
            # predicts from (the chaos run's jobs share the plan shape)
            baseline = None
            for _ in range(3):
                baseline = ctx.sql(sql).collect()
            ctx.close()
            base_digest = hashlib.sha256(
                repr(baseline.to_pydict()).encode()
            ).hexdigest()
            warm_stats = speculation_stats(reset=True)
            if seed is None:
                # pick the seed off the warm run's real task coordinates:
                # exactly one straggler per repetition, duplicate fast
                st = cluster.scheduler_impl.state
                coords = set()
                for k, _v in st.kv.get_prefix(st._key("tasks")):
                    tail = k.rsplit("/", 3)
                    coords.add((int(tail[2]), int(tail[3])))
                for cand in range(2000):
                    inj = ChaosInjector(cand, rate, sites=("task.slow",))
                    slow = [
                        c for c in sorted(coords)
                        if inj.should_inject("task.slow", f"{c[0]}/{c[1]}@a0")
                    ]
                    if len(slow) == 1 and not inj.should_inject(
                        "task.slow", f"{slow[0][0]}/{slow[0][1]}@a1"
                    ):
                        seed = cand
                        break
                if seed is None:
                    return None, None
            lats, _ttfbs, qps, digests = _drive_clients(
                host, port, str(d),
                {
                    **client_base,
                    "ballista.chaos.rate": str(rate),
                    "ballista.chaos.seed": str(seed),
                    "ballista.chaos.sites": "task.slow",
                    "ballista.chaos.slow_ms": str(slow_ms),
                },
                [sql], clients, duration, digest=True,
            )
            stats = speculation_stats(reset=True)
            lats.sort()

            def pct(q):
                return round(
                    1000 * lats[min(len(lats) - 1, int(len(lats) * q))], 1
                )

            return {
                "queries": len(lats),
                "qps": round(qps, 1),
                "p50_ms": pct(0.50),
                "p99_ms": pct(0.99),
                "bit_identical": digests == {base_digest},
                "warm_launched": int(warm_stats.get("launched", 0)),
                "speculation": {
                    k: (round(v, 4) if k == "wasted_seconds" else int(v))
                    for k, v in stats.items()
                },
            }, seed
        finally:
            cluster.shutdown()
            costmodel.reset()

    costmodel.reset()
    try:
        on, seed = run_mode(True, None)
        if on is None:
            print("[speculation] no qualifying chaos seed", file=sys.stderr)
            return None
        off, _ = run_mode(False, seed)
        if off is None:
            return None
    except RuntimeError as e:
        print(f"[speculation] client driver failed: {e}", file=sys.stderr)
        return None
    result = {
        "sf": sf,
        "duration_s": duration,
        "clients": clients,
        "slow_ms": slow_ms,
        "chaos_rate": rate,
        "chaos_seed": seed,
        "on": on,
        "off": off,
        "bit_identical": on["bit_identical"] and off["bit_identical"],
        "p99_speedup": round(off["p99_ms"] / max(on["p99_ms"], 1e-9), 2),
    }
    print(f"[speculation] ON p99={on['p99_ms']}ms OFF p99={off['p99_ms']}ms "
          f"({result['p99_speedup']}x) bit_identical="
          f"{result['bit_identical']} counters={on['speculation']}",
          file=sys.stderr)
    return result


def _sharedscan_scenario() -> dict | None:
    """Shared-scan serving scenario (ISSUE 13): N concurrent tenants each
    replay ONE DISTINCT aggregate query over the SAME table closed-loop
    against a standalone cluster — the workload where every solo execution
    pays its own scan/upload/launch and shared-scan batching collapses
    them to one per wave. Reports aggregate QPS per tenant level, the
    shared_scan counters (batches_formed / batched_stages / uploads_saved /
    launches_saved), and asserts-by-digest that every batched result is
    bit-identical to the never-batched (sequential, shared_scan=false)
    reference. The headline claim: aggregate QPS grows SUPERLINEARLY in
    tenant count at fixed hardware (qps@4 > 2x qps@1 on the CPU image).

    Knobs: BENCH_SS_SF (default 0.1), BENCH_SS_DURATION seconds per level
    (default 6; the CI smoke uses the same), BENCH_SS_TENANTS (default
    "1,2,4,8")."""
    import hashlib
    import threading

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import shared_scan_stats
    from benchmarks.tpch.datagen import generate, is_complete, register_all

    sf = float(os.environ.get("BENCH_SS_SF", "0.1"))
    duration = float(os.environ.get("BENCH_SS_DURATION", "6"))
    levels = [
        int(c) for c in os.environ.get("BENCH_SS_TENANTS", "1,2,4,8").split(",")
        if c.strip()
    ]
    d = REPO / ".bench_cache" / f"tpch_ss{sf}"
    if not is_complete(str(d)):
        d.parent.mkdir(exist_ok=True)
        generate(str(d), sf=sf, parts=1)
    # the dashboard mix: DISTINCT metrics/filters over the SAME breakdown
    # dimensions (the classic N-tiles-one-dataset dashboard) — numeric/date
    # device columns only (the string GROUP keys are host-side, and a
    # shared key set means the group ranking is computed once per wave),
    # and a common measure-column pool so the union read stays close to a
    # single member's read
    gby = ("group by l_returnflag, l_linestatus "
           "order by l_returnflag, l_linestatus")
    queries = [
        f"select l_returnflag, l_linestatus, sum(l_quantity) as s, "
        f"count(*) as n from lineitem {gby}",
        f"select l_returnflag, l_linestatus, sum(l_extendedprice) as s "
        f"from lineitem where l_quantity < 25 {gby}",
        f"select l_returnflag, l_linestatus, min(l_discount) as mn, "
        f"max(l_tax) as mx from lineitem {gby}",
        f"select l_returnflag, l_linestatus, count(*) as n from lineitem "
        f"where l_shipdate >= date '1994-01-01' {gby}",
        f"select l_returnflag, l_linestatus, "
        f"sum(l_extendedprice * (1 - l_discount)) as rev from lineitem {gby}",
        f"select l_returnflag, l_linestatus, min(l_shipdate) as d0, "
        f"max(l_shipdate) as d1 from lineitem {gby}",
        f"select l_returnflag, l_linestatus, avg(l_quantity) as aq "
        f"from lineitem where l_discount > 0.02 {gby}",
        f"select l_returnflag, l_linestatus, sum(l_quantity) as sq "
        f"from lineitem where l_tax < 0.05 {gby}",
    ]

    def settings(shared: bool) -> dict:
        return {
            "ballista.executor.backend": "tpu",
            "ballista.cache.results": "false",
            # few large row batches: per-batch dispatch overhead must not
            # drown the work (the headline bench runs 16M-row batches)
            "ballista.batch.size": "4194304",
            # serving-tier plan shape: per-query control-plane work (final-
            # stage tasks, statuses, fetches) must not drown the scan the
            # scenario is about
            "ballista.shuffle.partitions": "1",
            "ballista.shared_scan": "true" if shared else "false",
            # the scenario measures the SCAN-PER-QUERY regime (working sets
            # past HBM residency — the serving reality shared-scan exists
            # for): with residency on, a warm member rightly degrades to
            # its resident solo run and after one wave nothing would batch
            "ballista.tpu.device_cache": "false",
            # in-memory cost store (like the speculation scenario): the
            # evidence gate must judge THIS regime's solo-vs-batch rates,
            # not whatever a persisted store learned under residency
            "ballista.tpu.cost_model_dir": "",
            # the host decoded-table cache would likewise hide the scan
            # this scenario is about (real serving working sets exceed it)
            "ballista.scan.cache": "false",
            # the persisted layout tier is off for the same reason as the
            # scan cache: the scenario measures the streaming regime.
            # (Layout-warm members are shared-scan-ELIGIBLE since ISSUE 15
            # folded batch.size into the persist key — eligibility no
            # longer depends on this knob.)
            "ballista.tpu.layout_cache_dir": "",
        }

    def digest(tbl) -> str:
        return hashlib.sha256(repr(tbl.to_pydict()).encode()).hexdigest()

    # never-batched reference digests (sequential, shared off)
    reference = {}
    reference_tables = {}
    cluster = StandaloneCluster(
        n_executors=1,
        config=BallistaConfig({"ballista.shared_scan": "false"}),
    )
    try:
        ctx = BallistaContext(*cluster.scheduler_addr, settings=settings(False))
        register_all(ctx, str(d))
        for i, sql in enumerate(queries):
            tbl = ctx.sql(sql).collect()
            reference[i] = digest(tbl)
            reference_tables[i] = tbl.to_pydict()
        ctx.close()
    finally:
        cluster.shutdown()

    sweep = []
    bit_identical = True
    for tenants in levels:
        # FIXED saturated hardware is the claim's regime: one executor
        # slot (one chip's worth of serial stage capacity). Solo tenants
        # queue behind each other; shared-scan serves a whole queue wave
        # from one scan — that is where aggregate QPS grows superlinearly
        # in tenant count.
        cluster = StandaloneCluster(
            n_executors=1, concurrent_tasks=1,
            config=BallistaConfig({"ballista.tpu.cost_model_dir": ""}),
        )
        shared_scan_stats(reset=True)
        try:
            counts = [0] * tenants
            mismatches: list = []
            errors: list = []

            # untimed warm round: one concurrent pass with SYNCHRONOUS
            # combined-program compilation, so the timed loop measures
            # steady-state one-launch waves instead of compile warmup
            # (production deployments get this from the AOT disk tier)
            from ballista_tpu.ops import sharedscan

            def warm_round() -> None:
                def one(i: int) -> None:
                    try:
                        ctx = BallistaContext(
                            *cluster.scheduler_addr, settings=settings(True)
                        )
                        register_all(ctx, str(d))
                        ctx.sql(queries[i % len(queries)]).collect()
                        ctx.close()
                    except Exception as e:
                        errors.append(f"warm{i}: {e!r}")

                ws = [
                    threading.Thread(target=one, args=(i,))
                    for i in range(tenants)
                ]
                for w in ws:
                    w.start()
                for w in ws:
                    w.join(120)

            sharedscan.SYNC_COMPILE = True
            try:
                warm_round()
                warm_round()
            finally:
                sharedscan.SYNC_COMPILE = False
            shared_scan_stats(reset=True)

            def tenant_loop(i: int) -> None:
                try:
                    ctx = BallistaContext(
                        *cluster.scheduler_addr, settings=settings(True)
                    )
                    register_all(ctx, str(d))
                    qi = i % len(queries)
                    t0 = time.perf_counter()
                    while time.perf_counter() - t0 < duration:
                        tbl = ctx.sql(queries[qi]).collect()
                        if digest(tbl) != reference[qi]:
                            mismatches.append(qi)
                            print(
                                f"[sharedscan] MISMATCH q{qi}:\n"
                                f"  want {reference_tables[qi]}\n"
                                f"  got  {tbl.to_pydict()}",
                                file=sys.stderr,
                            )
                            return
                        counts[i] += 1
                    ctx.close()
                except Exception as e:
                    errors.append(f"tenant{i}: {e!r}")

            threads = [
                threading.Thread(target=tenant_loop, args=(i,))
                for i in range(tenants)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join(duration + 120)
            wall = time.perf_counter() - t0
            if errors or any(t.is_alive() for t in threads) or not sum(counts):
                print(f"[sharedscan] tenants={tenants}: "
                      f"{errors or ['hung/empty']}", file=sys.stderr)
                return None
            bit_identical = bit_identical and not mismatches
            stats = shared_scan_stats(reset=True)
            row = {
                "tenants": tenants,
                "queries": sum(counts),
                "qps": round(sum(counts) / wall, 2),
                "shared_scan": stats,
            }
            print(f"[sharedscan] {row}", file=sys.stderr)
            sweep.append(row)
        finally:
            cluster.shutdown()
    by_tenants = {r["tenants"]: r for r in sweep}
    result = {
        "sf": sf,
        "duration_s": duration,
        "distinct_queries": len(queries),
        "sweep": sweep,
        "bit_identical": bit_identical,
    }
    if 1 in by_tenants and 4 in by_tenants:
        result["qps_1"] = by_tenants[1]["qps"]
        result["qps_4"] = by_tenants[4]["qps"]
        result["qps_4_over_1"] = round(
            by_tenants[4]["qps"] / max(by_tenants[1]["qps"], 1e-9), 2
        )
    print(f"[sharedscan] sweep done: {[ (r['tenants'], r['qps']) for r in sweep ]} "
          f"bit_identical={bit_identical}", file=sys.stderr)
    return result


def _elastic_scenario() -> dict | None:
    """Elastic-fleet scenario (ISSUE 15): a burst of concurrent jobs on the
    SHARED shuffle tier against an autoscaled cluster (min=1, max=3) — the
    admission queue's cost-model-predicted backlog grows the fleet, every
    job completes bit-identical to a fixed single-executor reference with
    ZERO task retries, and the idle fleet drains gracefully back to min.
    Reports fleet-size/backlog gauges (peaks included), the scale/drain
    counters, and the storage-vs-peer shuffle fetch mix.

    Knobs: BENCH_ELASTIC_JOBS (default 6), BENCH_ELASTIC_ROWS (default
    60000), BENCH_ELASTIC_MAX (default 3)."""
    import tempfile

    import numpy as np
    import pyarrow as pa

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops.runtime import (
        fleet_stats,
        recovery_stats,
        shuffle_tier_stats,
    )
    from ballista_tpu.proto import ballista_pb2 as pb

    n_jobs = int(os.environ.get("BENCH_ELASTIC_JOBS", "6"))
    n_rows = int(os.environ.get("BENCH_ELASTIC_ROWS", "60000"))
    fleet_max = int(os.environ.get("BENCH_ELASTIC_MAX", "3"))
    rng = np.random.default_rng(15)
    table = pa.table({
        "g": pa.array(rng.integers(0, 11, n_rows), type=pa.int64()),
        "v": pa.array(np.round(rng.uniform(-100, 100, n_rows), 2)),
        "q": pa.array(rng.integers(1, 50, n_rows), type=pa.int64()),
    })
    sql = ("select g, sum(v) as s, min(q) as mn, max(q) as mx, count(*) as n "
           "from t group by g order by g")

    with tempfile.TemporaryDirectory(prefix="ballista-elastic-") as shared:
        client_settings = {
            "ballista.shuffle.partitions": "8",
            "ballista.cache.results": "false",
            "ballista.shuffle.tier": "shared",
            "ballista.shuffle.dir": shared,
        }
        # fixed single-executor reference (also the bit-identity oracle)
        cluster = StandaloneCluster(n_executors=1)
        try:
            ctx = BallistaContext(
                *cluster.scheduler_addr, settings=client_settings
            )
            ctx.register_record_batches("t", table, n_partitions=8)
            ref = ctx.sql(sql).collect()
            ctx.close()
        finally:
            cluster.shutdown()

        fleet_stats(reset=True)
        recovery_stats(reset=True)
        shuffle_tier_stats(reset=True)
        cluster = StandaloneCluster(
            n_executors=1,
            config=BallistaConfig({
                "ballista.fleet.min": "1",
                "ballista.fleet.max": str(fleet_max),
                "ballista.fleet.interval_s": "0.1",
                "ballista.fleet.target_backlog_s": "0.05",
            }),
        )
        try:
            ctx = BallistaContext(
                *cluster.scheduler_addr, settings=client_settings
            )
            ctx.register_record_batches("t", table, n_partitions=8)
            t0 = time.perf_counter()
            jobs = [ctx.submit(ctx.sql(sql).logical_plan())
                    for _ in range(n_jobs)]
            peak = cluster.fleet_size()
            deadline = time.time() + 120
            statuses = []
            while time.time() < deadline:
                peak = max(peak, cluster.fleet_size())
                statuses = [
                    ctx._client.get_job_status(
                        pb.GetJobStatusParams(job_id=j)
                    ).status
                    for j in jobs
                ]
                if all(
                    s.WhichOneof("status") in ("completed", "failed")
                    for s in statuses
                ):
                    break
                time.sleep(0.05)
            completed = sum(
                1 for s in statuses if s.WhichOneof("status") == "completed"
            )
            bit_identical = completed == n_jobs
            for j in jobs:
                got = ctx._collect_results(j, ref.schema)
                bit_identical = bit_identical and got.equals(ref)
            wall = time.perf_counter() - t0
            # idle drain back to min
            deadline = time.time() + 60
            while time.time() < deadline and cluster.fleet_size() > 1:
                time.sleep(0.1)
            fleet_final = cluster.fleet_size()
            ctx.close()
        finally:
            cluster.shutdown()

    fl = fleet_stats(reset=True)
    tier = shuffle_tier_stats(reset=True)
    rec = recovery_stats(reset=True)
    result = {
        "jobs": n_jobs,
        "fleet_min": 1,
        "fleet_max": fleet_max,
        "fleet_peak": int(peak),
        "fleet_final": int(fleet_final),
        "backlog_ms_peak": round(fl.get("backlog_ms_peak", 0.0), 1),
        "wall_s": round(wall, 2),
        "bit_identical": bit_identical,
        "fleet": {k: v for k, v in fl.items()},
        "shuffle_tier": tier,
        "task_retries": int(rec.get("task_retry", 0)),
    }
    print(f"[elastic] peak={result['fleet_peak']} "
          f"final={result['fleet_final']} "
          f"backlog_ms_peak={result['backlog_ms_peak']} "
          f"storage_fetch={tier.get('storage_fetch', 0)} "
          f"peer_fetch={tier.get('peer_fetch', 0)} "
          f"bit_identical={bit_identical}", file=sys.stderr)
    return result


def _exchange_scenario() -> dict | None:
    """HBM-resident exchange scenario (ISSUE 16): a 2-stage aggregation on
    one executor, run three ways — exchange ON (the reduce side resolves
    its local map pieces from the in-process registry: zero decode, zero
    re-upload), exchange OFF (the authoritative Arrow-piece ladder, also
    the bit-identity oracle), and exchange ON under seeded exchange.evict
    chaos (every consume-time probe torn: reads degrade to the ladder with
    ZERO task retries). Reports the skip/savings counters and a digest of
    the result bytes so CI can assert all three runs are bit-identical.

    Knobs: BENCH_EXCHANGE_ROWS (default 60000), BENCH_EXCHANGE_SEED
    (chaos seed, default 5)."""
    import hashlib

    import numpy as np
    import pyarrow as pa

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops import exchange
    from ballista_tpu.ops.runtime import exchange_stats, recovery_stats

    n_rows = int(os.environ.get("BENCH_EXCHANGE_ROWS", "60000"))
    chaos_seed = int(os.environ.get("BENCH_EXCHANGE_SEED", "5"))
    rng = np.random.default_rng(16)
    table = pa.table({
        "g": pa.array(rng.integers(0, 13, n_rows), type=pa.int64()),
        "v": pa.array(np.round(rng.uniform(-100, 100, n_rows), 2)),
        "q": pa.array(rng.integers(1, 50, n_rows), type=pa.int64()),
    })
    sql = ("select g, sum(v) as s, min(q) as mn, max(q) as mx, count(*) as n "
           "from t group by g order by g")

    def run(settings):
        exchange.reset()
        exchange_stats(reset=True)
        recovery_stats(reset=True)
        cluster = StandaloneCluster(n_executors=1)
        try:
            ctx = BallistaContext(*cluster.scheduler_addr, settings={
                "ballista.shuffle.partitions": "8",
                "ballista.cache.results": "false",
                **settings,
            })
            ctx.register_record_batches("t", table, n_partitions=8)
            t0 = time.perf_counter()
            out = ctx.sql(sql).collect()
            dt = time.perf_counter() - t0
            ctx.close()
        finally:
            cluster.shutdown()
        return out, dt, exchange_stats(reset=True), recovery_stats(reset=True)

    def digest(tbl):
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, tbl.schema) as w:
            w.write_table(tbl)
        return hashlib.sha256(sink.getvalue().to_pybytes()).hexdigest()[:16]

    on_out, on_dt, on_stats, on_rec = run({})
    off_out, off_dt, off_stats, _ = run({"ballista.tpu.exchange": "false"})
    chaos_out, chaos_dt, chaos_stats, chaos_rec = run({
        "ballista.chaos.rate": "1.0",
        "ballista.chaos.seed": str(chaos_seed),
        "ballista.chaos.sites": "exchange.evict",
    })

    bit_identical = on_out.equals(off_out) and chaos_out.equals(off_out)
    result = {
        "rows": n_rows,
        "digest": digest(off_out),
        "bit_identical": bit_identical,
        "on_ms": round(on_dt * 1000, 1),
        "off_ms": round(off_dt * 1000, 1),
        "chaos_ms": round(chaos_dt * 1000, 1),
        "published": int(on_stats.get("published", 0)),
        "reupload_skipped": int(on_stats.get("reupload_skipped", 0)),
        "h2d_bytes_saved": int(on_stats.get("h2d_bytes_saved", 0)),
        "served_from_registry": int(on_stats.get("served_from_registry", 0)),
        "d2h_bytes_saved": int(on_stats.get("d2h_bytes_saved", 0)),
        "off_stats_empty": off_stats == {},
        "task_retries": int(on_rec.get("task_retry", 0)),
        "chaos": {
            "evicted_chaos": int(chaos_stats.get("evicted_chaos", 0)),
            "miss": int(chaos_stats.get("miss", 0)),
            "injected": int(chaos_rec.get("chaos_injected", 0)),
            "task_retries": int(chaos_rec.get("task_retry", 0)),
        },
    }
    print(f"[exchange] reupload_skipped={result['reupload_skipped']} "
          f"h2d_bytes_saved={result['h2d_bytes_saved']} "
          f"d2h_bytes_saved={result['d2h_bytes_saved']} "
          f"chaos_evicted={result['chaos']['evicted_chaos']} "
          f"bit_identical={bit_identical}", file=sys.stderr)
    return result


def _delta_scenario() -> dict | None:
    """Incremental-execution scenario (ISSUE 19): a cached aggregation over
    a growing parquet chunk set, run four ways —

    - chunk reuse (advance off): an in-process engine with the persisted
      layout store re-runs the query after a file append and must RELOAD
      every existing chunk's tiles (chunks_reused >= 1) instead of
      re-preparing the whole set;
    - advancement: a standalone cluster with ballista.cache.advance on
      folds delta partials over only the appended file into the cached
      aggregate state (advance_hits >= 1) — strictly faster than a cold
      full run over the grown set, and bit-identical to it;
    - torn publish: the same append under seeded cache.advance chaos at
      rate 1.0 declines the advancement and falls back to a full
      recompute — still bit-identical, zero wrong answers;
    - restart: the advanced entry (state inline in a durable KV) keeps
      serving as a plain cache hit across a scheduler restart.

    Knobs: BENCH_DELTA_ROWS (rows per file, default 50000),
    BENCH_DELTA_SEED (chaos seed, default 19)."""
    import hashlib
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.executor.runtime import StandaloneCluster
    from ballista_tpu.ops import kernels
    from ballista_tpu.ops.runtime import (
        delta_stats,
        release_stage_residency,
        reset_residency,
        tenancy_stats,
    )
    from ballista_tpu.scheduler.kv import SqliteBackend

    n_rows = int(os.environ.get("BENCH_DELTA_ROWS", "50000"))
    chaos_seed = int(os.environ.get("BENCH_DELTA_SEED", "19"))
    sql = ("select g, sum(v) as sv, count(*) as c, min(v) as mn "
           "from t where w > -5 group by g order by g")

    def write_part(d, i):
        rng = np.random.default_rng(190 + i)
        pq.write_table(pa.table({
            "g": pa.array(rng.integers(0, 7, n_rows), type=pa.int64()),
            "v": pa.array(rng.integers(-50, 50, n_rows), type=pa.int64()),
            "w": pa.array(rng.integers(-10, 10, n_rows), type=pa.int64()),
        }), os.path.join(d, f"part-{i}.parquet"))

    def digest(tbl):
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, tbl.schema) as w:
            w.write_table(tbl)
        return hashlib.sha256(sink.getvalue().to_pybytes()).hexdigest()[:16]

    def reset_stage_caches():
        # fresh-process simulation: the chunk-reuse leg must reload tiles
        # from the persisted store, not from this process's stage cache
        for stage in kernels._stage_cache.values():
            if stage not in (None, False):
                release_stage_residency(stage)
        kernels._stage_cache.clear()
        kernels._stage_cache_pins.clear()
        kernels._stage_latest.clear()
        reset_residency()

    # -- leg 1: chunk reuse through the persisted layout store --------------
    with tempfile.TemporaryDirectory() as d, \
            tempfile.TemporaryDirectory() as cache_dir:
        write_part(d, 0)
        write_part(d, 1)

        def engine_run():
            ctx = ExecutionContext(BallistaConfig({
                "ballista.executor.backend": "tpu",
                "ballista.tpu.layout_cache_dir": cache_dir,
                "ballista.batch.size": "4096",
            }))
            ctx.register_parquet("t", d)
            return ctx.sql(sql).collect()

        delta_stats(reset=True)
        engine_run()
        write_part(d, 2)
        reset_stage_caches()
        engine_run()
        chunk_stats = delta_stats(reset=True)
        reset_stage_caches()

    def cluster_run(d, cluster, settings=None):
        ctx = BallistaContext(*cluster.scheduler_addr, settings={
            "ballista.cache.advance": "true",
            **(settings or {}),
        })
        ctx.register_parquet("t", d)
        t0 = time.perf_counter()
        out = ctx.sql(sql).collect()
        dt = time.perf_counter() - t0
        ctx.close()
        return out, dt

    # -- leg 2: advancement vs cold full run --------------------------------
    with tempfile.TemporaryDirectory() as d:
        write_part(d, 0)
        write_part(d, 1)
        cluster = StandaloneCluster(n_executors=2)
        try:
            delta_stats(reset=True)
            cluster_run(d, cluster)
            write_part(d, 2)
            adv_out, adv_dt = cluster_run(d, cluster)
            adv_stats = delta_stats(reset=True)
            cold_out, cold_dt = cluster_run(
                d, cluster, settings={"ballista.cache.results": "false"})
            cold_dt = min(cold_dt, cluster_run(
                d, cluster,
                settings={"ballista.cache.results": "false"})[1])
        finally:
            cluster.shutdown()

    # -- leg 3: torn publish under cache.advance chaos ----------------------
    with tempfile.TemporaryDirectory() as d:
        write_part(d, 0)
        write_part(d, 1)
        chaos_cfg = BallistaConfig({
            "ballista.chaos.seed": str(chaos_seed),
            "ballista.chaos.rate": "1.0",
            "ballista.chaos.sites": "cache.advance",
        })
        cluster = StandaloneCluster(n_executors=2, config=chaos_cfg)
        try:
            delta_stats(reset=True)
            cluster_run(d, cluster)
            write_part(d, 2)
            chaos_out, _ = cluster_run(d, cluster)
            chaos_stats = delta_stats(reset=True)
        finally:
            cluster.shutdown()

    # -- leg 4: advanced entry across a scheduler restart -------------------
    with tempfile.TemporaryDirectory() as d:
        write_part(d, 0)
        write_part(d, 1)
        kv = SqliteBackend.temporary()
        cluster = StandaloneCluster(n_executors=1, kv=kv)
        try:
            delta_stats(reset=True)
            cluster_run(d, cluster)
            write_part(d, 2)
            cluster_run(d, cluster)
            restart_advanced = delta_stats(reset=True).get(
                "advance_hits", 0) >= 1
            cluster.restart_scheduler()
            tenancy_stats(reset=True)
            restart_out, _ = cluster_run(d, cluster)
            restart_hit = tenancy_stats(reset=True).get("cache_hit", 0) >= 1
        finally:
            cluster.shutdown()

    bit_identical = (adv_out.equals(cold_out)
                     and chaos_out.equals(cold_out)
                     and restart_out.equals(cold_out))
    result = {
        "rows_per_file": n_rows,
        "digest": digest(cold_out),
        "bit_identical": bit_identical,
        "advance_ms": round(adv_dt * 1000, 1),
        "cold_ms": round(cold_dt * 1000, 1),
        "speedup": round(cold_dt / adv_dt, 2) if adv_dt else None,
        "chunks_reused": int(chunk_stats.get("chunks_reused", 0)),
        "chunks_prepared": int(chunk_stats.get("chunks_prepared", 0)),
        "bytes_reprepared_saved": int(
            chunk_stats.get("bytes_reprepared_saved", 0)),
        "advance_hits": int(adv_stats.get("advance_hits", 0)),
        "advance_declined": int(adv_stats.get("advance_declined", 0)),
        "chaos": {
            "advance_hits": int(chaos_stats.get("advance_hits", 0)),
            "advance_declined": int(chaos_stats.get("advance_declined", 0)),
        },
        "restart_advanced": restart_advanced,
        "restart_cache_hit": restart_hit,
    }
    print(f"[delta] advance_ms={result['advance_ms']} "
          f"cold_ms={result['cold_ms']} "
          f"chunks_reused={result['chunks_reused']} "
          f"advance_hits={result['advance_hits']} "
          f"bit_identical={bit_identical}", file=sys.stderr)
    return result


def _routing_scenario() -> dict | None:
    """Adaptive-execution smoke (ISSUE 10): an in-process skewed join whose
    build-key multiplicity sits past the static admission ladder, run cold,
    warm, and with the cost model off. CI asserts off the returned record
    that the `routing` block appears, that the cold run SPLIT at the tier
    boundary instead of declining wholesale, that every configuration's
    result is bit-identical to the host backend, and that the mispredict
    accounting sums (mispredicts <= predictions <= total decisions;
    mispredict_rate == mispredicts/predictions). Device-free images run
    this fine — the device path runs on whatever jax platform is up."""
    import tempfile

    import numpy as np
    import pyarrow as pa

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.engine import ExecutionContext
    from ballista_tpu.ops import costmodel
    from ballista_tpu.ops.runtime import routing_stats

    rng = np.random.default_rng(7)
    # one monster key past the top static tier (256) + a unique tail: the
    # shape partial offload exists for
    nb = 2000
    bkeys = np.concatenate([np.arange(nb), np.full(400, nb // 2)])
    rng.shuffle(bkeys)
    build = pa.table({"bk": pa.array(bkeys, type=pa.int64()),
                      "bv": pa.array(np.arange(len(bkeys), dtype=np.int64))})
    # guaranteed monster probes: the split shape must not ride rng luck
    pkeys = np.concatenate([rng.integers(0, nb + 200, 4000),
                            np.full(3, nb // 2)])
    probe = pa.table({"pk": pa.array(pkeys, type=pa.int64()),
                      "pv": pa.array(np.arange(len(pkeys), dtype=np.int64))})

    def run(backend: str, cm: str, store_dir: str, iters: int = 1):
        ctx = ExecutionContext(BallistaConfig({
            "ballista.executor.backend": backend,
            "ballista.tpu.cost_model": cm,
            "ballista.tpu.cost_model_dir": store_dir,
        }))
        ctx.register_record_batches("b", build, n_partitions=1)
        ctx.register_record_batches("p", probe, n_partitions=1)
        df = ctx.table("b").join(ctx.table("p"), ["bk"], ["pk"], how="inner")
        # iters > 1 warms the gather/host-cost buckets past
        # costmodel.MIN_OBSERVATIONS so later decisions carry predictions
        # (every iteration re-executes the join; results must all agree)
        outs = [df.collect().to_pylist() for _ in range(iters)]
        assert all(o == outs[0] for o in outs[1:])
        return outs[0]

    with tempfile.TemporaryDirectory() as tmp:
        costmodel.reset(clear_dir=True)
        routing_stats(reset=True)  # drain: attribute decisions to the runs
        host = run("cpu", "false", "")
        cold = run("tpu", "true", tmp, iters=6)
        costmodel.flush()
        costmodel.reset()  # fresh process simulation: reload from disk
        warm = run("tpu", "true", tmp, iters=2)
        off = run("tpu", "false", "")
        routing = _routing_snapshot()
    if routing is None:
        print("[routing] smoke made no routing decisions", file=sys.stderr)
        return None
    routing["bit_identical"] = host == cold == warm == off
    print(f"[routing] smoke: engines={routing['engines']} "
          f"splits={routing['splits']} "
          f"bit_identical={routing['bit_identical']}", file=sys.stderr)
    return routing


def _replica_client_proc(endpoints, home, table, settings, qlist, idx,
                         duration, out_q) -> None:
    """One closed-loop admission client homed to replica ``home`` (peer
    endpoints armed for redirect/failover). Buffered-collects every query
    and content-hashes the result so the parent can assert bit-identity
    across replica counts without shipping tables."""
    try:
        import hashlib

        from ballista_tpu.client import BallistaContext

        host, port = endpoints[home]
        ctx = BallistaContext(host, port, settings=settings,
                              endpoints=endpoints[home:] + endpoints[:home])
        ctx.register_record_batches("t", table, n_partitions=4)
        digests = set()
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < duration:
            sql = qlist[(idx + n) % len(qlist)]
            n += 1
            tbl = ctx.sql(sql).collect()
            digests.add(
                hashlib.sha256(repr(tbl.to_pydict()).encode()).hexdigest()
            )
        wall = time.perf_counter() - t0
        ctx.close()
        out_q.put(("ok", idx, n, wall, sorted(digests)))
    except Exception as e:
        out_q.put(("error", idx, repr(e)))


def _replica_scenario() -> dict | None:
    """Replicated control plane scenario (ISSUE 20): closed-loop admission
    against ONE process-local cluster run two ways — a single scheduler,
    then two lease-sharded replicas over the same KV store. C client
    processes (homed round-robin across the replicas, peers armed for
    ownership redirects) submit-and-collect a fixed aggregation workload
    for a fixed window. Reports per-config completed-query QPS and asserts
    the UNION of result digests is identical across configs, so the
    throughput comparison can never ride a correctness regression.

    Knobs: BENCH_REPLICA_DURATION (default 4 s), BENCH_REPLICA_CLIENTS
    (default 4), BENCH_REPLICA_ROWS (default 40000)."""
    import multiprocessing as mp

    import numpy as np
    import pyarrow as pa

    from ballista_tpu.executor.runtime import StandaloneCluster

    duration = float(os.environ.get("BENCH_REPLICA_DURATION", "4"))
    clients = int(os.environ.get("BENCH_REPLICA_CLIENTS", "4"))
    n_rows = int(os.environ.get("BENCH_REPLICA_ROWS", "40000"))
    rng = np.random.default_rng(20)
    table = pa.table({
        "g": pa.array(rng.integers(0, 40, n_rows), type=pa.int64()),
        "v": pa.array(np.round(rng.uniform(-100, 100, n_rows), 2)),
        "q": pa.array(rng.integers(1, 50, n_rows), type=pa.int64()),
        "s": pa.array([f"t{x}" for x in rng.integers(0, 5, n_rows)]),
    })
    settings = {"ballista.shuffle.partitions": "4"}
    qlist = [
        "select g, sum(v) as s, count(*) as n from t group by g order by g",
        "select s, min(q) as mn, max(q) as mx from t group by s order by s",
        "select g, sum(q) as sq from t where v > 0 group by g order by g",
        "select s, count(*) as n from t where q < 30 group by s order by s",
        "select g, s, sum(v) as sv from t group by g, s order by g, s",
        "select s, sum(v) as sv, sum(q) as sq from t group by s order by s",
    ]

    def run(n_schedulers: int):
        cluster = StandaloneCluster(n_executors=2, n_schedulers=n_schedulers)
        try:
            endpoints = [("127.0.0.1", p) for p in cluster.ports]
            mpctx = mp.get_context("spawn")
            out_q = mpctx.Queue()
            procs = [
                mpctx.Process(
                    target=_replica_client_proc,
                    args=(endpoints, i % n_schedulers, table, settings,
                          qlist, i, duration, out_q),
                    daemon=True,
                )
                for i in range(clients)
            ]
            for p in procs:
                p.start()
            qps, digests, errors = 0.0, set(), []
            got = 0
            deadline = time.monotonic() + duration + 240
            while got < clients and time.monotonic() < deadline:
                try:
                    msg = out_q.get(
                        timeout=max(0.1, deadline - time.monotonic())
                    )
                except Exception:
                    break
                got += 1
                if msg[0] == "error":
                    errors.append(f"client{msg[1]}: {msg[2]}")
                    continue
                _tag, _idx, n, wall, ds = msg
                qps += n / max(wall, 1e-9)
                digests.update(ds)
            for p in procs:
                p.join(10)
                if p.is_alive():
                    errors.append("client process still running; terminated")
                    p.terminate()
            if got < clients and not errors:
                errors.append(f"only {got}/{clients} clients reported")
            if errors:
                raise RuntimeError(str(errors))
            return qps, digests
        finally:
            cluster.shutdown()

    one_qps, one_digests = run(1)
    two_qps, two_digests = run(2)
    result = {
        "rows": n_rows,
        "clients": clients,
        "duration_s": duration,
        "one": {"schedulers": 1, "qps": round(one_qps, 2)},
        "two": {"schedulers": 2, "qps": round(two_qps, 2)},
        "speedup": round(two_qps / max(one_qps, 1e-9), 3),
        "digests_identical": one_digests == two_digests,
        "n_digests": len(one_digests),
    }
    print(f"[replica] 1-replica={result['one']['qps']}qps "
          f"2-replica={result['two']['qps']}qps "
          f"speedup={result['speedup']} "
          f"digests_identical={result['digests_identical']}",
          file=sys.stderr)
    return result


def main() -> None:
    if os.environ.get("BENCH_ROUTING_ONLY"):
        # adaptive-execution smoke only: runs without a reachable device
        print(json.dumps({"routing": _routing_scenario()}))
        return
    if os.environ.get("BENCH_LATENCY_ONLY"):
        # serving-tier scenario only: runs without a reachable device
        print(json.dumps({"latency": _latency_scenario()}))
        return
    if os.environ.get("BENCH_SPECULATION_ONLY"):
        # straggler-tail scenario only: runs without a reachable device
        print(json.dumps({"speculation": _speculation_scenario()}))
        return
    if os.environ.get("BENCH_MULTITENANT_ONLY"):
        # control-plane scenario only: runs without a reachable device
        print(json.dumps({"multitenant": _multitenant_scenario()}))
        return
    if os.environ.get("BENCH_SHAREDSCAN_ONLY"):
        # shared-scan scenario only: runs without a reachable device
        print(json.dumps({"shared_scan": _sharedscan_scenario()}))
        return
    if os.environ.get("BENCH_ELASTIC_ONLY"):
        # elastic-fleet scenario only: runs without a reachable device
        print(json.dumps({"elastic": _elastic_scenario()}))
        return
    if os.environ.get("BENCH_EXCHANGE_ONLY"):
        # HBM-resident exchange scenario only: runs without a reachable device
        print(json.dumps({"exchange": _exchange_scenario()}))
        return
    if os.environ.get("BENCH_DELTA_ONLY"):
        # incremental-execution scenario only: runs without a reachable device
        print(json.dumps({"delta": _delta_scenario()}))
        return
    if os.environ.get("BENCH_REPLICA_ONLY"):
        # replicated control-plane scenario only: runs without a device
        print(json.dumps({"replica": _replica_scenario()}))
        return
    _probe_device()
    ensure_data(SF)
    import pyarrow.parquet as pq

    files = sorted((data_dir(SF) / "lineitem").glob("*.parquet"))
    rows = pq.read_metadata(files[0]).num_rows * len(files)

    # headline: q1 at BENCH_SF — warmup (compile + caches) then best-of-3
    # steady state, both backends
    q1 = (QUERIES_DIR / "q1.sql").read_text()
    _ingest_snapshot()  # drain
    run_once("tpu", q1)
    headline_ingest = _ingest_snapshot()
    _readback_snapshot()  # drain
    _routing_snapshot()  # drain
    tpu_dt = min(run_once("tpu", q1) for _ in range(3))
    headline_readback = _per_query(_readback_snapshot(), 3)
    headline_routing = _routing_snapshot()
    run_once("cpu", q1)
    cpu_dt = min(run_once("cpu", q1) for _ in range(3))

    configs = []
    # default list: SF<=10 first, then taxi, then the slow SF=100 rows — so
    # the soft deadline can only ever truncate the tail, never the cheap
    # rows. An explicit BENCH_CONFIGS keeps the user's order and runs taxi
    # last, so requested rows are never starved by unrequested ones.
    user_configs = bool(os.environ.get("BENCH_CONFIGS"))
    ordered = CONFIGS if user_configs else sorted(CONFIGS, key=lambda c: c[0] > 10)
    taxi_done = False
    for sf, name in ordered:
        if not user_configs and not taxi_done and sf > 10:
            if time.monotonic() - _T_START <= MAX_SECONDS:
                configs.extend(_taxi_rows())
            taxi_done = True
        if (sf, name) == (SF, "q1"):
            configs.append({"name": "q1", "sf": SF,
                            "tpu_ms": round(tpu_dt * 1000, 1),
                            "cpu_ms": round(cpu_dt * 1000, 1),
                            "speedup": round(cpu_dt / tpu_dt, 2)})
            continue
        if time.monotonic() - _T_START > MAX_SECONDS:
            print(f"[config] {name} sf={sf}: skipped (past "
                  f"{MAX_SECONDS:.0f}s soft deadline)", file=sys.stderr)
            continue
        row = bench_config(sf, name, iters=3 if sf <= 1 else (2 if sf <= 10 else 1))
        if row is not None:
            configs.append(row)
    if not taxi_done and time.monotonic() - _T_START <= MAX_SECONDS:
        configs.extend(_taxi_rows())

    value = rows / tpu_dt
    baseline = rows / cpu_dt
    result = {
        "metric": f"tpch_q1_sf{SF}_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(value / baseline, 3),
        "configs": configs,
    }
    if headline_ingest is not None:
        result["ingest"] = headline_ingest
    if headline_readback is not None:
        result["readback"] = headline_readback
    if headline_routing is not None:
        result["routing"] = headline_routing
    if time.monotonic() - _T_START <= MAX_SECONDS:
        try:
            mt = _multitenant_scenario()
        except Exception as e:
            print(f"[multitenant] failed: {e}", file=sys.stderr)
            mt = None
        if mt is not None:
            result["multitenant"] = mt
    if time.monotonic() - _T_START <= MAX_SECONDS:
        try:
            latency = _latency_scenario()
        except Exception as e:
            print(f"[latency] failed: {e}", file=sys.stderr)
            latency = None
        if latency is not None:
            result["latency"] = latency
    if time.monotonic() - _T_START <= MAX_SECONDS:
        try:
            speculation = _speculation_scenario()
        except Exception as e:
            print(f"[speculation] failed: {e}", file=sys.stderr)
            speculation = None
        if speculation is not None:
            result["speculation"] = speculation
    if time.monotonic() - _T_START <= MAX_SECONDS:
        try:
            elastic = _elastic_scenario()
        except Exception as e:
            print(f"[elastic] failed: {e}", file=sys.stderr)
            elastic = None
        if elastic is not None:
            result["elastic"] = elastic
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    _persist_capture({**result, "platform": platform})
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""Benchmark: TPC-H q1 end-to-end through the engine, TPU backend vs host
Arrow backend on the same machine.

Prints ONE JSON line:
  {"metric": ..., "value": rows/s on the device backend,
   "unit": "rows/s/chip", "vs_baseline": speedup over the host backend}

Reference baseline context: the reference publishes no numbers
(BASELINE.md); the denominator here is this repo's own host Arrow path —
the same role the reference's Rust CPU executor plays in BASELINE.json's
target ("N x the CPU executor's rows/sec").
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

SF = float(os.environ.get("BENCH_SF", "1"))
DATA = REPO / ".bench_cache" / f"tpch_sf{SF}"
QUERIES_DIR = REPO / "benchmarks" / "tpch" / "queries"
QUERY = (QUERIES_DIR / "q1.sql").read_text()
BATCH = "16777216"
# secondary configs reported to stderr (BASELINE.md configs 1, 3 and the
# high-cardinality aggregate-over-join shape)
SIDE_QUERIES = ["q6", "q3", "q10"]


def ensure_data() -> None:
    if (DATA / "lineitem").exists():
        return
    from benchmarks.tpch.datagen import generate

    DATA.parent.mkdir(exist_ok=True)
    generate(str(DATA), sf=SF, parts=1)


_CTX = {}


def _context(backend: str):
    """One session per backend (TPC-style steady state: the context —
    catalog, caches, compiled artifacts — persists across queries)."""
    if backend not in _CTX:
        from ballista_tpu.config import BallistaConfig
        from ballista_tpu.engine import ExecutionContext
        from benchmarks.tpch.datagen import register_all

        ctx = ExecutionContext(
            BallistaConfig(
                {
                    "ballista.executor.backend": backend,
                    "ballista.batch.size": BATCH,
                }
            )
        )
        register_all(ctx, str(DATA))
        _CTX[backend] = ctx
    return _CTX[backend]


def run_once(backend: str, sql: str = QUERY) -> float:
    ctx = _context(backend)
    t0 = time.perf_counter()
    out = ctx.sql(sql).collect()
    dt = time.perf_counter() - t0
    assert out.num_rows >= 1
    return dt


def _probe_device(timeout_s: int = 180) -> None:
    """Fail fast (exit 3) when the TPU relay is unreachable: jax.devices()
    otherwise blocks forever and the whole bench run hangs silently."""
    import subprocess

    code = "import jax; print(jax.devices())"
    try:
        subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s, check=True,
            capture_output=True,
        )
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as e:
        tail = (e.stderr or b"").decode(errors="replace").strip().splitlines()[-3:]
        print(
            f"device backend unreachable ({e}); no benchmark possible\n"
            + "\n".join(tail),
            file=sys.stderr,
        )
        raise SystemExit(3)


def main() -> None:
    _probe_device()
    ensure_data()
    import pyarrow.parquet as pq

    rows = pq.read_metadata(
        sorted((DATA / "lineitem").glob("*.parquet"))[0]
    ).num_rows * len(list((DATA / "lineitem").glob("*.parquet")))

    # warmup (compile + caches) then best-of-3 steady state, both backends
    run_once("tpu")
    tpu_dt = min(run_once("tpu") for _ in range(3))
    run_once("cpu")
    cpu_dt = min(run_once("cpu") for _ in range(3))

    # secondary configs (stderr, not the tracked metric)
    try:
        from benchmarks.taxi.datagen import TRIP_AGG_QUERY, generate as taxi_gen

        taxi_dir = REPO / ".bench_cache" / "taxi_sf1"
        if not (taxi_dir / "trips").exists():
            taxi_gen(str(taxi_dir), sf=1.0, parts=1)
        for backend in ("tpu", "cpu"):
            ctx = _context(backend)
            if "trips" not in ctx.tables:
                ctx.register_parquet("trips", str(taxi_dir / "trips"))
        run_once("tpu", TRIP_AGG_QUERY)
        t = min(run_once("tpu", TRIP_AGG_QUERY) for _ in range(2))
        run_once("cpu", TRIP_AGG_QUERY)
        c = min(run_once("cpu", TRIP_AGG_QUERY) for _ in range(2))
        print(f"[side] taxi_10M_265groups: tpu={t*1000:.0f}ms cpu={c*1000:.0f}ms "
              f"speedup={c/t:.2f}x", file=sys.stderr)

        # high-cardinality variant: 10k zones (block-level granularity)
        taxi_hc = REPO / ".bench_cache" / "taxi_hc_sf1"
        if not (taxi_hc / "trips").exists():
            taxi_gen(str(taxi_hc), sf=1.0, parts=1, n_zones=10_000)
        hc_query = TRIP_AGG_QUERY.replace("from trips", "from trips_hc")
        for backend in ("tpu", "cpu"):
            ctx = _context(backend)
            if "trips_hc" not in ctx.tables:
                ctx.register_parquet("trips_hc", str(taxi_hc / "trips"))
        run_once("tpu", hc_query)
        t = min(run_once("tpu", hc_query) for _ in range(2))
        run_once("cpu", hc_query)
        c = min(run_once("cpu", hc_query) for _ in range(2))
        print(f"[side] taxi_10M_10kgroups: tpu={t*1000:.0f}ms cpu={c*1000:.0f}ms "
              f"speedup={c/t:.2f}x", file=sys.stderr)
    except Exception as e:
        print(f"[side] taxi: failed: {e}", file=sys.stderr)
    for q in SIDE_QUERIES:
        sql = (QUERIES_DIR / f"{q}.sql").read_text()
        try:
            run_once("tpu", sql)
            t = min(run_once("tpu", sql), run_once("tpu", sql))
            c = min(run_once("cpu", sql), run_once("cpu", sql))
            print(
                f"[side] {q}: tpu={t*1000:.0f}ms cpu={c*1000:.0f}ms "
                f"speedup={c/t:.2f}x",
                file=sys.stderr,
            )
        except Exception as e:
            print(f"[side] {q}: failed: {e}", file=sys.stderr)

    value = rows / tpu_dt
    baseline = rows / cpu_dt
    print(
        json.dumps(
            {
                "metric": f"tpch_q1_sf{SF}_rows_per_sec",
                "value": round(value, 1),
                "unit": "rows/s/chip",
                "vs_baseline": round(value / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()

"""dtype-discipline: the f64->f32 narrowing policy (ops/runtime.py module
docstring) — float64 must never reach traced code or flow into a device
transfer. Two checks:

1. any float64 mention inside a traced function (device compute is f32/
   int32 by contract; f64 is emulated and slow on TPU, and int packing
   assumes f32 lanes);
2. in device-path modules, a value created as float64 (astype/np.array
   dtype=...) must not flow into jnp.asarray / jax.device_put /
   make_sharded.

Host-side post-readback widening to float64 (Arrow result columns, the
int-exact host folds in ops/layout.py) is the documented result dtype and
is deliberately NOT flagged. ops/floatbits.py is whitelisted whole: its
f64<->i64 bijection is the documented exception to the narrowing policy.
"""

from __future__ import annotations

import ast
from typing import List

from dev.analysis.common import (
    Taint,
    dotted,
    final_name,
    is_device_path,
    iter_functions,
    traced_functions,
    walk_no_nested_defs,
)
from dev.analysis.core import Finding, SourceFile, register

_TRANSFERS = {"asarray", "device_put", "make_sharded"}
_TRANSFER_MODULES = ("jnp", "jax", "mh", "multihost")
_CREATORS = {"array", "zeros", "ones", "full", "empty", "asarray", "arange"}


def _mentions_f64(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, (ast.Attribute, ast.Name)) and final_name(n) == "float64":
            return True
        if isinstance(n, ast.Constant) and n.value == "float64":
            return True
    return False


def _creates_f64(call: ast.Call) -> bool:
    """astype(np.float64), np.zeros(..., dtype=np.float64), np.float64(x)."""
    name = final_name(call.func)
    if name == "float64":
        return True
    if name == "astype":
        return any(_mentions_f64(a) for a in call.args)
    if name in _CREATORS:
        if any(_mentions_f64(a) for a in call.args[1:]):
            return True
        return any(k.arg == "dtype" and _mentions_f64(k.value) for k in call.keywords)
    return False


@register("dtype-discipline")
def check(sf: SourceFile) -> List[Finding]:
    path = sf.path.replace("\\", "/")
    if path.endswith("ballista_tpu/ops/floatbits.py"):
        return []
    findings: List[Finding] = []

    # 1. float64 inside traced code
    for func in traced_functions(sf.tree):
        for node in walk_no_nested_defs(func):
            if isinstance(node, (ast.Attribute, ast.Name, ast.Constant)) and (
                (isinstance(node, ast.Constant) and node.value == "float64")
                or final_name(node) == "float64"
            ):
                findings.append(Finding(
                    "dtype-discipline", sf.path, node.lineno, node.col_offset,
                    f"float64 inside traced function '{func.name}' — device "
                    "compute is f32/int32 by the narrowing policy "
                    "(ops/runtime.py); f64 is emulated on TPU",
                ))

    # 2. f64-created values flowing into a device transfer
    if not is_device_path(sf.path):
        return findings
    for func, _cls in iter_functions(sf.tree):
        taint = Taint(func, lambda call, t: _creates_f64(call))
        for node in walk_no_nested_defs(func):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fname = final_name(node.func)
            if fname not in _TRANSFERS:
                continue
            base = dotted(node.func)
            if base and "." in base and base.split(".")[0] not in _TRANSFER_MODULES:
                continue
            arg = node.args[1] if fname == "make_sharded" and len(node.args) > 1 else node.args[0]
            if taint.expr_tainted(arg) or (
                isinstance(arg, ast.Call) and _creates_f64(arg)
            ):
                findings.append(Finding(
                    "dtype-discipline", sf.path, node.lineno, node.col_offset,
                    "float64-created value flows into a device transfer in "
                    f"'{func.name}' — narrow to f32/int32 first "
                    "(ops/runtime.py narrowing policy)",
                ))
    return findings

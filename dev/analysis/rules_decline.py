"""decline-discipline: device paths bail to host ONLY through the
canonical decline signals, so the kernels ladder stays enumerable:

- `raise UnsupportedOnDevice("<reason>")` — the reason string is mandatory
  (a bare decline is invisible in logs and unanalyzable in bench output);
- the ops/kernels.py helpers: `decline(reason)` (raising form),
  `host_fallback(reason)` (Optional-sentinel form, logs + counts), and
  `step_aside(reason)` (mid-ladder: the next rung still gets tried).

Checks, scoped to ballista_tpu/ops/ and ballista_tpu/parallel/:

1. `raise UnsupportedOnDevice()` / `raise TooManyGroups()` with no reason
   (or an empty one) is flagged;
2. inside an `except UnsupportedOnDevice` (or TooManyGroups) handler, a
   bare `return None` silently converts a reasoned decline into an
   anonymous host fallback — return `host_fallback(<reason>)` instead;
3. ad-hoc `raise Exception/RuntimeError/NotImplementedError` is not a
   decline channel (callers catch UnsupportedOnDevice; anything else
   either crashes the query or is swallowed by a broad fallback handler
   that then logs it as a real error)."""

from __future__ import annotations

import ast
from typing import List

from dev.analysis.common import final_name, is_device_path
from dev.analysis.core import Finding, SourceFile, register

_DECLINE_TYPES = {"UnsupportedOnDevice", "TooManyGroups"}
_ADHOC_TYPES = {"Exception", "RuntimeError", "NotImplementedError"}


def _handler_catches_decline(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(final_name(x) in _DECLINE_TYPES for x in types)


@register("decline-discipline")
def check(sf: SourceFile) -> List[Finding]:
    if not is_device_path(sf.path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Raise) and isinstance(node.exc, ast.Call):
            name = final_name(node.exc.func)
            if name in _DECLINE_TYPES:
                args = node.exc.args
                empty = not args or (
                    isinstance(args[0], ast.Constant)
                    and not str(args[0].value).strip()
                )
                if empty:
                    findings.append(Finding(
                        "decline-discipline", sf.path, node.lineno,
                        node.col_offset,
                        f"{name} raised without a reason — every decline "
                        "must say why (the ladder must stay enumerable)",
                    ))
            elif name in _ADHOC_TYPES:
                findings.append(Finding(
                    "decline-discipline", sf.path, node.lineno,
                    node.col_offset,
                    f"ad-hoc `raise {name}` in a device-path module — "
                    "decline with UnsupportedOnDevice(reason) / "
                    "kernels.decline(reason), or raise a specific typed "
                    "error",
                ))
        elif isinstance(node, ast.ExceptHandler) and _handler_catches_decline(node):
            for inner in ast.walk(node):
                if isinstance(inner, ast.Return):
                    v = inner.value
                    is_none = v is None or (
                        isinstance(v, ast.Constant) and v.value is None
                    )
                    if is_none:
                        findings.append(Finding(
                            "decline-discipline", sf.path, inner.lineno,
                            inner.col_offset,
                            "silent `return None` inside an "
                            "UnsupportedOnDevice handler — return "
                            "kernels.host_fallback(reason) so the decline "
                            "is logged and counted",
                        ))
    return findings

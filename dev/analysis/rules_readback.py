"""readback-discipline: device->host materializations of compiled-program
results in ballista_tpu/ops/ and ballista_tpu/parallel/ must pair with
record_readback (or the runtime.readback helper) in the same function —
otherwise bench.py's readback_rows/readback_bytes undercount and the
paper's O(limit)-readback claim goes unmeasured."""

from __future__ import annotations

import ast
import re
from typing import List

from dev.analysis.common import (
    Taint,
    dotted,
    final_name,
    is_device_path,
    iter_functions,
    walk_no_nested_defs,
)
from dev.analysis.core import Finding, SourceFile, register

# project naming convention for compiled-program factories/handles: a call
# to one of these produces (or IS) a compiled device program whose results
# live on-device until materialized
_PROGRAM_NAME_RE = re.compile(
    r"(^program$|_program$|^_kernel$|_step$|^_build|^_compile_predicate$"
    r"|^sorted_grouped_sum$|^grouped_aggregate$)"
)

_MATERIALIZE = {"np.asarray", "numpy.asarray", "jax.device_get"}
_RECORDERS = {"record_readback", "readback"}


def _jit_assigned_names(func: ast.AST) -> set:
    out = set()
    for node in walk_no_nested_defs(func):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if dotted(node.value.func) in ("jax.jit", "jit"):
                for t in node.targets:
                    name = final_name(t)
                    if name:
                        out.add(name)
    return out


@register("readback-discipline")
def check(sf: SourceFile) -> List[Finding]:
    if not is_device_path(sf.path):
        return []
    findings: List[Finding] = []
    for func, _cls in iter_functions(sf.tree):
        jit_names = _jit_assigned_names(func)

        def is_source(call: ast.Call, taint: Taint) -> bool:
            name = final_name(call.func)
            if name in jit_names or (name and _PROGRAM_NAME_RE.search(name)):
                return True
            return False

        taint = Taint(func, is_source)
        sites = []
        records = False
        for node in walk_no_nested_defs(func):
            if not isinstance(node, ast.Call):
                continue
            if final_name(node.func) in _RECORDERS:
                records = True
                continue
            name = dotted(node.func)
            if name in _MATERIALIZE and node.args:
                target = node.args[0]
            elif (final_name(node.func) == "block_until_ready"
                  and isinstance(node.func, ast.Attribute)):
                target = node.func.value
            else:
                continue
            if taint.expr_tainted(target):
                sites.append(node)
        if sites and not records:
            for s in sites:
                findings.append(Finding(
                    "readback-discipline", sf.path, s.lineno, s.col_offset,
                    "device result materialized without record_readback in "
                    f"'{func.name}' — route through ops.runtime.readback() or "
                    "call record_readback(rows, nbytes) in this function so "
                    "bench readback stats stay truthful",
                ))
    return findings

"""Shared AST machinery: dotted-name resolution, function-local taint
propagation, device-path scoping, and discovery of traced functions
(everything reachable from a jit/shard_map/pallas decoration site)."""

from __future__ import annotations

import ast
import re
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

DEVICE_PATH_RE = re.compile(r"ballista_tpu/(ops|parallel)/[^/]+\.py$")


def is_device_path(display_path: str) -> bool:
    return bool(DEVICE_PATH_RE.search(display_path.replace("\\", "/")))


def dotted(node: ast.AST) -> Optional[str]:
    """'np.asarray' for Attribute/Name chains; None for anything else."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def final_name(node: ast.AST) -> Optional[str]:
    """Last segment of a Name/Attribute (call targets of any base)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def walk_no_nested_defs(node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested function/class
    definitions (they are analyzed as their own scopes)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def iter_functions(tree: ast.Module):
    """Yield (func, enclosing_class_or_None) for every def at any depth."""
    def rec(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from rec(child, cls)
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, child)
            else:
                yield from rec(child, cls)

    yield from rec(tree, None)


class Taint:
    """Function-local forward taint: seeds are expressions `is_source`
    accepts; assignment targets of tainted right-hand sides become tainted,
    as do calls through tainted callees, subscripts, and attributes.
    Iterates to a fixpoint so textual order doesn't matter."""

    def __init__(self, func: ast.AST,
                 is_source: Callable[[ast.Call, "Taint"], bool]):
        self.func = func
        self.is_source = is_source
        self.names: Set[str] = set()
        self._solve()

    def expr_tainted(self, expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in self.names:
                return True
            if isinstance(node, ast.Call) and self.call_tainted(node):
                return True
        return False

    def call_tainted(self, call: ast.Call) -> bool:
        if self.is_source(call, self):
            return True
        # call through a tainted value: run(...), program(...)(...)
        f = call.func
        if isinstance(f, ast.Name) and f.id in self.names:
            return True
        if isinstance(f, ast.Call) and self.call_tainted(f):
            return True
        return False

    def _targets(self, t: ast.AST) -> List[str]:
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            out = []
            for e in t.elts:
                out.extend(self._targets(e))
            return out
        if isinstance(t, ast.Starred):
            return self._targets(t.value)
        return []

    def _solve(self) -> None:
        assigns = [
            n for n in walk_no_nested_defs(self.func)
            if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign))
        ]
        for _ in range(6):
            changed = False
            for a in assigns:
                value = a.value
                if value is None:
                    continue
                if not self.expr_tainted(value):
                    continue
                targets = (
                    a.targets if isinstance(a, ast.Assign) else [a.target]
                )
                for t in targets:
                    for name in self._targets(t):
                        if name not in self.names:
                            self.names.add(name)
                            changed = True
            if not changed:
                return


# -- traced-function discovery ----------------------------------------------
# Decoration sites: @jax.jit, jax.jit(fn), jax.jit(factory(...)),
# functools.partial(jax.jit, ...)(fn_or_factory_call), shard_map(fn, ...),
# pl.pallas_call(kernel, ...). From each resolved function the walk marks
# nested defs and same-module callees (by bare name / self-method name)
# traced, transitively. Project convention: module-level helpers named
# `jnp_*` or `widen_cols` are in-program by contract and always traced.

_JIT_NAMES = {"jax.jit", "jit"}
_WRAP_FINAL = {"shard_map", "pallas_call"}
_CONVENTION_RE = re.compile(r"^jnp_|^widen_cols$")


def _is_partial_jit(call: ast.Call) -> bool:
    """functools.partial(jax.jit, ...) — its result wraps like jax.jit."""
    if final_name(call.func) != "partial" or not call.args:
        return False
    return dotted(call.args[0]) in _JIT_NAMES


class ModuleIndex:
    """Name -> FunctionDef lookups for one module (bare-name resolution:
    good enough for this codebase, where helper names are unique)."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.by_name: Dict[str, List[ast.AST]] = {}
        self.parent_func: Dict[ast.AST, Optional[ast.AST]] = {}
        for func, _cls in iter_functions(tree):
            self.by_name.setdefault(func.name, []).append(func)

        def rec(node, cur):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.parent_func[child] = cur
                    rec(child, child)
                else:
                    rec(child, cur)

        rec(tree, None)

    def resolve(self, name: Optional[str]) -> List[ast.AST]:
        return self.by_name.get(name, []) if name else []


def _wrapped_arg(call: ast.Call) -> Optional[ast.AST]:
    """The function expression a decoration-site call wraps, if any."""
    name = dotted(call.func)
    fin = final_name(call.func)
    if name in _JIT_NAMES or fin in _WRAP_FINAL:
        return call.args[0] if call.args else None
    if isinstance(call.func, ast.Call) and _is_partial_jit(call.func):
        return call.args[0] if call.args else None
    return None


def _returned_inner_defs(factory: ast.AST, index: ModuleIndex) -> List[ast.AST]:
    """Inner defs a factory function returns (jax.jit(self._core()) style:
    the traced function is the closure `_core` builds and returns)."""
    inner = {
        n.name: n
        for n in ast.walk(factory)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not factory
    }
    out = []
    for node in ast.walk(factory):
        if isinstance(node, ast.Return) and node.value is not None:
            for leaf in ast.walk(node.value):
                if isinstance(leaf, ast.Name) and leaf.id in inner:
                    out.append(inner[leaf.id])
    return out


def traced_functions(tree: ast.Module) -> Set[ast.AST]:
    index = ModuleIndex(tree)
    traced: Set[ast.AST] = set()

    def seed(expr: Optional[ast.AST]) -> None:
        if expr is None:
            return
        if isinstance(expr, ast.Lambda):
            return  # lambdas have no statements to check
        name = final_name(expr)
        if name:
            for fn in index.resolve(name):
                traced.add(fn)
            return
        if isinstance(expr, ast.Call):
            # jax.jit(self._sorted_core()) — the factory's returned closure
            for factory in index.resolve(final_name(expr.func)):
                for fn in _returned_inner_defs(factory, index):
                    traced.add(fn)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            seed(_wrapped_arg(node))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if dotted(deco) in _JIT_NAMES:
                    traced.add(node)
                elif isinstance(deco, ast.Call) and (
                    dotted(deco.func) in _JIT_NAMES or _is_partial_jit(deco)
                    or final_name(deco.func) == "when"  # pl.when
                ):
                    traced.add(node)
            if _CONVENTION_RE.match(node.name):
                traced.add(node)

    # transitive closure: nested defs + same-module callees
    work = list(traced)
    while work:
        fn = work.pop()
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
                if node not in traced:
                    traced.add(node)
                    work.append(node)
            elif isinstance(node, ast.Call):
                callee = final_name(node.func)
                for target in index.resolve(callee):
                    if target not in traced:
                        traced.add(target)
                        work.append(target)
    return traced

"""lock-order: interprocedural lock-order graph + deadlock detection
(ISSUE 14).

Two layers share one per-file extraction:

**Per-file checks** (cached like every rule):

- *creation discipline*: every lock bound to a module global or instance
  attribute in ballista_tpu/ must be created through
  ``utils.locks.make_lock/make_rlock`` with its canonical
  ``<module>.<attr>`` name (so the dynamic witness can wrap it and speak
  the analyzer's vocabulary), and must be referenced by at least one
  ``guarded-by:``/``holds-lock:`` annotation in the file (the
  annotation-coverage meta-check).
- *atomicity*: a read of guarded state into a local under ``with lock:``
  followed by a dependent write under a RE-acquired ``with lock:`` is
  check-then-act across a release — flagged unless the write re-reads the
  state it writes (the double-checked-insert idiom) or carries an
  ``# atomicity-ok: <reason>`` annotation.

**Whole-program pass** (``register_global``, run by core.run_paths over
every file's facts): builds the acquired-while-held edge set — direct
``with b:`` inside ``with a:`` nesting, same-module call chains (the
tracer-hygiene style walk), ``# holds-lock:`` entry contexts, cross-module
calls resolved by dotted-base module match or unique bare name, and
``# may-acquire:`` annotations on dynamic-dispatch seams — then reports
every cycle (potential deadlock, both acquisition paths printed) and
enforces dev/analysis/lockorder.toml: every edge declared with a reason,
every edge forward in the canonical order.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dev.analysis import lockgraph
from dev.analysis.common import dotted, final_name, iter_functions
from dev.analysis.core import Finding, SourceFile, register, register_facts, \
    register_global
from dev.analysis.lockgraph import (
    ALIASES,
    KV_LOCK,
    LOCKISH_RE,
    EdgeSite,
    LockGraph,
    Manifest,
    canonical,
    module_of,
)

RULE = "lock-order"

_LOCK_CTORS = {"Lock", "RLock"}
_MAKE_CTORS = {"make_lock": "lock", "make_rlock": "rlock"}
# threading.Semaphore/BoundedSemaphore/Event/Condition are not mutual-
# exclusion locks; they stay raw and outside the graph


def _is_project_path(display_path: str) -> bool:
    return display_path.replace("\\", "/").startswith("ballista_tpu/")


def _lock_name_of_expr(expr: ast.AST, module: str,
                       known: Set[str]) -> Optional[str]:
    """Canonical lock name a with-item (or annotation target) denotes, or
    None when it does not look like a lock acquisition."""
    if isinstance(expr, ast.Call):
        # `<anything>.lock()` / `<client>.lock(name)`: the global KV lock
        if final_name(expr.func) == "lock":
            return KV_LOCK
        return None
    name = final_name(expr)
    if name is None:
        return None
    if name in known or LOCKISH_RE.search(name):
        return canonical(f"{module}.{name}")
    return None


def _lock_name_of_text(text: str, module: str) -> Optional[str]:
    """Canonical lock name from an annotation's source text
    (`self._mu`, `_res_lock`, `self.kv.lock()`, or an already-canonical
    dotted name)."""
    t = text.strip().rstrip(":")
    if t.endswith(".lock()") or t == "lock()":
        return KV_LOCK
    t = t.split("(")[0]
    leaf = t.split(".")[-1].strip()
    if not leaf:
        return None
    if "." in t and not t.startswith("self.") and not t.startswith("cls."):
        # already-canonical dotted form (may-acquire annotations)
        return canonical(t)
    return canonical(f"{module}.{leaf}")


class _Creation:
    __slots__ = ("attr", "kind", "line", "literal", "raw")

    def __init__(self, attr: str, kind: str, line: int,
                 literal: Optional[str], raw: bool) -> None:
        self.attr = attr
        self.kind = kind  # "lock" | "rlock"
        self.line = line
        self.literal = literal  # make_lock("...") name argument
        self.raw = raw  # created via threading.Lock/RLock directly


def _creations(sf: SourceFile) -> List[_Creation]:
    out: List[_Creation] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        fname = final_name(value.func)
        kind = None
        literal = None
        raw = False
        if fname in _LOCK_CTORS:
            base = dotted(value.func) or ""
            if not base.split(".")[0].lstrip("_").startswith("threading"):
                continue
            kind = "lock" if fname == "Lock" else "rlock"
            raw = True
        elif fname in _MAKE_CTORS:
            kind = _MAKE_CTORS[fname]
            if value.args and isinstance(value.args[0], ast.Constant) \
                    and isinstance(value.args[0].value, str):
                literal = value.args[0].value
        else:
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            attr = None
            if isinstance(t, ast.Name):
                attr = t.id
            elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id in ("self", "obj"):
                # `obj._mu = ...` covers the SqliteBackend.temporary()
                # __new__-style constructor
                attr = t.attr
            if attr is not None:
                out.append(_Creation(attr, kind, node.lineno, literal, raw))
    return out


class _FuncWalk(ast.NodeVisitor):
    """One function's acquisition/nesting/call record, tracking the held
    stack through `with` statements (entry context from holds-lock)."""

    def __init__(self, sf: SourceFile, module: str, known: Set[str],
                 entry: Optional[str]) -> None:
        self.sf = sf
        self.module = module
        self.known = known
        self.held: List[str] = [entry] if entry else []
        self.acquires: List[Tuple[str, int]] = []
        self.nested: List[Tuple[str, str, int]] = []
        self.calls: List[Tuple[str, str, int, Tuple[str, ...]]] = []

    def visit_With(self, node: ast.With) -> None:
        locks = []
        for item in node.items:
            name = _lock_name_of_expr(item.context_expr, self.module, self.known)
            if name is not None:
                self.acquires.append((name, node.lineno))
                if name in self.held:
                    # re-acquisition of a held lock class: record ONLY the
                    # self pair (an rlock re-entry is dropped at build
                    # time, a plain lock self-deadlocks) — NOT edges from
                    # the other held locks, which a reentrant re-entry can
                    # never deadlock against (it cannot block)
                    self.nested.append((name, name, node.lineno))
                else:
                    for h in self.held:
                        self.nested.append((h, name, node.lineno))
                locks.append(name)
        self.held.extend(locks)
        self.generic_visit(node)
        if locks:
            del self.held[-len(locks):]

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        callee = final_name(node.func)
        if callee and callee != "lock":
            base = ""
            if isinstance(node.func, ast.Attribute):
                # "<attr>" marks an attribute call whose base is not a
                # plain name chain (subscript, call result): it must NOT
                # fall through to bare-name resolution
                base = dotted(node.func.value) or "<attr>"
            self.calls.append((callee, base, node.lineno, tuple(self.held)))
        self.generic_visit(node)

    def _skip_nested_def(self, node) -> None:
        # nested defs are walked as their own functions (with the
        # DEFINING context's held stack as entry — a closure launched on a
        # thread starts lock-free, but one *called* inline inherits; the
        # conservative choice is the empty stack plus its own holds-lock)
        return

    visit_FunctionDef = _skip_nested_def
    visit_AsyncFunctionDef = _skip_nested_def
    visit_Lambda = _skip_nested_def


@register_facts(RULE)
def extract_facts(sf: SourceFile) -> dict:
    """Locks created + per-function acquisition/call records for the
    whole-program pass. JSON-serializable (cached per file)."""
    module = module_of(sf.path)
    creations = _creations(sf)
    known = {c.attr for c in creations}
    locks: Dict[str, dict] = {}
    for c in creations:
        name = canonical(f"{module}.{c.attr}")
        prev = locks.get(name)
        kind = c.kind
        if prev is not None and prev["kind"] == "rlock":
            kind = "rlock"  # merged classes: reentrant wins (conservative
            # for self-edges is "lock", but a merged rlock IS reentrant)
        locks[name] = {"kind": kind, "line": c.line}
    functions = []
    for func, _cls in iter_functions(sf.tree):
        entry_text = sf.holds_lock(func)
        entry = _lock_name_of_text(entry_text, module) if entry_text else None
        extra_text = sf.may_acquire_of(func)
        extra = []
        if extra_text:
            for part in extra_text.split(","):
                part = part.strip()
                if part.startswith("group:"):
                    # expanded against the manifest's [groups] in the
                    # whole-program pass (facts stay manifest-independent)
                    extra.append(part)
                    continue
                n = _lock_name_of_text(part, module)
                if n:
                    extra.append(n)
        walk = _FuncWalk(sf, module, known, entry)
        for stmt in func.body:
            walk.visit(stmt)
        functions.append({
            "name": func.name,
            "line": func.lineno,
            "entry": entry,
            "extra": extra,
            "acquires": [[n, ln] for n, ln in walk.acquires],
            "nested": [[h, n, ln] for h, n, ln in walk.nested],
            "calls": [
                [callee, base, ln, list(held)]
                for callee, base, ln, held in walk.calls
            ],
        })
    return {
        "module": module,
        "path": sf.path,
        "project": _is_project_path(sf.path),
        "locks": locks,
        "functions": functions,
    }


# -- per-file checks ---------------------------------------------------------

def _annotation_lock_names(sf: SourceFile, module: str) -> Set[str]:
    out: Set[str] = set()
    for table in (sf.guarded, sf.holds):
        for text in table.values():
            n = _lock_name_of_text(text, module)
            if n:
                out.add(n)
    return out


def _guarded_keys_for(sf: SourceFile, module: str,
                      lock: str) -> Set[Tuple[str, str]]:
    """('global'|'attr', name) state keys annotated guarded-by `lock`."""
    keys: Set[Tuple[str, str]] = set()
    for stmt, text in sf.guarded_targets():
        if _lock_name_of_text(text, module) != lock:
            continue
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name):
                keys.add(("global", t.id))
            elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                keys.add(("attr", t.attr))
    return keys


def _reads_of(expr: ast.AST, keys: Set[Tuple[str, str]]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and ("global", node.id) in keys:
            return True
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
                and node.value.id == "self" and ("attr", node.attr) in keys:
            return True
    return False


def _written_key(target: ast.AST) -> Optional[Tuple[str, str]]:
    t = target
    while isinstance(t, ast.Subscript):
        t = t.value
    if isinstance(t, ast.Name):
        return ("global", t.id)
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return ("attr", t.attr)
    return None


def _atomicity_findings(sf: SourceFile, module: str,
                        known: Set[str],
                        keys_override: Optional[Set[Tuple[str, str]]] = None,
                        rule: str = RULE) -> List[Finding]:
    """Check-then-act across a release: block A reads guarded state into
    locals, the lock is released, block B (same function, same lock)
    writes guarded state from those locals without re-reading it.

    `keys_override` swaps the guarded-by-derived state keys for an
    explicit set — rules_durability reuses this sweep over the durable
    attribute set (ISSUE 18), reporting under its own `rule`."""
    findings: List[Finding] = []
    for func, _cls in iter_functions(sf.tree):
        # with-blocks per lock, in source order, top-level walk of this
        # function only (nested defs handled as their own functions)
        blocks: Dict[str, List[ast.With]] = {}
        stack = list(func.body)
        while stack:
            node = stack.pop(0)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    name = _lock_name_of_expr(item.context_expr, module, known)
                    if name is not None:
                        blocks.setdefault(name, []).append(node)
            stack.extend(ast.iter_child_nodes(node))
        for lock, withs in blocks.items():
            if len(withs) < 2:
                continue
            keys = keys_override if keys_override is not None \
                else _guarded_keys_for(sf, module, lock)
            if not keys:
                continue
            withs.sort(key=lambda w: w.lineno)

            def covering(lineno: int) -> Optional[ast.With]:
                for w in withs:
                    if w.lineno <= lineno <= (w.end_lineno or w.lineno):
                        return w
                return None

            # ONE flow-ordered sweep over the function's assignments:
            # reading guarded state inside a with-block taints the target
            # locals (remembering WHICH block); a reassignment from fresh
            # (unguarded, untainted) data KILLS the taint — `x = walk_disk()`
            # between the blocks means the later write is not stale.
            assigns = sorted(
                (n for n in ast.walk(func)
                 if isinstance(n, (ast.Assign, ast.AugAssign))
                 and n.value is not None),
                key=lambda n: (n.lineno, n.col_offset),
            )
            tainted: Dict[str, ast.With] = {}  # local -> source block
            for node in assigns:
                here = covering(node.lineno)
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                value = node.value
                taint_sources = {
                    n.id for n in ast.walk(value)
                    if isinstance(n, ast.Name) and n.id in tainted
                }
                reads_guarded = _reads_of(value, keys)
                # the check itself: a guarded-state write inside a LATER
                # with-block from a local tainted by an EARLIER one
                if here is not None:
                    for t in targets:
                        key = _written_key(t)
                        if key is None or key not in keys:
                            continue
                        stale = {
                            n for n in taint_sources
                            if tainted[n] is not here
                        }
                        if not stale:
                            continue
                        # double-checked idiom: this block re-reads the
                        # state it writes before writing
                        reread = any(
                            _reads_of(n, {key})
                            for n in ast.walk(here)
                            if isinstance(n, (ast.Assign, ast.If))
                            and n.lineno < node.lineno
                        )
                        if reread:
                            continue
                        if node.lineno in sf.atomicity_ok or \
                                here.lineno in sf.atomicity_ok:
                            continue
                        src_w = tainted[next(iter(stale))]
                        shown = key[1] if key[0] == "global" else f"self.{key[1]}"
                        findings.append(Finding(
                            rule, sf.path, node.lineno, node.col_offset,
                            f"check-then-act across a release of '{lock}': "
                            f"'{shown}' is written from state read under an "
                            f"EARLIER `with` (line {src_w.lineno}) — the "
                            "lock was released in between, so the read may "
                            "be stale. Re-read under this acquisition or "
                            "annotate `# atomicity-ok: <reason>`",
                        ))
                # taint propagation / kill, in flow order
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if reads_guarded and here is not None:
                        tainted[t.id] = here
                    elif taint_sources:
                        # derived from a tainted local: inherit its block
                        tainted[t.id] = tainted[next(iter(taint_sources))]
                    else:
                        tainted.pop(t.id, None)  # fresh reassignment kills
    return findings


@register(RULE)
def check(sf: SourceFile) -> List[Finding]:
    module = module_of(sf.path)
    creations = _creations(sf)
    known = {c.attr for c in creations}
    findings: List[Finding] = []
    project = _is_project_path(sf.path)
    annotated = _annotation_lock_names(sf, module)
    is_locks_module = sf.path.replace("\\", "/").endswith(
        "ballista_tpu/utils/locks.py"
    )
    for c in creations:
        derived = canonical(f"{module}.{c.attr}")
        if project and c.raw and not is_locks_module:
            findings.append(Finding(
                RULE, sf.path, c.line, 0,
                f"raw threading.{'RLock' if c.kind == 'rlock' else 'Lock'}() "
                f"bound to '{c.attr}' — create project locks via "
                f"utils.locks.make_{'r' if c.kind == 'rlock' else ''}lock("
                f"{derived!r}) so the lock witness can wrap them",
            ))
        if c.literal is not None and c.literal != derived:
            findings.append(Finding(
                RULE, sf.path, c.line, 0,
                f"lock name {c.literal!r} does not match its canonical "
                f"identity {derived!r} (module.attr; aliases: {ALIASES}) — "
                "the static graph and the runtime witness must agree",
            ))
        if project and derived not in annotated and not is_locks_module:
            findings.append(Finding(
                RULE, sf.path, c.line, 0,
                f"lock '{c.attr}' has no guarded-by:/holds-lock: "
                "annotation in this file — annotate the state it guards "
                "(annotation-coverage meta-check, ISSUE 14)",
            ))
    findings.extend(_atomicity_findings(sf, module, known))
    return findings


# -- whole-program pass ------------------------------------------------------

def _resolve_calls(facts_by_path: Dict[str, dict]):
    """(lock kinds, per-function records with resolved callees).

    Resolution: same module by bare name first; else a dotted-base segment
    matching a module's last component (`self.kv.put` -> scheduler.kv,
    `costmodel.predict` -> ops.costmodel); else unique-ish bare name among
    lock-acquiring functions everywhere (bounded union — dynamic dispatch
    the name can't disambiguate is the witness's job, or a
    `# may-acquire:` annotation's)."""
    kinds: Dict[str, str] = {}
    by_module: Dict[str, Dict[str, List[dict]]] = {}
    last_comp: Dict[str, List[str]] = {}
    for facts in facts_by_path.values():
        if not facts:
            continue
        for name, info in facts.get("locks", {}).items():
            prev = kinds.get(name)
            kinds[name] = "rlock" if "rlock" in (prev, info["kind"]) else \
                info["kind"]
        mod = facts["module"]
        table = by_module.setdefault(mod, {})
        for f in facts.get("functions", ()):
            table.setdefault(f["name"], []).append(f)
        last_comp.setdefault(mod.split(".")[-1], []).append(mod)

    # seed may_acquire with direct acquisitions + annotations (group:NAME
    # tokens expand against the manifest's [groups] table)
    groups = Manifest.load().groups
    ma: Dict[int, Set[str]] = {}
    extras: Dict[int, Set[str]] = {}
    recs: List[Tuple[str, str, dict]] = []  # (module, path, frec)
    for path, facts in facts_by_path.items():
        if not facts:
            continue
        for f in facts.get("functions", ()):
            extra: Set[str] = set()
            for e in f["extra"]:
                if e.startswith("group:"):
                    extra |= set(groups.get(e[len("group:"):], ()))
                else:
                    extra.add(e)
            extras[id(f)] = extra
            ma[id(f)] = {n for n, _ln in f["acquires"]} | extra
            recs.append((facts["module"], facts["path"], f))

    def candidates(mod: str, callee: str, base: str) -> List[dict]:
        segs = [s.lstrip("_") for s in base.split(".") if s
                and s not in ("self", "cls")]
        if not segs:
            # bare name (imported function) or self-method: same module
            # first, else unique-ish among ACQUIRING functions anywhere
            local = by_module.get(mod, {}).get(callee)
            if local:
                return local
            hits = []
            for m, table in by_module.items():
                for g in table.get(callee, ()):
                    if ma[id(g)]:
                        hits.append(g)
            return hits if len(hits) <= 8 else []
        # attribute call: only a dotted-base segment naming a module can
        # resolve it (`self.kv.put` -> scheduler.kv, `costmodel.predict` ->
        # ops.costmodel). Anything else (`self._cache.get`, `q.put`) is a
        # collection/foreign method — resolving those by bare name painted
        # phantom kv.get edges under every counter lock. Dynamic dispatch a
        # base can't name (plan.execute, callbacks) is what the
        # `# may-acquire:` annotation and the runtime witness are for.
        for seg in segs:
            for m in last_comp.get(seg, ()):
                hit = by_module.get(m, {}).get(callee)
                if hit:
                    return hit
        return []

    resolved: Dict[int, List[List[dict]]] = {}
    for mod, _path, f in recs:
        resolved[id(f)] = [
            candidates(mod, callee, base)
            for callee, base, _ln, _held in f["calls"]
        ]
    # fixpoint: fold callee acquisitions upward until stable
    for _ in range(len(recs) + 2):
        changed = False
        for _mod, _path, f in recs:
            mine = ma[id(f)]
            before = len(mine)
            for cands in resolved[id(f)]:
                for g in cands:
                    mine |= ma[id(g)]
            if len(mine) != before:
                changed = True
        if not changed:
            break
    return kinds, recs, resolved, ma, extras


def build_graph(facts_by_path: Dict[str, dict]) -> Tuple[LockGraph, Dict[str, str]]:
    """The whole-program acquired-while-held graph from per-file facts."""
    kinds, recs, resolved, ma, extras = _resolve_calls(facts_by_path)
    graph = LockGraph()

    def reentrant_self(name: str) -> bool:
        return kinds.get(name) == "rlock"

    for _mod, path, f in recs:
        for h, n, ln in f["nested"]:
            if h == n and reentrant_self(n):
                continue
            graph.add(EdgeSite(h, n, path, ln, f["name"], ""))
        # a `# may-acquire:` annotation describes dynamic work inside THIS
        # function's body: it contributes edges from every lock the
        # function itself holds (its own acquisitions + its holds-lock
        # entry context), not just from its call sites
        held_here = {n for n, _ln in f["acquires"]}
        if f["entry"]:
            held_here.add(f["entry"])
        for h in held_here:
            for l in extras.get(id(f), ()):
                if h == l and reentrant_self(l):
                    continue
                graph.add(EdgeSite(h, l, path, f["line"], f["name"],
                                   "may-acquire"))
        for (callee, _base, ln, held), cands in zip(f["calls"],
                                                    resolved[id(f)]):
            if not held or not cands:
                continue
            acq: Set[str] = set()
            for g in cands:
                acq |= ma[id(g)]
            for h in held:
                for l in acq:
                    if reentrant_self(l) and l in held:
                        # the callee re-enters a reentrant lock this
                        # scope already holds (kv.lock -> counter lock ->
                        # kv.get): a re-entry cannot block, so it is not
                        # an ordering edge against ANY held lock
                        continue
                    graph.add(EdgeSite(h, l, path, ln, f["name"],
                                       f"{callee}()"))
    return graph, kinds


@register_global(RULE)
def global_check(facts_by_path: Dict[str, dict]) -> List[Finding]:
    # facts_by_path maps display path -> {rule name -> facts}; unwrap ours
    unwrapped = {
        p: f.get(RULE, {}) if isinstance(f, dict) else {}
        for p, f in facts_by_path.items()
    }
    graph, _kinds = build_graph(unwrapped)
    manifest = Manifest.load()
    findings: List[Finding] = []
    for (src, dst) in sorted(graph.edge_set()):
        complaint = manifest.check_edge(src, dst)
        if complaint is not None:
            site = graph.site(src, dst)
            findings.append(Finding(
                RULE, site.path, site.line, 0,
                complaint + f" [{site.describe()}]",
            ))
    # cycle detection over the graph MINUS plan-tree pairs (structurally
    # ordered per instance — a class-level cycle there is not a deadlock)
    cycle_graph = LockGraph()
    for (src, dst), sites in graph.edges.items():
        if not manifest.plan_pair(src, dst):
            cycle_graph.add(sites[0])
    for cycle in cycle_graph.cycles():
        if len(cycle) == 2 and cycle[0] == cycle[1]:
            continue  # self-edges already reported via check_edge
        anchor = cycle_graph.site(cycle[0], cycle[1])
        findings.append(Finding(
            RULE, anchor.path if anchor else "<graph>",
            anchor.line if anchor else 0, 0,
            "potential deadlock: lock-order cycle "
            + " -> ".join(cycle) + "\n" + cycle_graph.cycle_report(cycle),
        ))
    return findings


def static_edges(paths: List[str], use_cache: bool = True,
                 cache_path: Optional[str] = None) -> Set[Tuple[str, str]]:
    """The statically derived edge set for --check-witness (honors the
    CLI's cache flags)."""
    from dev.analysis.core import collect_facts

    facts = collect_facts(paths, use_cache=use_cache, cache_path=cache_path)
    unwrapped = {p: f.get(RULE, {}) for p, f in facts.items()}
    graph, _kinds = build_graph(unwrapped)
    return graph.edge_set()

"""durability: replica-coherence classification of scheduler state
(ISSUE 18).

The multi-scheduler direction (ROADMAP round 8) needs every piece of
``SchedulerState`` to be provably durable, derivable, or deliberately
replica-local. Each attribute assigned on a manifest-owned class
(``SchedulerState``, the KV-adjacent caches in scheduler/server.py) must
carry a classification annotation::

    # durability: durable(<kv-prefix>) | derived(<rebuild-fn>) | ephemeral(<reason>)

dev/analysis/durability.toml is the authoritative table (owners, the
attr classification rows, attempt-guard policy, ephemeral budgets).

**Per-file checks** (cached like every rule):

- *coverage & agreement*: every ``self.X = ...`` attribute of a
  participating class has at least one annotated assignment site, the
  annotation's argument parses (durable needs a prefix token, derived an
  identifier, ephemeral a reason), and owner-class annotations agree
  with the manifest's [attrs] rows.
- *durable write-through*: every mutation site of a durable attribute
  (attribute rebind outside __init__, item write/del, aug-assign, or a
  mutating method call) must have a KV operation against the declared
  prefix reachable in the same function scope — directly or through
  same-file callees (the ``_ledger_put``/``_spec_del`` helper idiom).
  The PR 14 atomicity sweep is reused over the durable key set, so
  check-then-act across a kv-lock release on durable state is flagged.
- *attempt-guard discipline*: a function folding a ``TaskStatus`` into
  durable state (calls ``save_task_status``) must be a guard, call one,
  be reviewed in the manifest, or carry ``# attempt-guard-ok: <reason>``
  (the PR 6 stale-echo lesson, machine-checked).

**Whole-program pass** (``register_global``): every derived(<fn>)
rebuild must be reachable from the owner's recover() in the static call
graph (the lockgraph cross-module resolver is reused — a read-through
cache that recovery forgets is a lint error, not a restart surprise);
per-module ephemeral counts stay within [budgets]; and [attrs] rows for
analyzed owner modules must still exist in source (stale-row check).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

try:  # py3.11+
    import tomllib as _toml
except ImportError:  # pragma: no cover - py3.10 fallback (PR 2 idiom)
    import tomli as _toml  # type: ignore

from dev.analysis.common import dotted, final_name, iter_functions, \
    walk_no_nested_defs
from dev.analysis.core import Finding, SourceFile, durability_manifest_path, \
    register, register_facts, register_global
from dev.analysis.lockgraph import module_of
from dev.analysis.rules_lockorder import _atomicity_findings, _resolve_calls

RULE = "durability"

# mutating container methods: calling one on a durable attribute is a
# mutation site that needs a paired KV operation
_MUTATORS = {
    "add", "append", "clear", "discard", "extend", "insert", "pop",
    "popitem", "remove", "setdefault", "update",
}
# KV operations that synchronize in-memory durable state with the store:
# the writes (write-through) and the prefix reads (rebuild-from-KV, the
# recover() direction)
_KV_OPS = {"put", "put_all", "delete", "delete_prefix", "get", "get_prefix"}
# the function that folds an executor-reported TaskStatus into KV state
_FOLD_FN = "save_task_status"

_VALUE_RE = re.compile(r"^(durable|derived|ephemeral)(?:\(\s*(.*?)\s*\))?$")
_IDENT_RE = re.compile(r"^[A-Za-z_]\w*$")
_PREFIX_RE = re.compile(r"^[A-Za-z_][\w-]*$")


def _manifest() -> dict:
    try:
        with open(durability_manifest_path(), "rb") as f:
            return _toml.load(f)
    except (OSError, ValueError):
        return {}


def _owner_for(man: dict, module: str, cls: str) -> Optional[dict]:
    for o in man.get("owners", ()):
        if o.get("module") == module and o.get("class") == cls:
            return o
    return None


def _owner_modules(man: dict) -> Set[str]:
    return {o.get("module", "") for o in man.get("owners", ())}


# -- class / attribute scan ---------------------------------------------------

def _self_attr_of(expr: ast.AST) -> Optional[str]:
    """`self.X`, `self.X[k]`, `self.X[k][j]` -> X; else None."""
    t = expr
    while isinstance(t, ast.Subscript):
        t = t.value
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return t.attr
    return None


def _scan_classes(sf: SourceFile) -> Dict[str, dict]:
    """class name -> {"assigned": {attr: first bind line},
    "annotated": {attr: (class, arg, line)}, "conflicts": [...]} from
    every `self.X = ...` bind in the class's methods."""
    out: Dict[str, dict] = {}
    for func, cls in iter_functions(sf.tree):
        if cls is None:
            continue
        info = out.setdefault(
            cls.name, {"assigned": {}, "annotated": {}, "conflicts": []}
        )
        for node in walk_no_nested_defs(func):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue  # plain binds only; item writes are mutations
                attr = t.attr
                first = info["assigned"].get(attr)
                if first is None or node.lineno < first:
                    info["assigned"][attr] = node.lineno
                ann = sf.durability.get(node.lineno)
                if ann is None:
                    continue
                prev = info["annotated"].get(attr)
                if prev is None:
                    info["annotated"][attr] = (ann[0], ann[1], node.lineno)
                elif (prev[0], prev[1]) != ann:
                    info["conflicts"].append((attr, node.lineno, ann, prev))
    return out


# -- durable write-through ---------------------------------------------------

def _prefix_in_expr(expr: ast.AST, helpers: Dict[str, str],
                    locals_p: Dict[str, str]) -> Optional[str]:
    """KV prefix an expression references: a `self._key("<prefix>", ...)`
    call, a call to a key-building helper, or a local bound from one."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = final_name(node.func)
            if name == "_key" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                return node.args[0].value
            if name in helpers:
                return helpers[name]
        elif isinstance(node, ast.Name) and node.id in locals_p:
            return locals_p[node.id]
    return None


def _helper_prefixes(sf: SourceFile) -> Dict[str, str]:
    """Key-building helpers: functions returning `self._key("<p>", ...)`
    (`_ledger_key` -> assignments, `_spec_key` -> speculation)."""
    out: Dict[str, str] = {}
    for func, _cls in iter_functions(sf.tree):
        for node in walk_no_nested_defs(func):
            if isinstance(node, ast.Return) and node.value is not None:
                p = _prefix_in_expr(node.value, {}, {})
                if p is not None:
                    out[func.name] = p
    return out


def _kv_prefixes(func: ast.AST, helpers: Dict[str, str]) -> Set[str]:
    """Prefixes this function touches with a KV op (kv.put/get/...) —
    after resolving locals bound from key-building expressions."""
    locals_p: Dict[str, str] = {}
    for node in walk_no_nested_defs(func):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            p = _prefix_in_expr(node.value, helpers, {})
            if p is not None:
                locals_p[node.targets[0].id] = p
    out: Set[str] = set()
    for node in walk_no_nested_defs(func):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in _KV_OPS:
            continue
        base = dotted(node.func.value)
        if not base or base.split(".")[-1] != "kv":
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            p = _prefix_in_expr(arg, helpers, locals_p)
            if p is not None:
                out.add(p)
    return out


def _closure_prefixes(sf: SourceFile) -> Dict[int, Set[str]]:
    """id(func) -> KV prefixes reachable from it through same-file calls
    (bare-name / self-method resolution, the lockgraph convention)."""
    helpers = _helper_prefixes(sf)
    funcs = [f for f, _c in iter_functions(sf.tree)]
    by_name: Dict[str, List[ast.AST]] = {}
    for f in funcs:
        by_name.setdefault(f.name, []).append(f)
    reach = {id(f): _kv_prefixes(f, helpers) for f in funcs}
    calls: Dict[int, Set[str]] = {}
    for f in funcs:
        names: Set[str] = set()
        for node in walk_no_nested_defs(f):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name):
                names.add(node.func.id)
            elif isinstance(node.func, ast.Attribute):
                base = dotted(node.func.value)
                if base in ("self", "cls"):
                    names.add(node.func.attr)
        calls[id(f)] = names
    for _ in range(len(funcs) + 2):
        changed = False
        for f in funcs:
            mine = reach[id(f)]
            before = len(mine)
            for name in calls[id(f)]:
                for g in by_name.get(name, ()):
                    mine |= reach[id(g)]
            if len(mine) != before:
                changed = True
        if not changed:
            break
    return reach


def _writethrough_findings(sf: SourceFile,
                           durable: Dict[str, Dict[str, str]]) -> List[Finding]:
    """Every mutation site of a durable attribute must have a KV op
    against its declared prefix reachable in the same function scope.
    `durable`: class name -> {attr: prefix}."""
    findings: List[Finding] = []
    reach = _closure_prefixes(sf)

    def mutated_attrs(node: ast.AST) -> List[Tuple[str, int]]:
        hits: List[Tuple[str, int]] = []
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _self_attr_of(t)
                if attr is not None:
                    hits.append((attr, node.lineno))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr_of(t)
                if attr is not None:
                    hits.append((attr, node.lineno))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            attr = _self_attr_of(node.func.value)
            if attr is not None:
                hits.append((attr, node.lineno))
        return hits

    for func, cls in iter_functions(sf.tree):
        if cls is None or cls.name not in durable:
            continue
        if func.name == "__init__":
            continue  # initialization of empty containers, not mutation
        attrs = durable[cls.name]
        for node in walk_no_nested_defs(func):
            for attr, line in mutated_attrs(node):
                prefix = attrs.get(attr)
                if prefix is None or prefix in reach[id(func)]:
                    continue
                findings.append(Finding(
                    RULE, sf.path, line, 0,
                    f"mutation of durable attribute 'self.{attr}' "
                    f"(durable({prefix})) in '{func.name}' has no KV "
                    f"operation against prefix '{prefix}' reachable in "
                    "the same function scope — pair it with kv.put/"
                    f"put_all/delete via self._key({prefix!r}, ...) "
                    "(directly or through a same-file helper), or "
                    "reclassify the attribute",
                ))
    return findings


# -- attempt-guard discipline ------------------------------------------------

def _attempt_guard_findings(sf: SourceFile, module: str,
                            man: dict) -> List[Finding]:
    ag = man.get("attempt_guard", {})
    guards = set(ag.get("guards", ()))
    reviewed = dict(ag.get("reviewed", {}))
    if not guards:
        return []
    if module not in _owner_modules(man) and not sf.durability:
        return []  # only files participating in the durability contract
    findings: List[Finding] = []
    for func, _cls in iter_functions(sf.tree):
        called = {
            final_name(n.func)
            for n in walk_no_nested_defs(func) if isinstance(n, ast.Call)
        }
        if _FOLD_FN not in called or func.name == _FOLD_FN:
            continue
        if func.name in guards or called & guards:
            continue
        if func.name in reviewed or sf.attempt_ok_of(func):
            continue
        findings.append(Finding(
            RULE, sf.path, func.lineno, 0,
            f"'{func.name}' folds a TaskStatus into durable state "
            f"(calls {_FOLD_FN}) without consulting the attempt/ledger "
            f"guard ({sorted(guards)}) — call a guard, list the function "
            "under [attempt_guard.reviewed] in durability.toml with a "
            "reason, or annotate the def `# attempt-guard-ok: <reason>`",
        ))
    return findings


# -- per-file check ----------------------------------------------------------

@register(RULE)
def check(sf: SourceFile) -> List[Finding]:
    module = module_of(sf.path)
    man = _manifest()
    classes = _scan_classes(sf)
    findings: List[Finding] = []
    consumed: Set[int] = set()
    durable: Dict[str, Dict[str, str]] = {}
    for cls_name in sorted(classes):
        info = classes[cls_name]
        owner = _owner_for(man, module, cls_name)
        if owner is None and not info["annotated"]:
            continue  # class does not participate in the contract
        for attr, line in sorted(info["assigned"].items(),
                                 key=lambda kv: (kv[1], kv[0])):
            if attr not in info["annotated"]:
                findings.append(Finding(
                    RULE, sf.path, line, 0,
                    f"attribute 'self.{attr}' of {cls_name} has no "
                    "`# durability:` annotation on any assignment site — "
                    "classify it durable(<kv-prefix>), "
                    "derived(<rebuild-fn>), or ephemeral(<reason>)",
                ))
        for attr, lineno, ann, prev in info["conflicts"]:
            consumed.add(lineno)  # conflicting, not dangling
            findings.append(Finding(
                RULE, sf.path, lineno, 0,
                f"conflicting durability classification for "
                f"'{cls_name}.{attr}': {ann[0]}({ann[1]}) here vs "
                f"{prev[0]}({prev[1]}) at line {prev[2]}",
            ))
        for attr in sorted(info["annotated"]):
            dclass, arg, line = info["annotated"][attr]
            consumed.add(line)
            if dclass == "durable" and not _PREFIX_RE.match(arg):
                findings.append(Finding(
                    RULE, sf.path, line, 0,
                    f"durable({arg!r}) on '{cls_name}.{attr}' needs a KV "
                    "prefix token (the first self._key(...) segment), "
                    "e.g. durable(assignments)",
                ))
            elif dclass == "derived" and not _IDENT_RE.match(arg):
                findings.append(Finding(
                    RULE, sf.path, line, 0,
                    f"derived({arg!r}) on '{cls_name}.{attr}' needs the "
                    "rebuild function's name, e.g. "
                    "derived(_ensure_task_index)",
                ))
            elif dclass == "ephemeral" and not arg:
                findings.append(Finding(
                    RULE, sf.path, line, 0,
                    f"ephemeral() on '{cls_name}.{attr}' needs a reason — "
                    "why is it correct for a scheduler replica to lose "
                    "this on restart?",
                ))
            if dclass == "durable" and _PREFIX_RE.match(arg):
                durable.setdefault(cls_name, {})[attr] = arg
            if owner is not None:
                key = f"{module}.{cls_name}.{attr}"
                row = man.get("attrs", {}).get(key)
                m = _VALUE_RE.match(row.strip()) if isinstance(row, str) \
                    else None
                if row is None:
                    findings.append(Finding(
                        RULE, sf.path, line, 0,
                        f"'{key}' is annotated {dclass}({arg}) but has no "
                        "[attrs] row in durability.toml — the manifest is "
                        "the reviewed classification table; add the row",
                    ))
                elif m is None or m.group(1) != dclass or (
                    dclass in ("durable", "derived")
                    and (m.group(2) or "") != arg
                ):
                    findings.append(Finding(
                        RULE, sf.path, line, 0,
                        f"'{key}' is annotated {dclass}({arg}) but "
                        f"durability.toml [attrs] says {row!r} — source "
                        "and manifest must agree",
                    ))
    for line in sorted(set(sf.durability) - consumed):
        dclass, arg = sf.durability[line]
        findings.append(Finding(
            RULE, sf.path, line, 0,
            f"dangling `# durability: {dclass}({arg})` annotation: no "
            "`self.<attr> = ...` bind on this line — attach it to an "
            "assignment site (inline, or standalone directly above)",
        ))
    if durable:
        durable_keys = {
            ("attr", attr) for attrs in durable.values() for attr in attrs
        }
        findings.extend(_atomicity_findings(
            sf, module, set(), keys_override=durable_keys, rule=RULE,
        ))
        findings.extend(_writethrough_findings(sf, durable))
    findings.extend(_attempt_guard_findings(sf, module, man))
    return findings


# -- facts for the whole-program pass ----------------------------------------

@register_facts(RULE)
def extract_facts(sf: SourceFile) -> dict:
    module = module_of(sf.path)
    classes = _scan_classes(sf)
    out_classes: Dict[str, dict] = {}
    ephemeral = 0
    derived: List[list] = []
    for cls_name in sorted(classes):
        annotated = classes[cls_name]["annotated"]
        if not annotated:
            continue
        table = {}
        for attr in sorted(annotated):
            dclass, arg, line = annotated[attr]
            table[attr] = [dclass, arg, line]
            if dclass == "ephemeral":
                ephemeral += 1
            elif dclass == "derived":
                derived.append([cls_name, attr, arg, line])
        out_classes[cls_name] = table
    return {
        "module": module,
        "path": sf.path,
        "project": sf.path.replace("\\", "/").startswith("ballista_tpu/"),
        "classes": out_classes,
        "ephemeral": ephemeral,
        "derived": derived,
    }


# -- whole-program pass ------------------------------------------------------

@register_global(RULE)
def global_check(facts_by_path: Dict[str, dict]) -> List[Finding]:
    man = _manifest()
    dur = {
        p: (f.get(RULE, {}) if isinstance(f, dict) else {})
        for p, f in facts_by_path.items()
    }
    findings: List[Finding] = []

    budgets = man.get("budgets", {})
    default_budget = int(budgets.get("default", 0))
    modules_present: Set[str] = set()
    observed: Set[str] = set()
    derived_decls: List[Tuple[str, str, str, str, str, int]] = []
    for f in dur.values():
        if not f or not f.get("project"):
            continue
        modules_present.add(f["module"])
        count = f.get("ephemeral", 0)
        if count:
            budget = int(budgets.get(f["module"], default_budget))
            if count > budget:
                findings.append(Finding(
                    RULE, f["path"], 1, 0,
                    f"module '{f['module']}' declares {count} ephemeral "
                    f"attributes, over its budget of {budget} — ephemeral "
                    "growth is a reviewed decision: raise the [budgets] "
                    "entry in durability.toml or make the state "
                    "durable/derived",
                ))
        for cls, table in f.get("classes", {}).items():
            for attr in table:
                observed.add(f"{f['module']}.{cls}.{attr}")
        for cls, attr, fn, line in f.get("derived", ()):
            derived_decls.append((f["module"], f["path"], cls, attr, fn, line))

    if derived_decls:
        lock = {
            p: (f.get("lock-order", {}) if isinstance(f, dict) else {})
            for p, f in facts_by_path.items()
        }
        _kinds, recs, resolved, _ma, _extras = _resolve_calls(lock)
        by_module: Dict[str, List[dict]] = {}
        for mod, _path, frec in recs:
            by_module.setdefault(mod, []).append(frec)
        cache: Dict[Tuple[str, str], Optional[Set[str]]] = {}

        def reachable_names(module: str, entry: str) -> Optional[Set[str]]:
            """Function names reachable from `module.entry` (any module),
            or None when no such entry function exists."""
            key = (module, entry)
            if key in cache:
                return cache[key]
            seeds = [f for f in by_module.get(module, ())
                     if f["name"] == entry]
            if not seeds:
                cache[key] = None
                return None
            seen: Set[int] = {id(f) for f in seeds}
            names: Set[str] = {f["name"] for f in seeds}
            work = list(seeds)
            while work:
                frec = work.pop()
                for cands in resolved.get(id(frec), ()):
                    for g in cands:
                        if id(g) in seen:
                            continue
                        seen.add(id(g))
                        names.add(g["name"])
                        work.append(g)
            cache[key] = names
            return names

        for module, path, cls, attr, fn, line in sorted(derived_decls):
            owner = _owner_for(man, module, cls)
            entry = owner.get("recover", "") if owner is not None \
                else "recover"
            if not entry:
                findings.append(Finding(
                    RULE, path, line, 0,
                    f"'{cls}.{attr}' is derived({fn}) but its owner entry "
                    "in durability.toml declares no `recover` function — "
                    "a derived classification needs a recovery entry "
                    "point to validate against",
                ))
                continue
            names = reachable_names(module, entry)
            if names is None:
                findings.append(Finding(
                    RULE, path, line, 0,
                    f"'{cls}.{attr}' is derived({fn}) but no '{entry}' "
                    f"function exists in module '{module}' to rebuild it "
                    "from",
                ))
            elif fn not in names:
                findings.append(Finding(
                    RULE, path, line, 0,
                    f"derived rebuild '{fn}' for '{cls}.{attr}' is NOT "
                    f"reachable from {module}.{entry}() in the static "
                    "call graph — a restarted replica would never rebuild "
                    f"it. Call {fn}() from recovery (directly or "
                    "transitively), or reclassify the attribute",
                ))

    for key in sorted(man.get("attrs", {})):
        mod = key.rsplit(".", 2)[0]
        if mod in modules_present and key not in observed:
            path = next(
                (f["path"] for f in dur.values()
                 if f and f.get("module") == mod), mod,
            )
            findings.append(Finding(
                RULE, path, 1, 0,
                f"stale durability.toml [attrs] row '{key}': no such "
                "annotated attribute in source — remove the row or "
                "restore the annotation",
            ))
    return findings

"""ballista-lint: AST-based invariant checker for the Ballista-TPU tree.

The device path's correctness story rests on conventions the compiler
cannot see; this package turns them into machine-checked gates
(`python -m dev.analysis ballista_tpu/`):

- **readback-discipline** — every device->host materialization of a
  compiled-program result inside `ballista_tpu/ops/` or
  `ballista_tpu/parallel/` must pair with `record_readback` (or the
  `readback` helper) in the same function, or bench.py's readback_rows/
  readback_bytes undercount and the O(limit)-readback claim is unmeasured.
- **tracer-hygiene** — code reached from a jit/shard_map/pallas decoration
  site must never branch (`if`/`while`) on, or host-materialize
  (`bool()`/`int()`/`float()`/`.item()`), a value derived from `jnp.*`/
  `jax.lax.*` calls: those are tracers during compilation.
- **dtype-discipline** — float64 must not reach traced code or flow into a
  device transfer (`jnp.asarray`/`jax.device_put`); the f64->f32 narrowing
  policy (ops/runtime.py module docstring) holds everywhere except
  ops/floatbits.py's deliberate order-preserving bijections. Host-side
  post-readback widening to f64 is the documented result dtype and is not
  flagged.
- **guarded-by** — state registered with a `# guarded-by: <lock>` comment
  may only be touched inside `with <lock>:` (or in a function annotated
  `# holds-lock: <lock>`, whose callers are checked instead). File-scoped
  by design: analysis is per-file so caching stays sound.
- **decline-discipline** — device paths bail to host only through the
  canonical signals: `raise UnsupportedOnDevice("<reason>")` (a reason is
  mandatory) or the `ops/kernels.py` helpers `decline`/`host_fallback`;
  an `except UnsupportedOnDevice` handler must not silently `return None`,
  and ad-hoc `Exception`/`RuntimeError`/`NotImplementedError` raises are
  not decline channels.
- **routing-discipline** / **failure-discipline** (`rules_routing.py`,
  `rules_failure.py`) — tier-routing and retry/requeue conventions; see
  their module docstrings.
- **lock-order** (`rules_lockorder.py` + `lockgraph.py` +
  `lockorder.toml`) — whole-program acquired-while-held graph, deadlock
  cycles, manifest-declared ordering, the check-then-act atomicity
  sub-check, and the `--check-witness` runtime cross-check (repeatable:
  per-process `<OUT>.<pid>` dumps from forked CI workers are merged).
- **durability** (`rules_durability.py` + `durability.toml`) — every
  attribute on SchedulerState/SchedulerServer/_PushSubscriber must carry
  `# durability: durable(<kv-prefix>) | derived(<rebuild-fn>) |
  ephemeral(<reason>)` agreeing with the reviewed manifest; durable
  mutations must pair with a same-scope KV op against the declared
  prefix, derived rebuilds must be reachable from `recover()`, ephemeral
  counts are budgeted per class, and `save_task_status` callers must
  consult the attempt/ledger guard (or carry `# attempt-guard-ok:`).

Suppression syntax (a reason is mandatory, checked by the always-on
`lint-usage` meta rule):

    something_flagged()  # ballista-lint: disable=<rule> -- <reason>

A standalone suppression comment covers the following line. Fixture files
under tests/ can opt into device-path scoping with a header comment
`# ballista-lint: path=ballista_tpu/ops/<virtual>.py`.

Zero third-party dependencies (stdlib ast/tokenize only); per-file result
caching keyed on (mtime, size, analyzer hash) in .ballista_lint_cache.json.
"""

from dev.analysis.core import RULE_NAMES, analyze_file, run_paths  # noqa: F401

"""CLI: python -m dev.analysis [paths...] [--json] [--no-cache] [--list-rules]

Exit codes: 0 clean, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from dev.analysis.core import RULE_NAMES, run_paths

SUPPRESSION_BUDGET = 5  # package-wide cap (ISSUE 3 acceptance criteria)


def check_witness(witness_paths, paths, as_json: bool = False,
                  use_cache: bool = True, cache_path=None) -> int:
    """--check-witness: runtime-vs-static lock-order cross-check (ISSUE 14).

    Accepts the flag repeatedly (ISSUE 18 satellite): witness CI lanes
    fork worker processes that each dump their own <OUT>.<pid> record, and
    the edge sets are MERGED (union of edges with summed counts, violations
    concatenated) before the diff — an edge witnessed in any process
    counts, a declared edge is stale only if NO process saw it.

    Exit 1 when the merged witness recorded edges the static analyzer
    never derived (analyzer bugs / missing may-acquire annotations) or
    recorded order violations; stale declared edges only warn."""
    from dev.analysis.lockgraph import Manifest, diff_witness, load_witness
    from dev.analysis.rules_lockorder import static_edges

    witness = {"edges": [], "violations": []}
    seen = {}
    for wp in witness_paths:
        try:
            rec = load_witness(wp)
        except (OSError, ValueError) as e:
            print(f"error: cannot read witness {wp}: {e}", file=sys.stderr)
            return 2
        for edge in rec.get("edges", ()):
            key = (edge.get("src"), edge.get("dst"))
            if key in seen:
                seen[key]["count"] = seen[key].get("count", 1) \
                    + edge.get("count", 1)
            else:
                seen[key] = dict(edge)
                witness["edges"].append(seen[key])
        witness["violations"].extend(rec.get("violations", ()))
    edges = static_edges(paths, use_cache=use_cache, cache_path=cache_path)
    report = diff_witness(witness, edges, Manifest.load())
    report["static_edges"] = len(edges)
    report["witness_files"] = len(witness_paths)
    report["ok"] = not report["missed"] and not report["violations"]
    if as_json:
        print(json.dumps(report, indent=2))
    else:
        print(f"witness: {report['runtime_edges']} runtime edge(s) from "
              f"{report['witness_files']} dump(s), "
              f"{report['static_edges']} static edge(s)")
        for s, d in report["missed"]:
            print(f"MISSED statically: {s} -> {d} (analyzer bug or missing "
                  "`# may-acquire:` on a dynamic-dispatch seam)")
        for v in report["violations"]:
            print(f"RUNTIME VIOLATION: {v.get('kind')} "
                  f"{v.get('src', v.get('lock'))} -> {v.get('dst', '')}")
        for s, d in report["never_witnessed"]:
            print(f"stale (declared, never witnessed): {s} -> {d}")
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dev.analysis",
        description="ballista-lint: AST-based invariant checker "
                    "(readback, tracer, dtype, lock, decline discipline)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: ballista_tpu/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not write the per-file result cache")
    ap.add_argument("--cache-file", default=None,
                    help="cache location (default: <repo>/.ballista_lint_cache.json)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--max-suppressions", type=int, default=SUPPRESSION_BUDGET,
                    help="fail when the tree carries more reasoned "
                         f"suppressions than this (default {SUPPRESSION_BUDGET}; "
                         "-1 disables)")
    ap.add_argument("--jobs", type=int, default=1,
                    help="process-pool width for per-file analysis (same "
                         "cache semantics, deterministic report order; "
                         "0 = one per CPU)")
    ap.add_argument("--check-witness", metavar="WITNESS_JSON", default=None,
                    action="append",
                    help="diff a runtime lock-witness dump "
                         "(ballista.debug.lock_witness) against the static "
                         "lock-order graph: runtime edges the analyzer "
                         "missed fail; declared-but-never-witnessed edges "
                         "are flagged stale. Repeatable: multi-process "
                         "lanes dump one <OUT>.<pid> file each, and the "
                         "edge sets merge before the diff")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULE_NAMES():
            print(r)
        return 0
    paths = args.paths or ["ballista_tpu"]
    jobs = args.jobs if args.jobs > 0 else (os.cpu_count() or 1)

    if args.check_witness:
        return check_witness(args.check_witness, paths, as_json=args.as_json,
                             use_cache=not args.no_cache,
                             cache_path=args.cache_file)

    try:
        findings, stats = run_paths(
            paths, use_cache=not args.no_cache, cache_path=args.cache_file,
            jobs=jobs,
        )
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    over_budget = (
        args.max_suppressions >= 0
        and stats["suppressions"] > args.max_suppressions
    )
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "stats": stats,
            "suppression_budget": args.max_suppressions,
            "over_suppression_budget": over_budget,
            "ok": not findings and not over_budget,
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
        print(
            f"ballista-lint: {stats['files']} files "
            f"({stats['cache_hits']} cached), {len(findings)} finding(s), "
            f"{stats['suppressions']} suppression(s)"
        )
        # per-rule cost/yield (ISSUE 18 satellite): only rules that found
        # something are worth a line; clean runs keep the one-line summary
        for rule, rec in stats.get("rules", {}).items():
            if rec["findings"]:
                print(f"  {rule}: {rec['findings']} finding(s), "
                      f"{rec['wall_s']:.3f}s")
        if over_budget:
            print(
                f"ballista-lint: suppression budget exceeded "
                f"({stats['suppressions']} > {args.max_suppressions})",
                file=sys.stderr,
            )
    return 1 if findings or over_budget else 0


if __name__ == "__main__":
    sys.exit(main())

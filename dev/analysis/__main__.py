"""CLI: python -m dev.analysis [paths...] [--json] [--no-cache] [--list-rules]

Exit codes: 0 clean, 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys

from dev.analysis.core import RULE_NAMES, run_paths

SUPPRESSION_BUDGET = 5  # package-wide cap (ISSUE 3 acceptance criteria)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dev.analysis",
        description="ballista-lint: AST-based invariant checker "
                    "(readback, tracer, dtype, lock, decline discipline)",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: ballista_tpu/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not write the per-file result cache")
    ap.add_argument("--cache-file", default=None,
                    help="cache location (default: <repo>/.ballista_lint_cache.json)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--max-suppressions", type=int, default=SUPPRESSION_BUDGET,
                    help="fail when the tree carries more reasoned "
                         f"suppressions than this (default {SUPPRESSION_BUDGET}; "
                         "-1 disables)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULE_NAMES():
            print(r)
        return 0
    paths = args.paths or ["ballista_tpu"]
    try:
        findings, stats = run_paths(
            paths, use_cache=not args.no_cache, cache_path=args.cache_file
        )
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    over_budget = (
        args.max_suppressions >= 0
        and stats["suppressions"] > args.max_suppressions
    )
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "stats": stats,
            "suppression_budget": args.max_suppressions,
            "over_suppression_budget": over_budget,
            "ok": not findings and not over_budget,
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
        print(
            f"ballista-lint: {stats['files']} files "
            f"({stats['cache_hits']} cached), {len(findings)} finding(s), "
            f"{stats['suppressions']} suppression(s)"
        )
        if over_budget:
            print(
                f"ballista-lint: suppression budget exceeded "
                f"({stats['suppressions']} > {args.max_suppressions})",
                file=sys.stderr,
            )
    return 1 if findings or over_budget else 0


if __name__ == "__main__":
    sys.exit(main())

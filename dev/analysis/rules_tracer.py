"""tracer-hygiene: functions traced by jit/shard_map/pallas (including
same-module callees reached from the decoration sites) must not branch on,
or host-materialize, values derived from jnp/jax.lax calls — those are
abstract tracers at trace time, and `if`/`while`/`bool()`/`float()`/
`int()`/`.item()` on them either crashes (ConcretizationTypeError) or, via
a silent python fallback, bakes one batch's data into the compiled program.
"""

from __future__ import annotations

import ast
from typing import List

from dev.analysis.common import (
    Taint,
    dotted,
    final_name,
    traced_functions,
    walk_no_nested_defs,
)
from dev.analysis.core import Finding, SourceFile, register

_TRACER_PREFIXES = ("jnp.", "jax.lax.", "jax.ops.", "jax.nn.", "jax.numpy.")
_CASTS = {"bool", "int", "float"}


def _is_tracer_call(call: ast.Call) -> bool:
    name = dotted(call.func)
    if name is None:
        return False
    return any(name.startswith(p) or name == p[:-1] for p in _TRACER_PREFIXES)


@register("tracer-hygiene")
def check(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    traced = traced_functions(sf.tree)
    if not traced:
        return findings
    for func in traced:
        params = {
            a.arg
            for a in list(func.args.args) + list(func.args.kwonlyargs)
            + list(func.args.posonlyargs)
            if a.arg not in ("self", "cls")
        }
        taint = Taint(func, lambda call, t: _is_tracer_call(call))
        for node in walk_no_nested_defs(func):
            if isinstance(node, (ast.If, ast.While)):
                if taint.expr_tainted(node.test):
                    kw = "while" if isinstance(node, ast.While) else "if"
                    findings.append(Finding(
                        "tracer-hygiene", sf.path, node.lineno, node.col_offset,
                        f"`{kw}` branches on a jnp-derived value inside traced "
                        f"function '{func.name}' — use jnp.where/lax.cond; a "
                        "tracer has no concrete truth value",
                    ))
            elif isinstance(node, ast.Call):
                fname = dotted(node.func)
                if fname in _CASTS and node.args and taint.expr_tainted(node.args[0]):
                    findings.append(Finding(
                        "tracer-hygiene", sf.path, node.lineno, node.col_offset,
                        f"{fname}() on a jnp-derived value inside traced "
                        f"function '{func.name}' forces host materialization "
                        "at trace time",
                    ))
                elif (final_name(node.func) == "item"
                      and isinstance(node.func, ast.Attribute)):
                    base = node.func.value
                    base_is_param = isinstance(base, ast.Name) and base.id in params
                    if base_is_param or taint.expr_tainted(base):
                        findings.append(Finding(
                            "tracer-hygiene", sf.path, node.lineno, node.col_offset,
                            f".item() inside traced function '{func.name}' "
                            "materializes a tracer at trace time",
                        ))
    return findings

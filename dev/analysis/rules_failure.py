"""failure-discipline: the failure-recovery paths stay analyzable.

Three invariants (ISSUE 5/11), scoped to the whole ballista_tpu package:

1. A `fetch_failed` status must CARRY THE LOST LOCATION. Any function that
   assigns `<status>.fetch_failed.error` must also assign
   `.fetch_failed.map_executor_id` and `.fetch_failed.path` — without the
   lineage the scheduler cannot recompute the lost map partition and the
   report degrades into an anonymous failure.

2. Chaos injection sites must be REGISTERED. Calls to the injector
   (`maybe_fail` / `should_inject`) must name a literal site present in
   `ballista_tpu/utils/chaos.py::SITES`, and `ChaosInjected` may only be
   raised by the injector itself — ad-hoc raises (or `random`-driven ones)
   are invisible to the registry and break chaos-run determinism.

3. Speculative duplicates must FLOW THROUGH THE LEDGER (ISSUE 11). A scope
   that MINTS a speculative attempt — assigns a literal `True` to a
   `.speculative` field — must also record it durably in the same scope
   (`_spec_put`, or `_ledger_put` for a promotion into the assignment
   ledger). An ad-hoc second-attempt path is invisible to scheduler-restart
   recovery and to the first-completion-wins bookkeeping; echo sites
   (`td.speculative = flag`) copy a non-literal and are exempt.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from dev.analysis.common import walk_no_nested_defs
from dev.analysis.core import Finding, SourceFile, register

_INJECTOR_METHODS = {"maybe_fail", "should_inject"}
_CHAOS_MODULE_SUFFIX = "ballista_tpu/utils/chaos.py"

# durable-record calls that legitimize a minted speculative attempt: the
# speculation ledger itself, or the assignment ledger for a promotion
_SPEC_LEDGER_METHODS = {"_spec_put", "_ledger_put"}

# fallback if chaos.py cannot be located from the scanned file (fixtures
# analyzed outside the repo tree); keep in sync with utils/chaos.py::SITES
_DEFAULT_SITES = frozenset(
    {
        "flight.fetch", "rpc.call", "task.execute", "kv.put",
        "executor.death", "scheduler.plan_write", "scheduler.crash",
        "cache.put", "scheduler.admit", "scheduler.push", "aot.load",
        "scheduler.batch", "task.slow", "shuffle.store", "fleet.scale",
        "exchange.evict", "cache.advance", "scheduler.lease", "kv.lease",
    }
)

_sites_cache: Dict[str, frozenset] = {}


def _registered_sites(real_path: str) -> frozenset:
    """SITES parsed from the chaos module nearest the scanned file: walk up
    from its directory until ballista_tpu/utils/chaos.py appears, so the
    rule checks against the registry of the tree actually being linted."""
    d = os.path.dirname(os.path.abspath(real_path))
    while True:
        candidate = os.path.join(d, _CHAOS_MODULE_SUFFIX.replace("/", os.sep))
        if os.path.isfile(candidate):
            if candidate not in _sites_cache:
                _sites_cache[candidate] = _parse_sites(candidate)
            return _sites_cache[candidate]
        parent = os.path.dirname(d)
        if parent == d:
            return _DEFAULT_SITES
        d = parent


def _parse_sites(chaos_path: str) -> frozenset:
    try:
        with open(chaos_path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return _DEFAULT_SITES
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "SITES" for t in node.targets
        ):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List, ast.Set)):
            vals = [
                e.value
                for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
            if vals:
                return frozenset(vals)
    return _DEFAULT_SITES


def _fetch_failed_field(node: ast.AST) -> Optional[str]:
    """'error' for targets shaped <base>.fetch_failed.<field>."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Attribute)
        and node.value.attr == "fetch_failed"
    ):
        return node.attr
    return None


def _scopes(tree: ast.Module):
    """Module + every def: fetch_failed field assignments are aggregated
    per enclosing scope (the status is built in one function)."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


@register("failure-discipline")
def check(sf: SourceFile) -> List[Finding]:
    path = sf.path.replace("\\", "/")
    in_chaos_module = path.endswith("utils/chaos.py")
    findings: List[Finding] = []

    # -- 1. fetch_failed must carry the lost location -----------------------
    for scope in _scopes(sf.tree):
        fields: Set[str] = set()
        error_assign = None
        # walk without descending into nested defs: each is its own scope
        for node in walk_no_nested_defs(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    f = _fetch_failed_field(t)
                    if f is not None:
                        fields.add(f)
                        if f == "error" and error_assign is None:
                            error_assign = node
        if error_assign is not None and not {"map_executor_id", "path"} <= fields:
            missing = sorted({"map_executor_id", "path"} - fields)
            findings.append(Finding(
                "failure-discipline", sf.path,
                error_assign.lineno, error_assign.col_offset,
                "fetch_failed status without the lost location (missing "
                f"{', '.join(missing)}) — the scheduler cannot recompute "
                "the lost map partition from an anonymous fetch failure",
            ))

    # -- 3. speculative attempts must flow through the ledger ----------------
    # a scope assigning a LITERAL True to `.speculative` is minting a new
    # duplicate attempt (echo sites copy a flag, a non-literal); without a
    # same-scope _spec_put/_ledger_put the attempt is invisible to restart
    # recovery and to first-completion-wins bookkeeping
    for scope in _scopes(sf.tree):
        mint = None
        ledgered = False
        for node in walk_no_nested_defs(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr == "speculative"
                        and isinstance(node.value, ast.Constant)
                        and node.value.value is True
                        and mint is None
                    ):
                        mint = node
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SPEC_LEDGER_METHODS
            ):
                ledgered = True
        if mint is not None and not ledgered:
            findings.append(Finding(
                "failure-discipline", sf.path,
                mint.lineno, mint.col_offset,
                "ad-hoc speculative attempt: `.speculative = True` without "
                "a durable ledger record in the same scope — duplicate "
                "dispatch must flow through _spec_put (or _ledger_put for "
                "a promotion) so restart recovery and first-completion-"
                "wins bookkeeping can see it",
            ))

    # -- 2. chaos sites must be registered ----------------------------------
    if not in_chaos_module:
        sites = _registered_sites(sf.real_path)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _INJECTOR_METHODS:
                arg = node.args[0] if node.args else None
                if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                    findings.append(Finding(
                        "failure-discipline", sf.path,
                        node.lineno, node.col_offset,
                        f"chaos {node.func.attr}() site must be a string "
                        "literal from chaos.SITES (a computed site evades "
                        "the registry)",
                    ))
                elif arg.value not in sites:
                    findings.append(Finding(
                        "failure-discipline", sf.path,
                        node.lineno, node.col_offset,
                        f"unregistered chaos site {arg.value!r} — register "
                        "it in ballista_tpu/utils/chaos.py::SITES first",
                    ))
            elif isinstance(node, ast.Raise) and node.exc is not None:
                target = node.exc.func if isinstance(node.exc, ast.Call) else node.exc
                name = target.attr if isinstance(target, ast.Attribute) else (
                    target.id if isinstance(target, ast.Name) else None
                )
                if name == "ChaosInjected":
                    findings.append(Finding(
                        "failure-discipline", sf.path,
                        node.lineno, node.col_offset,
                        "ad-hoc `raise ChaosInjected` outside the injector "
                        "— faults must come from a registered site via "
                        "ChaosInjector.maybe_fail",
                    ))
    return findings

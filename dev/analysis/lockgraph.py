"""Whole-program lock-order graph (ISSUE 14).

Model shared by the static rule (rules_lockorder.py), the runner's global
pass (core.run_paths), and the dynamic witness cross-check
(`python -m dev.analysis --check-witness`).

Canonical lock names
--------------------
A lock's identity is its *class*, not its instance: `<module>.<name>` where
`<module>` is the source path under ballista_tpu/ with slashes -> dots and
no extension (`scheduler.state`, `ops.runtime`) and `<name>` is the module
global or instance attribute the lock is bound to (`_res_lock`,
`_tenant_mu`). Two instances of one class share a name — conservative:
merging can only add edges, never hide one. Special case: the global
scheduler KV lock is acquired as `<anything>.lock()` (the KvBackend.lock()
contract) and canonicalizes to `scheduler.kv.lock`; the backends' own
`self._mu` RLocks ARE that lock, so ALIASES folds them in.

Manifest (lockorder.toml)
-------------------------
`order` ranks every known lock: an observed edge src->dst must go FORWARD
(rank[src] < rank[dst]) and be explicitly declared in `[[edges]]` with a
reason — an undeclared nested acquisition is a lint error, so new nesting
is a reviewed decision, not an accident. `[locks."<name>"]` carries
per-lock attributes: `reentrant = true` (RLock semantics: self-edges are
legal re-entry) and `instance_tree = "<reason>"` (distinct instances of
this class nest in an acyclic structural order, e.g. a plan tree's join
build locks; same-OBJECT re-acquisition is still a deadlock and the
dynamic witness asserts on it).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

try:  # py3.11+
    import tomllib as _toml
except ImportError:  # pragma: no cover - py3.10 fallback (PR 2 idiom)
    import tomli as _toml  # type: ignore

MANIFEST_BASENAME = "lockorder.toml"

# the global scheduler lock: `with <x>.lock():` anywhere, and the KV
# backends' own `self._mu` reentrant locks that implement it
KV_LOCK = "scheduler.kv.lock"
ALIASES = {
    "scheduler.kv._mu": KV_LOCK,
    # factagg acquires its INNER FusedAggregateStage's prepare lock
    # (`with self.inner._prepare_lock:`); same lock class, stage's module
    "ops.factagg._prepare_lock": "ops.stage._prepare_lock",
}

# with-item expressions that look like lock acquisitions even when the
# lock object was created elsewhere (bare Name / self-attribute form)
LOCKISH_RE = re.compile(r"(_mu|_lock)\d*$|^_?lock$")


def canonical(name: str) -> str:
    return ALIASES.get(name, name)


def module_of(display_path: str) -> str:
    """`ballista_tpu/scheduler/state.py` -> `scheduler.state` (tests keep
    their own prefix so fixture locks can't collide with production ones)."""
    p = display_path.replace("\\", "/")
    for root in ("ballista_tpu/",):
        if p.startswith(root):
            p = p[len(root):]
            break
    if p.endswith(".py"):
        p = p[:-3]
    return p.replace("/", ".")


@dataclasses.dataclass(frozen=True)
class EdgeSite:
    """One concrete place an acquired-while-held edge was observed."""

    src: str
    dst: str
    path: str
    line: int
    func: str
    via: str  # "" for a direct `with` nesting, else the call chain

    def describe(self) -> str:
        how = f" via {self.via}" if self.via else ""
        return (f"{self.path}:{self.line} in {self.func}: "
                f"{self.src} -> {self.dst}{how}")


class LockGraph:
    """Directed graph of acquired-while-held edges with example sites."""

    def __init__(self) -> None:
        self.edges: Dict[Tuple[str, str], List[EdgeSite]] = {}

    def add(self, site: EdgeSite) -> None:
        self.edges.setdefault((site.src, site.dst), []).append(site)

    def edge_set(self) -> Set[Tuple[str, str]]:
        return set(self.edges)

    def locks(self) -> Set[str]:
        out: Set[str] = set()
        for s, d in self.edges:
            out.add(s)
            out.add(d)
        return out

    def site(self, src: str, dst: str) -> Optional[EdgeSite]:
        sites = self.edges.get((src, dst))
        return sites[0] if sites else None

    def cycles(self) -> List[List[str]]:
        """Elementary cycles (each reported once, smallest-lock-first
        rotation), via iterative DFS back-edge detection per SCC member.
        The graphs here are tiny; clarity over asymptotics."""
        adj: Dict[str, Set[str]] = {}
        for s, d in self.edges:  # self-loops included: a cycle of one
            adj.setdefault(s, set()).add(d)
        seen: Set[Tuple[str, ...]] = set()
        out: List[List[str]] = []

        def norm(cycle: List[str]) -> Tuple[str, ...]:
            i = cycle.index(min(cycle))
            return tuple(cycle[i:] + cycle[:i])

        for start in sorted(adj):
            # DFS from `start`, only visiting nodes >= start to bound work
            stack: List[Tuple[str, List[str]]] = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == start:
                        key = norm(path)
                        if key not in seen:
                            seen.add(key)
                            out.append(path + [start])
                    elif nxt not in path and len(path) < 8:
                        stack.append((nxt, path + [nxt]))
        return out

    def cycle_report(self, cycle: List[str]) -> str:
        """Both (all) acquisition paths of a cycle, one line per edge."""
        lines = []
        for a, b in zip(cycle, cycle[1:]):
            site = self.site(a, b)
            lines.append("  " + (site.describe() if site else f"{a} -> {b}"))
        return "\n".join(lines)


class Manifest:
    """Parsed lockorder.toml: ranks, declared edges, per-lock attributes,
    lock groups (an edge with `dst_group` declares src -> every member)."""

    def __init__(self, data: Optional[dict] = None) -> None:
        data = data or {}
        self.order: List[str] = list(data.get("order", ()))
        self.rank: Dict[str, int] = {n: i for i, n in enumerate(self.order)}
        self.groups: Dict[str, List[str]] = dict(data.get("groups", {}))
        self.declared: Dict[Tuple[str, str], str] = {}
        for e in data.get("edges", ()):
            dsts = [e["dst"]] if "dst" in e else list(
                self.groups.get(e.get("dst_group", ""), ())
            )
            for dst in dsts:
                self.declared[(e["src"], dst)] = e.get("reason", "")
        self.attrs: Dict[str, dict] = dict(data.get("locks", {}))

    @classmethod
    def load(cls, path: Optional[str] = None) -> "Manifest":
        if path is None:
            path = default_manifest_path()
        if not os.path.exists(path):
            return cls()
        with open(path, "rb") as f:
            return cls(_toml.load(f))

    def reentrant(self, lock: str) -> bool:
        return bool(self.attrs.get(lock, {}).get("reentrant"))

    def instance_tree(self, lock: str) -> bool:
        return bool(self.attrs.get(lock, {}).get("instance_tree")
                    or self.attrs.get(lock, {}).get("plan_tree"))

    def plan_tree(self, lock: str) -> bool:
        """Plan-tree node lock: distinct instances acquire along the plan
        tree, which is acyclic across instances by construction — so
        class-level edges AMONG plan-tree locks are exempt from the
        declared order (a class-level cycle there does not imply an
        instance-level one)."""
        return bool(self.attrs.get(lock, {}).get("plan_tree"))

    def plan_pair(self, src: str, dst: str) -> bool:
        return self.plan_tree(src) and self.plan_tree(dst)

    def check_edge(self, src: str, dst: str) -> Optional[str]:
        """None if the edge is declared and forward; else the complaint."""
        if src != dst and self.plan_pair(src, dst):
            return None
        if src == dst:
            if self.reentrant(src) or self.instance_tree(src):
                return None
            return (f"self-acquisition of non-reentrant lock '{src}' would "
                    "self-deadlock — use an RLock, restructure, or declare "
                    f"`instance_tree` for it in {MANIFEST_BASENAME}")
        if (src, dst) not in self.declared:
            return (f"undeclared lock-order edge {src} -> {dst}: declare it "
                    f"in {MANIFEST_BASENAME} [[edges]] (with a reason) or "
                    "restructure to avoid the nested acquisition")
        rs, rd = self.rank.get(src), self.rank.get(dst)
        if rs is None or rd is None:
            missing = src if rs is None else dst
            return (f"lock '{missing}' is missing from the canonical `order` "
                    f"list in {MANIFEST_BASENAME}")
        if rs >= rd:
            return (f"lock-order inversion: {src} (rank {rs}) acquired "
                    f"before {dst} (rank {rd}) but the canonical order says "
                    f"{dst} < {src}")
        return None

    def check_locks_ranked(self, locks: Iterable[str]) -> List[str]:
        return [n for n in sorted(locks) if n not in self.rank]


def default_manifest_path() -> str:
    # overridable for tests (the override's hash still folds into every
    # per-file cache key — core._manifest_hash resolves THIS function)
    return os.environ.get("BALLISTA_LOCKORDER_MANIFEST") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), MANIFEST_BASENAME
    )


# -- witness cross-check ------------------------------------------------------

def load_witness(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def diff_witness(witness: dict, static_edges: Set[Tuple[str, str]],
                 manifest: Manifest) -> dict:
    """Cross-check a runtime witness dump against the static graph:

    - `missed`: edges the runtime actually took but the static analyzer
      never derived — analyzer bugs (or a missing `# may-acquire:`
      annotation on a dynamic-dispatch seam). Hard failures.
    - `stale`: declared manifest edges neither witnessed at runtime nor
      (for extra signal) derived statically — candidates for removal.
    - `violations`: order inversions the witness recorded as they
      happened (each carries both stacks in the dump).
    """
    runtime = {
        (e["src"], e["dst"]) for e in witness.get("edges", ())
        if e["src"] != e["dst"]
    }
    # plan-tree pairs are structurally ordered per instance; the static
    # analyzer does not chase dynamic plan composition among them
    missed = sorted(
        (s, d) for (s, d) in runtime - static_edges
        if not manifest.plan_pair(s, d)
    )
    witnessed = runtime | {(d, s) for s, d in runtime}
    stale = sorted(
        (s, d) for (s, d) in manifest.declared
        if (s, d) not in witnessed and (s, d) not in static_edges
    )
    never_witnessed = sorted(
        (s, d) for (s, d) in manifest.declared if (s, d) not in runtime
    )
    return {
        "missed": missed,
        "stale": stale,
        "never_witnessed": never_witnessed,
        "violations": list(witness.get("violations", ())),
        "runtime_edges": len(runtime),
    }

"""guarded-by: lock discipline for annotated shared state.

Registration (file-scoped — analysis is per-file so caching stays sound):

    _resident_bytes = 0          # guarded-by: _res_lock
    self._data: Dict[...] = {}   # guarded-by: self._mu

Every later read or write of a registered module global (by name) or
`self.<attr>` (within the registering file) must be lexically inside
`with <lock>:` — matched on the exact source text of the with-item — or
inside a function annotated `# holds-lock: <lock>` on its def line
(meaning: the caller holds the lock; call sites of such functions are then
checked for the same guard). Exemptions: the registering statement itself,
module top level and class bodies (single-threaded import time), and
`__init__`/`__new__` (the object is not yet shared)."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dev.analysis.core import Finding, SourceFile, register


def _norm(expr: str) -> str:
    return expr.replace(" ", "")


def _target_keys(stmt: ast.AST) -> List[Tuple[str, str]]:
    """('global', name) / ('attr', name) keys for an assignment's targets."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    out = []
    for t in targets:
        if isinstance(t, ast.Name):
            out.append(("global", t.id))
        elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            out.append(("attr", t.attr))
    return out


class _Checker(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, guards: Dict[Tuple[str, str], str],
                 registration_lines: Set[int]):
        self.sf = sf
        self.guards = guards
        self.registration_lines = registration_lines
        self.findings: List[Finding] = []
        self.held: List[str] = []
        self.func_stack: List[ast.AST] = []
        self.holds_fns: Dict[str, str] = {}  # func name -> lock it requires

    # -- context tracking ---------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        locks = [
            _norm(ast.unparse(item.context_expr)) for item in node.items
        ]
        self.held.extend(locks)
        self.generic_visit(node)
        del self.held[len(self.held) - len(locks):]

    def _visit_func(self, node) -> None:
        held_here = self.sf.holds_lock(node)
        if held_here:
            self.holds_fns[node.name] = _norm(held_here)
        saved = self.held
        self.held = [_norm(held_here)] if held_here else []
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()
        self.held = saved

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- access checks ------------------------------------------------------
    def _exempt(self) -> bool:
        if not self.func_stack:
            return True  # module top level / class body: import-time init
        return self.func_stack[-1].name in ("__init__", "__new__")

    def _check(self, node: ast.AST, key: Tuple[str, str], shown: str) -> None:
        lock = self.guards.get(key)
        if lock is None or self._exempt():
            return
        if node.lineno in self.registration_lines:
            return
        if _norm(lock) in self.held:
            return
        fn = self.func_stack[-1].name if self.func_stack else "<module>"
        self.findings.append(Finding(
            "guarded-by", self.sf.path, node.lineno, node.col_offset,
            f"'{shown}' is guarded by '{lock}' but accessed outside "
            f"`with {lock}` in '{fn}' — acquire the lock or annotate the "
            f"function `# holds-lock: {lock}`",
        ))

    def visit_Name(self, node: ast.Name) -> None:
        self._check(node, ("global", node.id), node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            self._check(node, ("attr", node.attr), f"self.{node.attr}")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # a call to a holds-lock function must itself happen under the lock
        fname = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        lock = self.holds_fns.get(fname or "")
        if lock and lock not in self.held and not self._exempt():
            fn = self.func_stack[-1].name if self.func_stack else "<module>"
            self.findings.append(Finding(
                "guarded-by", self.sf.path, node.lineno, node.col_offset,
                f"'{fname}' requires holding '{lock}' (holds-lock "
                f"annotation) but is called without it in '{fn}'",
            ))
        self.generic_visit(node)


@register("guarded-by")
def check(sf: SourceFile) -> List[Finding]:
    guards: Dict[Tuple[str, str], str] = {}
    registration_lines: Set[int] = set()
    for stmt, lock in sf.guarded_targets():
        for key in _target_keys(stmt):
            guards[key] = lock
        registration_lines.add(stmt.lineno)
    # collect holds-lock functions FIRST so call-site checks see them all
    checker = _Checker(sf, guards, registration_lines)
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            held = sf.holds_lock(node)
            if held:
                checker.holds_fns[node.name] = _norm(held)
    if not guards and not checker.holds_fns:
        return []
    checker.visit(sf.tree)
    return checker.findings

"""Framework: findings, per-file source model (comments, suppressions,
annotations), rule registry, per-file cache, and the directory runner."""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Dict, List, Optional, Tuple

META_RULE = "lint-usage"

# populated by dev.analysis.rules at import time (rule name -> check fn)
_REGISTRY: Dict[str, object] = {}
# per-file fact extractors feeding whole-program passes (name -> fn(sf))
_FACTS: Dict[str, object] = {}
# whole-program passes run by the runner over every file's cached facts
# (name -> fn(facts_by_path) -> findings). Their findings are recomputed on
# every run — never cached per file, since they depend on OTHER files.
_GLOBAL: Dict[str, object] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def register_facts(name: str):
    def deco(fn):
        _FACTS[name] = fn
        return fn

    return deco


def register_global(name: str):
    def deco(fn):
        _GLOBAL[name] = fn
        return fn

    return deco


def RULE_NAMES() -> List[str]:
    _load_rules()
    return sorted(set(_REGISTRY) | set(_GLOBAL)) + [META_RULE]


_RULES_LOADED = False


def _load_rules() -> None:
    # a dedicated flag, NOT `if _REGISTRY:` — importing one rule module
    # directly (tests do) pre-populates the registry, and the truthiness
    # guard would then silently skip loading every other rule
    global _RULES_LOADED
    if _RULES_LOADED:
        return
    _RULES_LOADED = True
    from dev.analysis import (  # noqa: F401
        rules_decline,
        rules_dtype,
        rules_failure,
        rules_guarded,
        rules_lockorder,
        rules_readback,
        rules_routing,
        rules_tracer,
    )


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_DIRECTIVE_RE = re.compile(r"#\s*ballista-lint:\s*(.*)")
_DISABLE_RE = re.compile(r"disable=([\w.,-]+)(?:\s*--\s*(.*\S))?\s*$")
_PATH_RE = re.compile(r"path=(\S+)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\S[^#]*?)\s*$")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(\S[^#]*?)\s*$")
# check-then-act across a lock release, reviewed and accepted (ISSUE 14)
_ATOMICITY_OK_RE = re.compile(r"#\s*atomicity-ok:\s*(\S[^#]*?)\s*$")
# dynamic-dispatch seam (callback, plan-tree execute): the annotated def
# may acquire the named canonical locks even though no call edge resolves
# to them statically — feeds the lock-order graph (ISSUE 14)
_MAY_ACQUIRE_RE = re.compile(r"#\s*may-acquire:\s*(\S[^#]*?)\s*$")


@dataclasses.dataclass
class Suppression:
    lines: Tuple[int, ...]  # physical lines this suppression covers
    rules: Tuple[str, ...]
    reason: Optional[str]
    comment_line: int
    used: bool = False


class SourceFile:
    """Parsed view of one file: AST + comment-driven directives.

    `path` is the display/scoping path: relative to the repo root when the
    file lives under it, and overridable by a `# ballista-lint: path=...`
    header so test fixtures can exercise device-path-scoped rules."""

    def __init__(self, real_path: str, source: str, display_path: str):
        self.real_path = real_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=real_path)
        self.suppressions: List[Suppression] = []
        self.guarded: Dict[int, str] = {}  # line -> lock expr
        self.holds: Dict[int, str] = {}  # line -> lock expr
        self.atomicity_ok: Dict[int, str] = {}  # line -> reason
        self.may_acquire: Dict[int, str] = {}  # line -> lock list expr
        self.meta_findings: List[Finding] = []
        self.path = display_path
        self._scan_comments()

    # -- comment scanning --------------------------------------------------
    def _scan_comments(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.source).readline))
        except tokenize.TokenError:
            return
        known = set(_REGISTRY) | {META_RULE}
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            standalone = self.lines[line - 1][: tok.start[1]].strip() == ""
            text = tok.string
            g = _GUARDED_RE.search(text)
            if g:
                # a standalone annotation covers the next line's statement
                self.guarded[line if not standalone else line + 1] = g.group(1).strip()
            h = _HOLDS_RE.search(text)
            if h:
                self.holds[line] = h.group(1).strip()
            a = _ATOMICITY_OK_RE.search(text)
            if a:
                # a standalone annotation covers the next line's statement
                self.atomicity_ok[line if not standalone else line + 1] = \
                    a.group(1).strip()
            ma = _MAY_ACQUIRE_RE.search(text)
            if ma:
                self.may_acquire[line] = ma.group(1).strip()
            m = _DIRECTIVE_RE.search(text)
            if not m:
                continue
            body = m.group(1).strip()
            if line <= 10 and _PATH_RE.match(body):
                self.path = _PATH_RE.match(body).group(1)
                continue
            d = _DISABLE_RE.match(body)
            if not d:
                self.meta_findings.append(
                    Finding(META_RULE, self.path, line, tok.start[1],
                            f"unrecognized ballista-lint directive: {body!r}")
                )
                continue
            rules = tuple(r.strip() for r in d.group(1).split(",") if r.strip())
            reason = d.group(2)
            unknown = [r for r in rules if r not in known]
            if unknown:
                self.meta_findings.append(
                    Finding(META_RULE, self.path, line, tok.start[1],
                            f"suppression names unknown rule(s) {unknown}; "
                            f"known: {sorted(known)}")
                )
            if not reason:
                self.meta_findings.append(
                    Finding(META_RULE, self.path, line, tok.start[1],
                            "suppression without a reason — write "
                            "'# ballista-lint: disable=<rule> -- <why>'")
                )
                continue  # a reasonless suppression does not suppress
            covered = (line,) if not standalone else (line, line + 1)
            self.suppressions.append(Suppression(covered, rules, reason, line))

    # -- annotation lookup -------------------------------------------------
    def guarded_targets(self) -> List[Tuple[ast.AST, str]]:
        """(assignment statement, lock expr) pairs for every statement a
        guarded-by comment attaches to."""
        out = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                lock = self.guarded.get(node.lineno)
                if lock:
                    out.append((node, lock))
        return out

    def holds_lock(self, func: ast.AST) -> Optional[str]:
        """Lock named by a `# holds-lock:` comment on the def's signature."""
        return self._def_annotation(func, self.holds)

    def may_acquire_of(self, func: ast.AST) -> Optional[str]:
        """Lock list named by a `# may-acquire:` comment on the def."""
        return self._def_annotation(func, self.may_acquire)

    def _def_annotation(self, func: ast.AST, table: Dict[int, str]) -> Optional[str]:
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        end = func.body[0].lineno if func.body else func.lineno + 1
        # lineno-1 covers a standalone annotation directly above the def
        for line in range(func.lineno - 1, end + 1):
            if line in table:
                return table[line]
        return None

    # -- suppression application -------------------------------------------
    def apply_suppressions(self, findings: List[Finding]) -> List[Finding]:
        kept = []
        for f in findings:
            hit = None
            for s in self.suppressions:
                if f.rule in s.rules and f.line in s.lines:
                    hit = s
                    break
            if hit is None:
                kept.append(f)
            else:
                hit.used = True
        for s in self.suppressions:
            if not s.used:
                kept.append(
                    Finding(META_RULE, self.path, s.comment_line, 0,
                            f"unused suppression for {', '.join(s.rules)} — "
                            "remove it or move it onto the flagged line")
                )
        return kept


# -- per-file analysis -------------------------------------------------------

def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _display_path(path: str) -> str:
    ap = os.path.abspath(path)
    root = _repo_root()
    return os.path.relpath(ap, root) if ap.startswith(root + os.sep) else path


def _analyze(path: str) -> Tuple[List[Finding], int, dict]:
    """(surviving findings, reasoned-suppression count, facts) for one
    file — one read/parse/tokenize pass serves all three. Facts feed the
    whole-program passes (lock-order graph) and are cached beside the
    findings."""
    _load_rules()
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        sf = SourceFile(path, source, _display_path(path))
    except SyntaxError as e:
        return [Finding(META_RULE, _display_path(path), e.lineno or 1, 0,
                        f"syntax error: {e.msg}")], 0, {}
    findings: List[Finding] = []
    for name, check in sorted(_REGISTRY.items()):
        findings.extend(check(sf))
    findings = sf.apply_suppressions(findings)
    findings.extend(sf.meta_findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    facts = {name: fn(sf) for name, fn in sorted(_FACTS.items())}
    return findings, len(sf.suppressions), facts


def _global_findings(facts_by_path: Dict[str, dict]) -> List[Finding]:
    """Run every whole-program pass over the collected per-file facts."""
    _load_rules()
    findings: List[Finding] = []
    for name, fn in sorted(_GLOBAL.items()):
        findings.extend(fn(facts_by_path))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_file(path: str) -> List[Finding]:
    """All surviving findings for one file (suppressions applied) —
    including the whole-program passes scoped to just this file, so a
    single-file CLI run (and the fixture pair tests) exercise the
    lock-order graph checks."""
    findings, _n, facts = _analyze(path)
    findings = findings + _global_findings({_display_path(path): facts})
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def suppression_count(path: str) -> int:
    """Reasoned suppressions present in a file (for budget accounting)."""
    return _analyze(path)[1]


# -- cache -------------------------------------------------------------------

CACHE_BASENAME = ".ballista_lint_cache.json"


def _analyzer_hash() -> str:
    """Hash of the analyzer's own sources AND the lock-order manifest: a
    rule or manifest change invalidates every cached verdict."""
    d = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha1()
    for name in sorted(os.listdir(d)):
        if name.endswith(".py") or name.endswith(".toml"):
            with open(os.path.join(d, name), "rb") as f:
                h.update(name.encode())
                h.update(f.read())
    return h.hexdigest()[:16]


class FileCache:
    def __init__(self, cache_path: Optional[str]):
        self.cache_path = cache_path
        self.data: Dict[str, dict] = {}
        self.dirty = False
        self.hits = 0
        self._ahash = _analyzer_hash()
        if cache_path and os.path.exists(cache_path):
            try:
                with open(cache_path) as f:
                    blob = json.load(f)
                if blob.get("analyzer") == self._ahash:
                    self.data = blob.get("files", {})
            except (OSError, ValueError):
                pass

    def _key(self, path: str) -> str:
        st = os.stat(path)
        return f"{st.st_mtime_ns}:{st.st_size}"

    def get(self, path: str) -> Optional[Tuple[List[Finding], int, dict]]:
        ap = os.path.abspath(path)
        ent = self.data.get(ap)
        if ent is None or ent.get("key") != self._key(path):
            return None
        self.hits += 1
        return (
            [Finding(**f) for f in ent["findings"]],
            ent.get("suppressions", 0),
            ent.get("facts", {}),
        )

    def put(self, path: str, findings: List[Finding], suppressions: int,
            facts: dict) -> None:
        ap = os.path.abspath(path)
        self.data[ap] = {
            "key": self._key(path),
            "findings": [f.to_dict() for f in findings],
            "suppressions": suppressions,
            "facts": facts,
        }
        self.dirty = True

    def save(self) -> None:
        if not self.cache_path or not self.dirty:
            return
        tmp = self.cache_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"analyzer": self._ahash, "files": self.data}, f)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass


# -- runner ------------------------------------------------------------------

def collect_py_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".jax_cache")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif p.endswith(".py"):
            out.append(p)
    return out


def _analyze_for_pool(path: str) -> Tuple[str, List[dict], int, dict]:
    """Process-pool worker: one file, serialized findings (dicts pickle
    smaller and version-stably across pool boundaries)."""
    findings, n_supp, facts = _analyze(path)
    return path, [f.to_dict() for f in findings], n_supp, facts


def run_paths(paths: List[str], use_cache: bool = True,
              cache_path: Optional[str] = None,
              jobs: int = 1) -> Tuple[List[Finding], dict]:
    """Analyze every .py under `paths`. Returns (findings, stats).

    `jobs` > 1 fans the per-file analysis over a process pool (ISSUE 14:
    the strict lint gate stops being serial as rule count grows) with the
    SAME cache semantics — cached files never hit the pool, fresh results
    land in the cache identically — and a deterministic report order
    (results are reassembled in file order regardless of completion
    order). The whole-program lock-order pass then runs over every file's
    facts, cached or fresh; its findings depend on OTHER files and are
    recomputed each run, never cached."""
    _load_rules()
    files = collect_py_files(paths)
    if use_cache and cache_path is None:
        cache_path = os.path.join(_repo_root(), CACHE_BASENAME)
    cache = FileCache(cache_path if use_cache else None)
    per_file: Dict[str, Tuple[List[Finding], int, dict]] = {}
    fresh = []
    for path in files:
        cached = cache.get(path) if use_cache else None
        if cached is not None:
            per_file[path] = cached
        else:
            fresh.append(path)
    if fresh and jobs > 1:
        import concurrent.futures

        with concurrent.futures.ProcessPoolExecutor(max_workers=jobs) as ex:
            for path, fdicts, n_supp, facts in ex.map(
                _analyze_for_pool, fresh, chunksize=4
            ):
                per_file[path] = ([Finding(**d) for d in fdicts], n_supp, facts)
    else:
        for path in fresh:
            per_file[path] = _analyze(path)
    findings: List[Finding] = []
    n_suppressions = 0
    facts_by_path: Dict[str, dict] = {}
    fresh_set = set(fresh)
    for path in files:
        result, n_supp, facts = per_file[path]
        if use_cache and path in fresh_set:
            cache.put(path, result, n_supp, facts)
        findings.extend(result)
        n_suppressions += n_supp
        facts_by_path[_display_path(path)] = facts
    cache.save()
    findings.extend(_global_findings(facts_by_path))
    stats = {
        "files": len(files),
        "cache_hits": cache.hits,
        "suppressions": n_suppressions,
        "findings": len(findings),
    }
    return findings, stats


def collect_facts(paths: List[str], use_cache: bool = True,
                  cache_path: Optional[str] = None) -> Dict[str, dict]:
    """Per-file facts for every .py under `paths` (display path -> facts)
    — the static side of the witness cross-check."""
    _load_rules()
    files = collect_py_files(paths)
    if use_cache and cache_path is None:
        cache_path = os.path.join(_repo_root(), CACHE_BASENAME)
    cache = FileCache(cache_path if use_cache else None)
    out: Dict[str, dict] = {}
    for path in files:
        cached = cache.get(path) if use_cache else None
        if cached is not None:
            out[_display_path(path)] = cached[2]
        else:
            findings, n_supp, facts = _analyze(path)
            if use_cache:
                cache.put(path, findings, n_supp, facts)
            out[_display_path(path)] = facts
    cache.save()
    return out

"""Framework: findings, per-file source model (comments, suppressions,
annotations), rule registry, per-file cache, and the directory runner."""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Dict, List, Optional, Tuple

META_RULE = "lint-usage"

# populated by dev.analysis.rules at import time (rule name -> check fn)
_REGISTRY: Dict[str, object] = {}
# per-file fact extractors feeding whole-program passes (name -> fn(sf))
_FACTS: Dict[str, object] = {}
# whole-program passes run by the runner over every file's cached facts
# (name -> fn(facts_by_path) -> findings). Their findings are recomputed on
# every run — never cached per file, since they depend on OTHER files.
_GLOBAL: Dict[str, object] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def register_facts(name: str):
    def deco(fn):
        _FACTS[name] = fn
        return fn

    return deco


def register_global(name: str):
    def deco(fn):
        _GLOBAL[name] = fn
        return fn

    return deco


def RULE_NAMES() -> List[str]:
    _load_rules()
    return sorted(set(_REGISTRY) | set(_GLOBAL)) + [META_RULE]


_RULES_LOADED = False


def _load_rules() -> None:
    # a dedicated flag, NOT `if _REGISTRY:` — importing one rule module
    # directly (tests do) pre-populates the registry, and the truthiness
    # guard would then silently skip loading every other rule
    global _RULES_LOADED
    if _RULES_LOADED:
        return
    _RULES_LOADED = True
    from dev.analysis import (  # noqa: F401
        rules_decline,
        rules_dtype,
        rules_durability,
        rules_failure,
        rules_guarded,
        rules_lockorder,
        rules_readback,
        rules_routing,
        rules_tracer,
    )


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_DIRECTIVE_RE = re.compile(r"#\s*ballista-lint:\s*(.*)")
_DISABLE_RE = re.compile(r"disable=([\w.,-]+)(?:\s*--\s*(.*\S))?\s*$")
_PATH_RE = re.compile(r"path=(\S+)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\S[^#]*?)\s*$")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(\S[^#]*?)\s*$")
# check-then-act across a lock release, reviewed and accepted (ISSUE 14)
_ATOMICITY_OK_RE = re.compile(r"#\s*atomicity-ok:\s*(\S[^#]*?)\s*$")
# dynamic-dispatch seam (callback, plan-tree execute): the annotated def
# may acquire the named canonical locks even though no call edge resolves
# to them statically — feeds the lock-order graph (ISSUE 14)
_MAY_ACQUIRE_RE = re.compile(r"#\s*may-acquire:\s*(\S[^#]*?)\s*$")
# replica-coherence classification of scheduler state (ISSUE 18):
# durable(<kv-prefix>) | derived(<rebuild-fn>) | ephemeral(<reason>)
_DURABILITY_RE = re.compile(
    r"#\s*durability:\s*(durable|derived|ephemeral)\(([^()]*)\)"
)
# a function folding a TaskStatus into durable state without the attempt/
# ledger guard, reviewed and accepted (ISSUE 18)
_ATTEMPT_OK_RE = re.compile(r"#\s*attempt-guard-ok:\s*(\S[^#]*?)\s*$")


@dataclasses.dataclass
class Suppression:
    lines: Tuple[int, ...]  # physical lines this suppression covers
    rules: Tuple[str, ...]
    reason: Optional[str]
    comment_line: int
    used: bool = False


class SourceFile:
    """Parsed view of one file: AST + comment-driven directives.

    `path` is the display/scoping path: relative to the repo root when the
    file lives under it, and overridable by a `# ballista-lint: path=...`
    header so test fixtures can exercise device-path-scoped rules."""

    def __init__(self, real_path: str, source: str, display_path: str):
        self.real_path = real_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=real_path)
        self.suppressions: List[Suppression] = []
        self.guarded: Dict[int, str] = {}  # line -> lock expr
        self.holds: Dict[int, str] = {}  # line -> lock expr
        self.atomicity_ok: Dict[int, str] = {}  # line -> reason
        self.may_acquire: Dict[int, str] = {}  # line -> lock list expr
        self.durability: Dict[int, Tuple[str, str]] = {}  # line -> (class, arg)
        self.attempt_ok: Dict[int, str] = {}  # line -> reason
        self.meta_findings: List[Finding] = []
        self.path = display_path
        self._scan_comments()

    # -- comment scanning --------------------------------------------------
    def _scan_comments(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.source).readline))
        except tokenize.TokenError:
            return
        known = set(_REGISTRY) | {META_RULE}
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            standalone = self.lines[line - 1][: tok.start[1]].strip() == ""
            text = tok.string
            g = _GUARDED_RE.search(text)
            if g:
                # a standalone annotation covers the next line's statement
                self.guarded[line if not standalone else line + 1] = g.group(1).strip()
            h = _HOLDS_RE.search(text)
            if h:
                self.holds[line] = h.group(1).strip()
            a = _ATOMICITY_OK_RE.search(text)
            if a:
                # a standalone annotation covers the next line's statement
                self.atomicity_ok[line if not standalone else line + 1] = \
                    a.group(1).strip()
            ma = _MAY_ACQUIRE_RE.search(text)
            if ma:
                self.may_acquire[line] = ma.group(1).strip()
            du = _DURABILITY_RE.search(text)
            if du:
                # a standalone annotation covers the next line's statement
                self.durability[line if not standalone else line + 1] = (
                    du.group(1), du.group(2).strip()
                )
            ao = _ATTEMPT_OK_RE.search(text)
            if ao:
                self.attempt_ok[line] = ao.group(1).strip()
            m = _DIRECTIVE_RE.search(text)
            if not m:
                continue
            body = m.group(1).strip()
            if line <= 10 and _PATH_RE.match(body):
                self.path = _PATH_RE.match(body).group(1)
                continue
            d = _DISABLE_RE.match(body)
            if not d:
                self.meta_findings.append(
                    Finding(META_RULE, self.path, line, tok.start[1],
                            f"unrecognized ballista-lint directive: {body!r}")
                )
                continue
            rules = tuple(r.strip() for r in d.group(1).split(",") if r.strip())
            reason = d.group(2)
            unknown = [r for r in rules if r not in known]
            if unknown:
                self.meta_findings.append(
                    Finding(META_RULE, self.path, line, tok.start[1],
                            f"suppression names unknown rule(s) {unknown}; "
                            f"known: {sorted(known)}")
                )
            if not reason:
                self.meta_findings.append(
                    Finding(META_RULE, self.path, line, tok.start[1],
                            "suppression without a reason — write "
                            "'# ballista-lint: disable=<rule> -- <why>'")
                )
                continue  # a reasonless suppression does not suppress
            covered = (line,) if not standalone else (line, line + 1)
            self.suppressions.append(Suppression(covered, rules, reason, line))

    # -- annotation lookup -------------------------------------------------
    def guarded_targets(self) -> List[Tuple[ast.AST, str]]:
        """(assignment statement, lock expr) pairs for every statement a
        guarded-by comment attaches to."""
        out = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                lock = self.guarded.get(node.lineno)
                if lock:
                    out.append((node, lock))
        return out

    def holds_lock(self, func: ast.AST) -> Optional[str]:
        """Lock named by a `# holds-lock:` comment on the def's signature."""
        return self._def_annotation(func, self.holds)

    def may_acquire_of(self, func: ast.AST) -> Optional[str]:
        """Lock list named by a `# may-acquire:` comment on the def."""
        return self._def_annotation(func, self.may_acquire)

    def attempt_ok_of(self, func: ast.AST) -> Optional[str]:
        """Reason named by an `# attempt-guard-ok:` comment on the def."""
        return self._def_annotation(func, self.attempt_ok)

    def _def_annotation(self, func: ast.AST, table: Dict[int, str]) -> Optional[str]:
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        end = func.body[0].lineno if func.body else func.lineno + 1
        # lineno-1 covers a standalone annotation directly above the def
        for line in range(func.lineno - 1, end + 1):
            if line in table:
                return table[line]
        return None

    # -- suppression application -------------------------------------------
    def apply_suppressions(self, findings: List[Finding]) -> List[Finding]:
        kept = []
        for f in findings:
            hit = None
            for s in self.suppressions:
                if f.rule in s.rules and f.line in s.lines:
                    hit = s
                    break
            if hit is None:
                kept.append(f)
            else:
                hit.used = True
        for s in self.suppressions:
            if not s.used:
                kept.append(
                    Finding(META_RULE, self.path, s.comment_line, 0,
                            f"unused suppression for {', '.join(s.rules)} — "
                            "remove it or move it onto the flagged line")
                )
        return kept


# -- per-file analysis -------------------------------------------------------

def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _display_path(path: str) -> str:
    ap = os.path.abspath(path)
    root = _repo_root()
    return os.path.relpath(ap, root) if ap.startswith(root + os.sep) else path


def _analyze(path: str) -> Tuple[List[Finding], int, dict, Dict[str, float]]:
    """(surviving findings, reasoned-suppression count, facts, per-rule
    wall seconds) for one file — one read/parse/tokenize pass serves all
    four. Facts feed the whole-program passes (lock-order graph, durability
    coverage) and are cached beside the findings; timings are never cached
    (they describe THIS run's work, ISSUE 18 satellite)."""
    import time as _time

    _load_rules()
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        sf = SourceFile(path, source, _display_path(path))
    except SyntaxError as e:
        return [Finding(META_RULE, _display_path(path), e.lineno or 1, 0,
                        f"syntax error: {e.msg}")], 0, {}, {}
    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    for name, check in sorted(_REGISTRY.items()):
        t0 = _time.perf_counter()
        findings.extend(check(sf))
        timings[name] = timings.get(name, 0.0) + (_time.perf_counter() - t0)
    findings = sf.apply_suppressions(findings)
    findings.extend(sf.meta_findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    facts = {}
    for name, fn in sorted(_FACTS.items()):
        t0 = _time.perf_counter()
        facts[name] = fn(sf)
        # fact extraction bills to its rule: the cost is real either way
        timings[name] = timings.get(name, 0.0) + (_time.perf_counter() - t0)
    return findings, len(sf.suppressions), facts, timings


def _global_findings(
    facts_by_path: Dict[str, dict],
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Run every whole-program pass over the collected per-file facts.
    When `timings` is given, each pass's wall seconds accumulate into it
    under the pass's rule name."""
    import time as _time

    _load_rules()
    findings: List[Finding] = []
    for name, fn in sorted(_GLOBAL.items()):
        t0 = _time.perf_counter()
        findings.extend(fn(facts_by_path))
        if timings is not None:
            timings[name] = timings.get(name, 0.0) + (
                _time.perf_counter() - t0
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_file(path: str) -> List[Finding]:
    """All surviving findings for one file (suppressions applied) —
    including the whole-program passes scoped to just this file, so a
    single-file CLI run (and the fixture pair tests) exercise the
    lock-order graph checks."""
    findings, _n, facts, _t = _analyze(path)
    findings = findings + _global_findings({_display_path(path): facts})
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def suppression_count(path: str) -> int:
    """Reasoned suppressions present in a file (for budget accounting)."""
    return _analyze(path)[1]


# -- cache -------------------------------------------------------------------

CACHE_BASENAME = ".ballista_lint_cache.json"


def _analyzer_hash() -> str:
    """Hash of the analyzer's own sources AND the in-tree manifests: a
    rule or manifest change invalidates every cached verdict."""
    d = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha1()
    for name in sorted(os.listdir(d)):
        if name.endswith(".py") or name.endswith(".toml"):
            with open(os.path.join(d, name), "rb") as f:
                h.update(name.encode())
                h.update(f.read())
    return h.hexdigest()[:16]


def durability_manifest_path() -> str:
    """dev/analysis/durability.toml, overridable via
    BALLISTA_DURABILITY_MANIFEST (tests point it at scratch manifests)."""
    return os.environ.get("BALLISTA_DURABILITY_MANIFEST") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "durability.toml"
    )


def _manifest_hash() -> str:
    """Hash of the manifests as RESOLVED right now (env overrides
    included). Folded into every per-file cache key: per-file findings
    depend on the manifests (durability agreement, ISSUE 18), and the
    blob-level analyzer hash only covers the in-tree copies — an
    env-overridden manifest edit used to leave stale per-file verdicts
    until an analyzer-hash bump."""
    from dev.analysis.lockgraph import default_manifest_path

    h = hashlib.sha1()
    for path in (default_manifest_path(), durability_manifest_path()):
        h.update(path.encode())
        try:
            with open(path, "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"<absent>")
    return h.hexdigest()[:12]


class FileCache:
    def __init__(self, cache_path: Optional[str]):
        self.cache_path = cache_path
        self.data: Dict[str, dict] = {}
        self.dirty = False
        self.hits = 0
        self._ahash = _analyzer_hash()
        self._mhash = _manifest_hash()
        if cache_path and os.path.exists(cache_path):
            try:
                with open(cache_path) as f:
                    blob = json.load(f)
                if blob.get("analyzer") == self._ahash:
                    self.data = blob.get("files", {})
            except (OSError, ValueError):
                pass

    def _key(self, path: str) -> str:
        st = os.stat(path)
        return f"{st.st_mtime_ns}:{st.st_size}:{self._mhash}"

    def get(self, path: str) -> Optional[Tuple[List[Finding], int, dict]]:
        ap = os.path.abspath(path)
        ent = self.data.get(ap)
        if ent is None or ent.get("key") != self._key(path):
            return None
        self.hits += 1
        return (
            [Finding(**f) for f in ent["findings"]],
            ent.get("suppressions", 0),
            ent.get("facts", {}),
        )

    def put(self, path: str, findings: List[Finding], suppressions: int,
            facts: dict) -> None:
        ap = os.path.abspath(path)
        self.data[ap] = {
            "key": self._key(path),
            "findings": [f.to_dict() for f in findings],
            "suppressions": suppressions,
            "facts": facts,
        }
        self.dirty = True

    def save(self) -> None:
        if not self.cache_path or not self.dirty:
            return
        tmp = self.cache_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"analyzer": self._ahash, "files": self.data}, f)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass


# -- runner ------------------------------------------------------------------

def collect_py_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".jax_cache")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif p.endswith(".py"):
            out.append(p)
    return out


def _analyze_for_pool(
    path: str,
) -> Tuple[str, List[dict], int, dict, Dict[str, float]]:
    """Process-pool worker: one file, serialized findings (dicts pickle
    smaller and version-stably across pool boundaries)."""
    findings, n_supp, facts, timings = _analyze(path)
    return path, [f.to_dict() for f in findings], n_supp, facts, timings


def run_paths(paths: List[str], use_cache: bool = True,
              cache_path: Optional[str] = None,
              jobs: int = 1) -> Tuple[List[Finding], dict]:
    """Analyze every .py under `paths`. Returns (findings, stats).

    `jobs` > 1 fans the per-file analysis over a process pool (ISSUE 14:
    the strict lint gate stops being serial as rule count grows) with the
    SAME cache semantics — cached files never hit the pool, fresh results
    land in the cache identically — and a deterministic report order
    (results are reassembled in file order regardless of completion
    order). The whole-program lock-order pass then runs over every file's
    facts, cached or fresh; its findings depend on OTHER files and are
    recomputed each run, never cached."""
    _load_rules()
    files = collect_py_files(paths)
    if use_cache and cache_path is None:
        cache_path = os.path.join(_repo_root(), CACHE_BASENAME)
    cache = FileCache(cache_path if use_cache else None)
    per_file: Dict[str, Tuple[List[Finding], int, dict]] = {}
    rule_wall: Dict[str, float] = {}
    fresh = []
    for path in files:
        cached = cache.get(path) if use_cache else None
        if cached is not None:
            per_file[path] = cached
        else:
            fresh.append(path)
    if fresh and jobs > 1:
        import concurrent.futures

        with concurrent.futures.ProcessPoolExecutor(max_workers=jobs) as ex:
            for path, fdicts, n_supp, facts, timings in ex.map(
                _analyze_for_pool, fresh, chunksize=4
            ):
                per_file[path] = ([Finding(**d) for d in fdicts], n_supp, facts)
                for rule, secs in timings.items():
                    rule_wall[rule] = rule_wall.get(rule, 0.0) + secs
    else:
        for path in fresh:
            findings_f, n_supp, facts, timings = _analyze(path)
            per_file[path] = (findings_f, n_supp, facts)
            for rule, secs in timings.items():
                rule_wall[rule] = rule_wall.get(rule, 0.0) + secs
    findings: List[Finding] = []
    n_suppressions = 0
    facts_by_path: Dict[str, dict] = {}
    fresh_set = set(fresh)
    for path in files:
        result, n_supp, facts = per_file[path]
        if use_cache and path in fresh_set:
            cache.put(path, result, n_supp, facts)
        findings.extend(result)
        n_suppressions += n_supp
        facts_by_path[_display_path(path)] = facts
    cache.save()
    findings.extend(_global_findings(facts_by_path, timings=rule_wall))
    # per-rule finding counts + wall seconds (ISSUE 18 satellite): CI logs
    # make a rule whose cost regresses visible. Wall covers FRESH analyses
    # + the global passes; cached files cost (and bill) nothing.
    by_rule: Dict[str, dict] = {}
    for f in findings:
        by_rule.setdefault(f.rule, {"findings": 0, "wall_s": 0.0})
        by_rule[f.rule]["findings"] += 1
    for rule, secs in rule_wall.items():
        by_rule.setdefault(rule, {"findings": 0, "wall_s": 0.0})
        by_rule[rule]["wall_s"] = round(secs, 4)
    stats = {
        "files": len(files),
        "cache_hits": cache.hits,
        "suppressions": n_suppressions,
        "findings": len(findings),
        "rules": dict(sorted(by_rule.items())),
    }
    return findings, stats


def collect_facts(paths: List[str], use_cache: bool = True,
                  cache_path: Optional[str] = None) -> Dict[str, dict]:
    """Per-file facts for every .py under `paths` (display path -> facts)
    — the static side of the witness cross-check."""
    _load_rules()
    files = collect_py_files(paths)
    if use_cache and cache_path is None:
        cache_path = os.path.join(_repo_root(), CACHE_BASENAME)
    cache = FileCache(cache_path if use_cache else None)
    out: Dict[str, dict] = {}
    for path in files:
        cached = cache.get(path) if use_cache else None
        if cached is not None:
            out[_display_path(path)] = cached[2]
        else:
            findings, n_supp, facts, _t = _analyze(path)
            if use_cache:
                cache.put(path, findings, n_supp, facts)
            out[_display_path(path)] = facts
    cache.save()
    return out

"""Framework: findings, per-file source model (comments, suppressions,
annotations), rule registry, per-file cache, and the directory runner."""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Dict, List, Optional, Tuple

META_RULE = "lint-usage"

# populated by dev.analysis.rules at import time (rule name -> check fn)
_REGISTRY: Dict[str, object] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def RULE_NAMES() -> List[str]:
    _load_rules()
    return sorted(_REGISTRY) + [META_RULE]


def _load_rules() -> None:
    if _REGISTRY:
        return
    from dev.analysis import (  # noqa: F401
        rules_decline,
        rules_dtype,
        rules_failure,
        rules_guarded,
        rules_readback,
        rules_routing,
        rules_tracer,
    )


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_DIRECTIVE_RE = re.compile(r"#\s*ballista-lint:\s*(.*)")
_DISABLE_RE = re.compile(r"disable=([\w.,-]+)(?:\s*--\s*(.*\S))?\s*$")
_PATH_RE = re.compile(r"path=(\S+)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\S[^#]*?)\s*$")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(\S[^#]*?)\s*$")


@dataclasses.dataclass
class Suppression:
    lines: Tuple[int, ...]  # physical lines this suppression covers
    rules: Tuple[str, ...]
    reason: Optional[str]
    comment_line: int
    used: bool = False


class SourceFile:
    """Parsed view of one file: AST + comment-driven directives.

    `path` is the display/scoping path: relative to the repo root when the
    file lives under it, and overridable by a `# ballista-lint: path=...`
    header so test fixtures can exercise device-path-scoped rules."""

    def __init__(self, real_path: str, source: str, display_path: str):
        self.real_path = real_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=real_path)
        self.suppressions: List[Suppression] = []
        self.guarded: Dict[int, str] = {}  # line -> lock expr
        self.holds: Dict[int, str] = {}  # line -> lock expr
        self.meta_findings: List[Finding] = []
        self.path = display_path
        self._scan_comments()

    # -- comment scanning --------------------------------------------------
    def _scan_comments(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(self.source).readline))
        except tokenize.TokenError:
            return
        known = set(_REGISTRY) | {META_RULE}
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            standalone = self.lines[line - 1][: tok.start[1]].strip() == ""
            text = tok.string
            g = _GUARDED_RE.search(text)
            if g:
                # a standalone annotation covers the next line's statement
                self.guarded[line if not standalone else line + 1] = g.group(1).strip()
            h = _HOLDS_RE.search(text)
            if h:
                self.holds[line] = h.group(1).strip()
            m = _DIRECTIVE_RE.search(text)
            if not m:
                continue
            body = m.group(1).strip()
            if line <= 10 and _PATH_RE.match(body):
                self.path = _PATH_RE.match(body).group(1)
                continue
            d = _DISABLE_RE.match(body)
            if not d:
                self.meta_findings.append(
                    Finding(META_RULE, self.path, line, tok.start[1],
                            f"unrecognized ballista-lint directive: {body!r}")
                )
                continue
            rules = tuple(r.strip() for r in d.group(1).split(",") if r.strip())
            reason = d.group(2)
            unknown = [r for r in rules if r not in known]
            if unknown:
                self.meta_findings.append(
                    Finding(META_RULE, self.path, line, tok.start[1],
                            f"suppression names unknown rule(s) {unknown}; "
                            f"known: {sorted(known)}")
                )
            if not reason:
                self.meta_findings.append(
                    Finding(META_RULE, self.path, line, tok.start[1],
                            "suppression without a reason — write "
                            "'# ballista-lint: disable=<rule> -- <why>'")
                )
                continue  # a reasonless suppression does not suppress
            covered = (line,) if not standalone else (line, line + 1)
            self.suppressions.append(Suppression(covered, rules, reason, line))

    # -- annotation lookup -------------------------------------------------
    def guarded_targets(self) -> List[Tuple[ast.AST, str]]:
        """(assignment statement, lock expr) pairs for every statement a
        guarded-by comment attaches to."""
        out = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                lock = self.guarded.get(node.lineno)
                if lock:
                    out.append((node, lock))
        return out

    def holds_lock(self, func: ast.AST) -> Optional[str]:
        """Lock named by a `# holds-lock:` comment on the def's signature."""
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        end = func.body[0].lineno if func.body else func.lineno + 1
        # lineno-1 covers a standalone annotation directly above the def
        for line in range(func.lineno - 1, end + 1):
            if line in self.holds:
                return self.holds[line]
        return None

    # -- suppression application -------------------------------------------
    def apply_suppressions(self, findings: List[Finding]) -> List[Finding]:
        kept = []
        for f in findings:
            hit = None
            for s in self.suppressions:
                if f.rule in s.rules and f.line in s.lines:
                    hit = s
                    break
            if hit is None:
                kept.append(f)
            else:
                hit.used = True
        for s in self.suppressions:
            if not s.used:
                kept.append(
                    Finding(META_RULE, self.path, s.comment_line, 0,
                            f"unused suppression for {', '.join(s.rules)} — "
                            "remove it or move it onto the flagged line")
                )
        return kept


# -- per-file analysis -------------------------------------------------------

def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _display_path(path: str) -> str:
    ap = os.path.abspath(path)
    root = _repo_root()
    return os.path.relpath(ap, root) if ap.startswith(root + os.sep) else path


def _analyze(path: str) -> Tuple[List[Finding], int]:
    """(surviving findings, reasoned-suppression count) for one file —
    one read/parse/tokenize pass serves both."""
    _load_rules()
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    try:
        sf = SourceFile(path, source, _display_path(path))
    except SyntaxError as e:
        return [Finding(META_RULE, _display_path(path), e.lineno or 1, 0,
                        f"syntax error: {e.msg}")], 0
    findings: List[Finding] = []
    for name, check in sorted(_REGISTRY.items()):
        findings.extend(check(sf))
    findings = sf.apply_suppressions(findings)
    findings.extend(sf.meta_findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, len(sf.suppressions)


def analyze_file(path: str) -> List[Finding]:
    """All surviving findings for one file (suppressions applied)."""
    return _analyze(path)[0]


def suppression_count(path: str) -> int:
    """Reasoned suppressions present in a file (for budget accounting)."""
    return _analyze(path)[1]


# -- cache -------------------------------------------------------------------

CACHE_BASENAME = ".ballista_lint_cache.json"


def _analyzer_hash() -> str:
    """Hash of the analyzer's own sources: a rule change invalidates every
    cached verdict."""
    d = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha1()
    for name in sorted(os.listdir(d)):
        if name.endswith(".py"):
            with open(os.path.join(d, name), "rb") as f:
                h.update(name.encode())
                h.update(f.read())
    return h.hexdigest()[:16]


class FileCache:
    def __init__(self, cache_path: Optional[str]):
        self.cache_path = cache_path
        self.data: Dict[str, dict] = {}
        self.dirty = False
        self.hits = 0
        self._ahash = _analyzer_hash()
        if cache_path and os.path.exists(cache_path):
            try:
                with open(cache_path) as f:
                    blob = json.load(f)
                if blob.get("analyzer") == self._ahash:
                    self.data = blob.get("files", {})
            except (OSError, ValueError):
                pass

    def _key(self, path: str) -> str:
        st = os.stat(path)
        return f"{st.st_mtime_ns}:{st.st_size}"

    def get(self, path: str) -> Optional[Tuple[List[Finding], int]]:
        ap = os.path.abspath(path)
        ent = self.data.get(ap)
        if ent is None or ent.get("key") != self._key(path):
            return None
        self.hits += 1
        return [Finding(**f) for f in ent["findings"]], ent.get("suppressions", 0)

    def put(self, path: str, findings: List[Finding], suppressions: int) -> None:
        ap = os.path.abspath(path)
        self.data[ap] = {
            "key": self._key(path),
            "findings": [f.to_dict() for f in findings],
            "suppressions": suppressions,
        }
        self.dirty = True

    def save(self) -> None:
        if not self.cache_path or not self.dirty:
            return
        tmp = self.cache_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"analyzer": self._ahash, "files": self.data}, f)
            os.replace(tmp, self.cache_path)
        except OSError:
            pass


# -- runner ------------------------------------------------------------------

def collect_py_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".jax_cache")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif p.endswith(".py"):
            out.append(p)
    return out


def run_paths(paths: List[str], use_cache: bool = True,
              cache_path: Optional[str] = None) -> Tuple[List[Finding], dict]:
    """Analyze every .py under `paths`. Returns (findings, stats)."""
    _load_rules()
    files = collect_py_files(paths)
    if use_cache and cache_path is None:
        cache_path = os.path.join(_repo_root(), CACHE_BASENAME)
    cache = FileCache(cache_path if use_cache else None)
    findings: List[Finding] = []
    n_suppressions = 0
    for path in files:
        cached = cache.get(path) if use_cache else None
        if cached is not None:
            result, n_supp = cached
        else:
            result, n_supp = _analyze(path)
            if use_cache:
                cache.put(path, result, n_supp)
        findings.extend(result)
        n_suppressions += n_supp
    cache.save()
    stats = {
        "files": len(files),
        "cache_hits": cache.hits,
        "suppressions": n_suppressions,
        "findings": len(findings),
    }
    return findings, stats

"""routing-discipline: every decline is a routing decision, and routing
decisions must be observable (ISSUE 10).

The adaptive-execution bench block (`routing`) is only truthful if every
site that sends work off the device path records that it did. Any call to
one of the canonical decline helpers — ``decline`` / ``host_fallback`` /
``step_aside`` (ops/kernels.py) — in a device-path module must therefore
be paired with a routing observation in the same function (or a lexically
enclosing one):

- ``record_routing`` / ``record_routing_event`` (ops/runtime.py), or
- ``record_join_path`` (the join counters feed the same bench truth), or
- ``costmodel.observe(...)`` — qualified, so an unrelated object's
  ``.observe()`` method cannot silence the rule (the decline's cost
  became evidence).

A site that is genuinely not a routing decision — a compile-time shape
check whose consumer records the decision, a test-only shim — carries a
``# cold-path: <why>`` annotation on the call line or the line above it,
which is this rule's equivalent of guarded-by's documented opt-out: the
exemption is visible and reviewable at the site.

The helper DEFINITIONS themselves (functions named decline /
host_fallback / step_aside) are exempt — they are the channel, not a
site."""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from dev.analysis.common import (
    final_name,
    is_device_path,
    iter_functions,
    walk_no_nested_defs,
)
from dev.analysis.core import Finding, SourceFile, register

_DECLINE_HELPERS = {"decline", "host_fallback", "step_aside"}
_RECORDERS = {
    "record_routing",
    "record_routing_event",
    "record_join_path",
}
_COLD_PATH_RE = re.compile(r"#\s*cold-path:\s*\S")


def _parent_map(tree: ast.Module) -> Dict[ast.AST, Optional[ast.AST]]:
    """func def -> lexically enclosing func def (None at module level)."""
    parents: Dict[ast.AST, Optional[ast.AST]] = {}

    def rec(node, cur):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                parents[child] = cur
                rec(child, child)
            else:
                rec(child, cur)

    rec(tree, None)
    return parents


def _records_routing(func: ast.AST) -> bool:
    # walk_no_nested_defs for symmetry with the decline scan: a recorder
    # inside a nested def (possibly never invoked on the decline path)
    # must not vouch for the enclosing function — enclosing scopes vouch
    # via the parents chain in check(), never inner ones
    for node in walk_no_nested_defs(func):
        if not isinstance(node, ast.Call):
            continue
        if final_name(node.func) in _RECORDERS:
            return True
        # cost-store observation counts ONLY when qualified on the
        # costmodel module — a bare/foreign .observe() must not satisfy
        # the pairing
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "observe"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "costmodel"
        ):
            return True
    return False


@register("routing-discipline")
def check(sf: SourceFile) -> List[Finding]:
    if not is_device_path(sf.path):
        return []
    parents = _parent_map(sf.tree)
    findings: List[Finding] = []
    for func, _cls in iter_functions(sf.tree):
        if func.name in _DECLINE_HELPERS:
            continue  # the canonical channel itself, not a call site
        # walk_no_nested_defs: a nested def's calls are attributed to the
        # nested def, which iter_functions visits as its own scope
        for node in walk_no_nested_defs(func):
            if not (
                isinstance(node, ast.Call)
                and final_name(node.func) in _DECLINE_HELPERS
            ):
                continue
            # cold-path annotation on the call line or the line above
            annotated = any(
                0 < ln <= len(sf.lines)
                and _COLD_PATH_RE.search(sf.lines[ln - 1])
                for ln in (node.lineno, node.lineno - 1)
            )
            if annotated:
                continue
            # a recorder anywhere in this function or a lexically
            # enclosing one satisfies the pairing
            cur: Optional[ast.AST] = func
            recorded = False
            while cur is not None:
                if _records_routing(cur):
                    recorded = True
                    break
                cur = parents.get(cur)
            if not recorded:
                findings.append(Finding(
                    "routing-discipline", sf.path, node.lineno,
                    node.col_offset,
                    f"`{final_name(node.func)}` call without a routing "
                    "observation in scope — pair it with record_routing/"
                    "record_routing_event/record_join_path (or annotate "
                    "`# cold-path: <why>`) so the bench routing block "
                    "stays truthful",
                ))
    return findings

#!/usr/bin/env bash
# ballista-lint entry point: AST-based invariant checks (readback, tracer,
# dtype, lock, decline discipline) over the production tree. Strict: any
# finding — or more than the 5-suppression budget — fails. Pass extra
# paths/flags through, e.g. `dev/lint.sh --json` or `dev/lint.sh tests/`.
set -euo pipefail
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD:${PYTHONPATH:-}"

if [ "$#" -gt 0 ] && [ "${1#-}" = "$1" ]; then
    # explicit paths given: lint those
    exec python -m dev.analysis "$@"
fi
exec python -m dev.analysis ballista_tpu/ "$@"

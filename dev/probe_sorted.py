"""Correctness + perf probe for sorted_grouped_sum on the current backend.

python dev/probe_sorted.py            # real TPU
JAX_PLATFORMS=cpu python dev/probe_sorted.py   # interpret mode
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")  # axon overrides the env var
    import jax.numpy as jnp

    from ballista_tpu.ops.pallas_kernels import SORT_BLOCK, sorted_grouped_sum

    print("backend:", jax.default_backend())
    rng = np.random.default_rng(0)

    for N, G in ((1 << 15, 700), (6_000_000, 1_500_000), (6_000_000, 10_000)):
        if jax.default_backend() == "cpu" and N > 1 << 15:
            continue
        # sorted dense ranks with random segment lengths
        lens = rng.integers(1, max(2, 2 * N // G), G)
        codes_np = np.repeat(np.arange(G, dtype=np.int32), lens)[:N]
        if len(codes_np) < N:
            codes_np = np.concatenate(
                [codes_np, np.full(N - len(codes_np), codes_np[-1], np.int32)]
            )
        G_real = int(codes_np.max()) + 1
        v_np = rng.uniform(0, 100_000, N).astype(np.float32)
        mask_np = (rng.uniform(size=N) < 0.54).astype(np.float32)

        pad = (-N) % SORT_BLOCK
        if pad:
            codes_np = np.concatenate([codes_np, np.full(pad, codes_np[-1], np.int32)])
            v_np = np.concatenate([v_np, np.zeros(pad, np.float32)])
            mask_np = np.concatenate([mask_np, np.zeros(pad, np.float32)])

        vals_np = np.stack([mask_np, v_np * mask_np])
        codes = jnp.asarray(codes_np)
        vals = jnp.asarray(vals_np)

        out = sorted_grouped_sum(codes, vals, G_real)
        out.block_until_ready()
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = sorted_grouped_sum(codes, vals, G_real)
            out.block_until_ready()
            best = min(best, time.perf_counter() - t0)
        got = np.asarray(out, dtype=np.float64)

        oracle_sum = np.zeros(G_real)
        np.add.at(oracle_sum, codes_np, (v_np * mask_np).astype(np.float64))
        oracle_cnt = np.zeros(G_real)
        np.add.at(oracle_cnt, codes_np, mask_np.astype(np.float64))
        rel_s = np.abs(got[1] - oracle_sum).max() / max(1.0, oracle_sum.max())
        rel_c = np.abs(got[0] - oracle_cnt).max()
        print(f"N={N} G={G_real}: {best*1e3:8.2f}ms  sum maxrel {rel_s:.2e}  "
              f"count maxabs {rel_c:.1e}")


if __name__ == "__main__":
    main()

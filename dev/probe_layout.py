"""Probe v2: chunked-segment-layout aggregation (cache-time sorted residency).

Adaptive L1 (covers ~90th pct of segment lengths), fully vectorized build,
and dispatch-vs-d2h timing split.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def chunk_spans(starts: np.ndarray, lens: np.ndarray, L: int):
    """Vectorized: split each [start, start+len) into chunks of <= L rows.
    Returns take-index [V, L], pad mask [V, L] f32, owner [V] (group of each
    chunk), all in group order."""
    nchunks = np.maximum(-(-lens // L), 1)
    V = int(nchunks.sum())
    owner = np.repeat(np.arange(len(lens)), nchunks)
    # position of each chunk within its group, vectorized
    firsts = np.zeros(V, dtype=np.int64)
    firsts[np.cumsum(nchunks)[:-1]] = nchunks[:-1]
    chunk_pos = np.arange(V) - np.cumsum(firsts) + firsts.cumsum() * 0
    # simpler: global arange minus repeated group-chunk-offsets
    offs = np.repeat(np.cumsum(nchunks) - nchunks, nchunks)
    chunk_pos = np.arange(V) - offs
    cstart = starts[owner] + chunk_pos * L
    clen = np.minimum(lens[owner] - chunk_pos * L, L)
    clen = np.maximum(clen, 0)
    idx = cstart[:, None] + np.arange(L)[None, :]
    pad = np.arange(L)[None, :] < clen[:, None]
    idx = np.where(pad, idx, 0)
    return idx.astype(np.int32), pad.astype(np.float32), owner


def build_layout(codes_sorted: np.ndarray, L2: int = 128):
    G = int(codes_sorted[-1]) + 1
    starts = np.searchsorted(codes_sorted, np.arange(G))
    ends = np.searchsorted(codes_sorted, np.arange(G), side="right")
    lens = ends - starts
    # L1: power of two covering the 90th percentile length, in [8, 1024]
    p90 = int(np.percentile(lens, 90)) if len(lens) else 8
    L1 = 8
    while L1 < p90 and L1 < 1024:
        L1 <<= 1
    idx1, pad1, owner = chunk_spans(starts, lens, L1)
    levels = [(idx1, pad1)]
    while len(owner) != G:
        o_starts = np.searchsorted(owner, np.arange(G))
        o_ends = np.searchsorted(owner, np.arange(G), side="right")
        idx, pad, owner = chunk_spans(o_starts, o_ends - o_starts, L2)
        levels.append((idx, pad))
    return levels, G, L1


def main():
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    print("backend:", jax.default_backend())
    rng = np.random.default_rng(0)

    cases = [("q3ish", 6_000_000, None, "lineitem"),
             ("taxi", 10_000_000, 10_000, "zipf")]
    for name, N, G_req, kind in cases:
        if kind == "lineitem":
            lens = rng.integers(1, 8, N // 4)
            codes_all = np.repeat(np.arange(len(lens), dtype=np.int32), lens)[:N]
        else:
            z = rng.zipf(1.3, N).astype(np.int64)
            codes_all = (z % G_req).astype(np.int32)
            _, codes_all = np.unique(codes_all, return_inverse=True)
            codes_all = codes_all.astype(np.int32)
        if len(codes_all) < N:
            codes_all = np.concatenate(
                [codes_all, np.full(N - len(codes_all), codes_all[-1], np.int32)])
        codes_all = np.sort(codes_all[:N])
        v_np = rng.uniform(0, 100_000, N).astype(np.float32)
        filt_np = rng.uniform(0, 1, N).astype(np.float32)

        t0 = time.perf_counter()
        levels, G, L1 = build_layout(codes_all)
        t_build = time.perf_counter() - t0
        idx1, pad1 = levels[0]
        V1 = pad1.shape[0]
        waste = V1 * L1 / N

        t0 = time.perf_counter()
        v_l = jnp.asarray(v_np[idx1.reshape(-1)].reshape(V1, L1))
        f_l = jnp.asarray(filt_np[idx1.reshape(-1)].reshape(V1, L1))
        pad1_d = jnp.asarray(pad1)
        upper = [(jnp.asarray(i), jnp.asarray(p)) for i, p in levels[1:]]
        jax.block_until_ready((v_l, f_l, pad1_d))
        t_resid = time.perf_counter() - t0
        print(f"\n{name}: N={N} G={G} L1={L1} layout={V1}x{L1} "
              f"(waste {waste:.2f}x) levels={[p.shape for _, p in levels]} "
              f"build={t_build*1e3:.0f}ms resid={t_resid*1e3:.0f}ms")

        @jax.jit
        def query(v_l, f_l, pad1_d, cutoff):
            mask = (f_l > cutoff).astype(jnp.float32) * pad1_d
            s = jnp.sum(v_l * mask, axis=1)
            c = jnp.sum(mask, axis=1)
            for idx, pad in upper:
                s = jnp.sum(s[idx] * pad, axis=1)
                c = jnp.sum(c[idx] * pad, axis=1)
            return jnp.stack([s, c])

        out = query(v_l, f_l, pad1_d, 0.46)
        out.block_until_ready()
        t_disp = t_tot = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            out = query(v_l, f_l, pad1_d, 0.46)
            out.block_until_ready()
            t1 = time.perf_counter()
            got = np.asarray(out)
            t2 = time.perf_counter()
            t_disp = min(t_disp, t1 - t0)
            t_tot = min(t_tot, t2 - t0)

        m = filt_np > 0.46
        oracle = np.zeros(G)
        np.add.at(oracle, codes_all[m], v_np[m].astype(np.float64))
        rel = np.abs(got[0].astype(np.float64) - oracle).max() / max(1, oracle.max())
        t0 = time.perf_counter()
        w = np.where(m, v_np, 0).astype(np.float64)
        np.bincount(codes_all, weights=w, minlength=G)
        t_host = time.perf_counter() - t0
        print(f"  compute {t_disp*1e3:7.2f}ms  +d2h {t_tot*1e3:7.2f}ms  "
              f"maxrel {rel:.1e}   host bincount(f64): {t_host*1e3:.0f}ms")


if __name__ == "__main__":
    main()

"""Measure high-cardinality grouped-aggregation strategies on the real chip.

Candidates for G > 1024 (where the unrolled per-group path stops scaling):
  A. two-level one-hot matmul: code = hi*K2 + lo; out[hi, lo] accumulated as
     H^T @ (L * v) per row block on the MXU. FLOPs = 2*N*G*n_out.
  B. XLA segment_sum (scatter lowering), unsorted vs sorted codes.
  C. device argsort cost (per-query sort if we wanted sort-based agg).
  D. host baselines: np.bincount and pyarrow group_by.

Run: python dev/probe_highcard.py  (real TPU via default env)
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")


def bench(fn, *args, reps=3):
    out = fn(*args)
    out.block_until_ready()  # compile + warmup
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        out.block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best, out


def main():
    import jax
    import jax.numpy as jnp
    from functools import partial

    print("backend:", jax.default_backend(), jax.devices())

    N = 6_000_000
    rng = np.random.default_rng(0)
    v_np = rng.uniform(0.0, 100_000.0, N).astype(np.float32)
    mask_np = rng.uniform(size=N) < 0.54

    for G in (8192, 131072):
        codes_np = rng.integers(0, G, N).astype(np.int32)
        # f64 oracle
        oracle = np.zeros(G)
        np.add.at(oracle, codes_np[mask_np], v_np[mask_np].astype(np.float64))
        ocnt = np.zeros(G)
        np.add.at(ocnt, codes_np[mask_np], 1.0)

        t0 = time.perf_counter()
        w = np.where(mask_np, v_np, 0).astype(np.float64)
        hb = np.bincount(codes_np, weights=w, minlength=G)
        t_host = time.perf_counter() - t0
        print(f"\nG={G}  host np.bincount(f64): {t_host*1e3:.1f}ms "
              f"(relerr {np.abs(hb - oracle).max() / max(1, oracle.max()):.1e})")

        codes = jnp.asarray(codes_np)
        v = jnp.asarray(v_np)
        mask = jnp.asarray(mask_np)

        def acc(got, name, t):
            got = np.asarray(got, dtype=np.float64)
            rel = np.abs(got - oracle).max() / max(1.0, np.abs(oracle).max())
            print(f"  {name:42s} {t*1e3:8.1f}ms  maxrel {rel:.1e}")

        # --- A: two-level matmul ---------------------------------------
        for K2 in (128, 256):
            K1 = G // K2
            for prec_name in ("default", "split2", "highest"):

                @partial(jax.jit, static_argnames=("k1", "k2", "prec"))
                def two_level(codes, v, mask, k1, k2, prec):
                    B = 1 << 16
                    nb = codes.shape[0] // B

                    def body(carry, xs):
                        c, vv, m = xs
                        hi = c // k2
                        lo = c % k2
                        mv = vv * m.astype(jnp.float32)
                        H = (hi[:, None] == jax.lax.broadcasted_iota(
                            jnp.int32, (1, k1), 1)).astype(jnp.float32)
                        L = (lo[:, None] == jax.lax.broadcasted_iota(
                            jnp.int32, (1, k2), 1)).astype(jnp.float32)
                        M = L * mv[:, None]
                        Mc = L * m.astype(jnp.float32)[:, None]
                        if prec == "split2":
                            M1 = M.astype(jnp.bfloat16).astype(jnp.float32)
                            M2 = M - M1
                            s = (jnp.dot(H.T, M1, preferred_element_type=jnp.float32)
                                 + jnp.dot(H.T, M2, preferred_element_type=jnp.float32))
                        else:
                            p = (jax.lax.Precision.HIGHEST if prec == "highest"
                                 else jax.lax.Precision.DEFAULT)
                            s = jnp.dot(H.T, M, precision=p,
                                        preferred_element_type=jnp.float32)
                        cs = jnp.dot(H.T, Mc, precision=jax.lax.Precision.DEFAULT,
                                     preferred_element_type=jnp.float32)
                        return (carry[0] + s, carry[1] + cs), None

                    init = (jnp.zeros((k1, k2), jnp.float32),
                            jnp.zeros((k1, k2), jnp.float32))
                    (s, cs), _ = jax.lax.scan(
                        body, init,
                        (codes.reshape(nb, B), v.reshape(nb, B),
                         mask.reshape(nb, B)))
                    return jnp.stack([s.reshape(-1), cs.reshape(-1)])

                try:
                    t, out = bench(two_level, codes, v, mask, K1, K2, prec_name)
                    acc(np.asarray(out)[0], f"two_level K2={K2} {prec_name}", t)
                except Exception as e:
                    print(f"  two_level K2={K2} {prec_name}: FAIL {type(e).__name__} {e}"[:200])

        # --- B: segment_sum --------------------------------------------
        @jax.jit
        def seg_unsorted(codes, v, mask):
            return jax.ops.segment_sum(v * mask.astype(jnp.float32), codes,
                                       num_segments=G)

        try:
            t, out = bench(seg_unsorted, codes, v, mask)
            acc(out, "segment_sum unsorted", t)
        except Exception as e:
            print("  segment_sum unsorted FAIL", repr(e)[:120])

        order = np.argsort(codes_np, kind="stable")
        codes_s = jnp.asarray(codes_np[order])
        v_s = jnp.asarray(v_np[order])
        mask_s = jnp.asarray(mask_np[order])

        @jax.jit
        def seg_sorted(codes, v, mask):
            return jax.ops.segment_sum(v * mask.astype(jnp.float32), codes,
                                       num_segments=G, indices_are_sorted=True)

        try:
            t, out = bench(seg_sorted, codes_s, v_s, mask_s)
            acc(out, "segment_sum sorted", t)
        except Exception as e:
            print("  segment_sum sorted FAIL", repr(e)[:120])

        # --- C: device argsort -----------------------------------------
        @jax.jit
        def dev_sort(codes):
            return jnp.argsort(codes)

        try:
            t, _ = bench(dev_sort, codes)
            print(f"  {'device argsort(int32)':42s} {t*1e3:8.1f}ms")
        except Exception as e:
            print("  device argsort FAIL", repr(e)[:120])


if __name__ == "__main__":
    main()

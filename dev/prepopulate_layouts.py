"""Pre-populate the persisted device-layout cache for bench.py's configs.

Runs each bench config's device-backend side ONCE on CPU jax (the host
prepare — decode, encode, rank, sort, materialize, narrow — is identical on
any jax platform, and the persisted artifact is host-side numpy), so a later
relay-attached bench run skips straight to the h2d transfer. Each config
runs in its OWN subprocess: a SF=100 prepare's host peak is tens of GB and
earlier configs' pinned residency must not stack under it (the in-process
loop OOM-killed a 125 GB host). Holds a flock on /tmp/ballista_prepop.lock
while running; dev/relay_watch.sh waits on it so a live-relay capture never
shares the machine with this scan-heavy job.

Usage (from the repo root, relay-free CPU env):
  env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
      JAX_PLATFORMS=cpu python dev/prepopulate_layouts.py            # all
  ... python dev/prepopulate_layouts.py q5 100.0                     # one
"""

import os
import pathlib
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
os.chdir(REPO)  # the layout-cache default dir is cwd-relative

import jax

jax.config.update("jax_platforms", "cpu")

LOCK = pathlib.Path("/tmp/ballista_prepop.lock")
_lock_fh = None  # held open for the process lifetime


def _acquire_lock() -> bool:
    """flock-based mutual exclusion: released automatically on process
    death, so stale locks cannot exist and there is no check-then-unlink
    race. relay_watch.sh tests the same lock with `flock -n ... true`."""
    global _lock_fh
    import fcntl

    # "a" not "w": must not truncate a pre-flock-scheme holder's pid record
    # before knowing the lock is ours
    _lock_fh = open(LOCK, "a")
    try:
        fcntl.flock(_lock_fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except BlockingIOError:
        print("[prepop] another instance holds the lock", flush=True)
        return False
    # legacy holder (no flock, pid content): defer if it is still alive
    try:
        pid = int(LOCK.read_text().strip() or "0")
        if pid > 0 and pid != os.getpid():
            os.kill(pid, 0)
            print(f"[prepop] legacy instance (pid {pid}) is running",
                  flush=True)
            return False
    except (ValueError, ProcessLookupError, OSError):
        pass
    _lock_fh.seek(0)
    _lock_fh.truncate()
    _lock_fh.write(str(os.getpid()))
    _lock_fh.flush()
    return True


def _release_lock() -> None:
    # truncate before release: a later run must not mistake OUR stale
    # pid (possibly recycled) for a live legacy holder
    try:
        _lock_fh.seek(0)
        _lock_fh.truncate()
    except OSError:
        pass
    _lock_fh.close()  # releases the flock; the file itself stays


def run_one(name: str, sf: float) -> None:
    """Prepare one config in THIS process (child mode)."""
    import bench

    sql = (bench.QUERIES_DIR / f"{name}.sql").read_text()
    bench.run_once("tpu", sql, sf)


def main() -> None:
    if not _acquire_lock():
        return
    try:
        import subprocess

        import bench
        from benchmarks.tpch.datagen import is_complete

        for sf, name in bench.CONFIGS:
            try:
                if not is_complete(str(bench.data_dir(sf))):
                    print(f"[prepop] {name} sf={sf}: dataset absent, skipped",
                          flush=True)
                    continue
                t0 = time.monotonic()
                # child stdout/stderr stream to ours: progress stays live
                r = subprocess.run(
                    [sys.executable, str(REPO / "dev" /
                                         "prepopulate_layouts.py"),
                     name, str(sf)],
                )
                status = "ok" if r.returncode == 0 else f"rc={r.returncode}"
                print(f"[prepop] {name} sf={sf}: {status} "
                      f"{time.monotonic()-t0:.1f}s", flush=True)
            except Exception as e:
                print(f"[prepop] {name} sf={sf}: failed: {e}", flush=True)
    finally:
        _release_lock()


if __name__ == "__main__":
    if len(sys.argv) == 3:
        run_one(sys.argv[1], float(sys.argv[2]))  # child: no lock, one config
    else:
        main()

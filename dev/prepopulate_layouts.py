"""Pre-populate the persisted device-layout cache for bench.py's configs.

Runs each bench config's device-backend side ONCE on CPU jax (the host
prepare — decode, encode, rank, sort, materialize, narrow — is identical on
any jax platform, and the persisted artifact is host-side numpy), so a later
relay-attached bench run skips straight to the h2d transfer. Holds
/tmp/ballista_prepop.lock while running; dev/relay_watch.sh waits on it so a
live-relay capture never shares the machine with this scan-heavy job.

Usage: run from the repo root with the relay-free CPU env:
  env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
      JAX_PLATFORMS=cpu python dev/prepopulate_layouts.py
"""

import os
import pathlib
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
os.chdir(REPO)  # the layout-cache default dir is cwd-relative

import jax

jax.config.update("jax_platforms", "cpu")

LOCK = pathlib.Path("/tmp/ballista_prepop.lock")


def _acquire_lock() -> bool:
    """Exclusive-create the lock; a live holder wins, a dead one is replaced."""
    while True:
        try:
            fd = os.open(LOCK, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            with os.fdopen(fd, "w") as f:
                f.write(str(os.getpid()))
            return True
        except FileExistsError:
            try:
                pid = int(LOCK.read_text().strip() or "0")
            except (OSError, ValueError):
                pid = 0
            if pid > 0:
                try:
                    os.kill(pid, 0)
                    print(f"[prepop] another instance (pid {pid}) is running",
                          flush=True)
                    return False
                except ProcessLookupError:
                    pass
            LOCK.unlink(missing_ok=True)  # stale: retry the exclusive create


def main() -> None:
    if not _acquire_lock():
        return
    try:
        import bench

        for sf, name in bench.CONFIGS:
            try:
                from benchmarks.tpch.datagen import is_complete

                if not is_complete(str(bench.data_dir(sf))):
                    print(f"[prepop] {name} sf={sf}: dataset absent, skipped",
                          flush=True)
                    continue
                sql = (bench.QUERIES_DIR / f"{name}.sql").read_text()
                t0 = time.monotonic()
                bench.run_once("tpu", sql, sf)
                print(f"[prepop] {name} sf={sf}: {time.monotonic()-t0:.1f}s",
                      flush=True)
            except Exception as e:
                print(f"[prepop] {name} sf={sf}: failed: {e}", flush=True)
    finally:
        LOCK.unlink(missing_ok=True)


if __name__ == "__main__":
    main()

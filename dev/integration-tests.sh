#!/usr/bin/env bash
# Integration test driver (ref dev/integration-tests.sh + rust/benchmarks/tpch/run.sh):
# generate TPC-H data, start a cluster, run the reference's integration query
# set (q1, q3, q5, q6, q10, q12) through a real scheduler + executors.
set -euo pipefail
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD:${PYTHONPATH:-}"

DATA=${DATA:-/tmp/ballista-tpu-it}
SF=${SF:-0.01}

# strict static-analysis gate FIRST: the device-path invariants (readback
# accounting, tracer hygiene, dtype narrowing, lock discipline, decline
# ladder) and the scheduler durability contract (KV write-through,
# recover() coverage, replica-coherence classification — ISSUE 18) are
# machine-checked before anything executes — a violation fails the tier
# in seconds instead of surfacing as a wrong bench number later.
# --jobs 8 (ISSUE 15 satellite, PR 14 residue): per-file analysis fans out
# over a process pool — 5.2s -> 1.6s cold on a 24-core box — with output
# and cache semantics identical to serial (pinned by
# tests/test_lockorder.py::test_jobs_parallel_matches_serial_and_caches).
python -m dev.analysis --jobs 8 ballista_tpu/

[ -d "$DATA/lineitem" ] || python -m benchmarks.tpch.runner datagen --sf "$SF" --out "$DATA" --parts 2

python - <<'PY'
import os, pathlib, sys
sys.path.insert(0, os.getcwd())
from ballista_tpu.client import BallistaContext
from ballista_tpu.executor.runtime import StandaloneCluster
from benchmarks.tpch.datagen import register_all

data = os.environ.get("DATA", "/tmp/ballista-tpu-it")
cluster = StandaloneCluster(n_executors=2)
ctx = BallistaContext(*cluster.scheduler_addr)
register_all(ctx, data)
for q in (1, 3, 5, 6, 10, 12):
    sql = pathlib.Path(f"benchmarks/tpch/queries/q{q}.sql").read_text()
    out = ctx.sql(sql).collect()
    print(f"q{q}: OK ({out.num_rows} rows)")
cluster.shutdown()
print("integration tests passed")
PY

# cross-engine comparison on the same data: hand-written pyarrow
# implementations validate the CI query set (the reference's Spark
# comparison role); host engine only — the TPU relay may be absent in CI
python -m benchmarks.compare --data "$DATA" \
    --queries q1 q3 q5 q6 q10 q12 --iterations 1 --engines host pyarrow --strict

# strict gate on the fused Sort+Limit epilogue, the float-bits bijection,
# and the M:N join multiplicity kernel: these modules are the bit-exactness
# contract for the O(limit) readback, q2's device path, and duplicate-key
# joins staying on device — a regression here must fail the tier loudly,
# not vanish into a silent host fallback
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_floatbits.py tests/test_topk_epilogue.py \
    tests/test_join_multiplicity.py

# strict gate on failure recovery (ISSUE 5): bounded retries with attempt
# history, lineage-based shuffle recovery (fetch_failed -> map recompute),
# the poll-loop TOCTOU fix, transient-RPC backoff, and the seeded chaos
# acceptance runs. Chaos verdicts are pure functions of (seed, site,
# plan-coordinate key) — no wall-clock or RNG flake by construction — and
# the chaos runs must stay bit-identical to the fault-free runs.
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_chaos.py tests/test_fault_tolerance.py

# strict gate on scheduler crash tolerance (ISSUE 6): the durable
# assignment ledger + restart reconciliation (seeded scheduler.crash +
# restart on the same SqliteBackend store, bit-identical, no owned task
# re-executed), torn-planning-write atomicity, the fetch-time restart of
# completed jobs with lost result partitions, and the distributed fuzz
# slice with the chaos sites folded in.
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_scheduler_restart.py \
    "tests/test_fuzz_device.py::test_fuzz_distributed_two_stage_chaos"

# strict gate on multi-tenant serving (ISSUE 7): weighted fair-share
# admission with per-tenant in-flight quotas (the starvation bound), the
# plan-fingerprint result cache (zero-task cache hits, mtime invalidation,
# restart durability, lost-cached-partition resubmission), chaos-armed
# cache.put / scheduler.admit staying bit-identical to fault-free, and the
# concurrent-submission fuzz slice (N tenant clients, Zipf-repeated mix,
# cache-hit results bit-identical to cold execution).
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_multitenant.py \
    "tests/test_fuzz_device.py::test_fuzz_concurrent_submission_cache"

# strict gate on the low-latency serving tier (ISSUE 8): push dispatch
# (zero poll-dispatched tasks on a healthy stream; drop -> poll fallback ->
# re-subscribe; stale-attempt rejection), the persistent AOT program cache
# (roundtrip, corrupted/version-mismatched artifact fallback, prewarm,
# aot.load chaos), streaming collect bit-equal to buffered incl. lost-
# partition recovery, seeded scheduler.push chaos bit-identical to
# fault-free, adaptive idle-poll backoff, and result-cache eviction.
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_latency_tier.py

# strict gate on adaptive execution (ISSUE 10): the measured cost model —
# store roundtrip/corruption/fingerprint-mismatch safety, evidence-gated
# extended-tier admission with the static ladder as cold-start prior and
# hard cap, partial-offload splits bit-identical to the host oracle,
# mispredict-driven re-tiering, the general skew handler, build-side
# swapping, the chunked h2d upload, the device-join AOT disk tier, and
# the routing fuzz slice (cold / warm / off / adversarial store entries,
# results bit-identical in every configuration).
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_costmodel.py \
    "tests/test_fuzz_device.py::test_fuzz_routing"

# adaptive-execution bench smoke (ISSUE 10): the skewed join past the
# static ladder must SPLIT at the tier boundary instead of declining
# wholesale, results bit-identical across cold/warm/off, and the routing
# block's mispredict accounting must sum (mispredicts <= predictions <=
# total decisions; rate == mispredicts/predictions).
JAX_PLATFORMS=cpu BENCH_ROUTING_ONLY=1 python bench.py \
    > /tmp/_ballista_routing_smoke.json
python - /tmp/_ballista_routing_smoke.json <<'PY'
import json, sys
rec = json.load(open(sys.argv[1]))["routing"]
assert rec is not None, "routing smoke returned no record"
assert rec["bit_identical"], "routing changed results"
assert rec["splits"] >= 1, f"no partial-offload split: {rec}"
assert rec["engines"].get("split", 0) >= 1, rec
total = sum(rec["engines"].values())
assert 0 <= rec["mispredicts"] <= rec["predictions"] <= total, rec
want = rec["mispredicts"] / rec["predictions"] if rec["predictions"] else 0.0
assert abs(rec["mispredict_rate"] - want) < 1e-4, rec
assert rec["events"].get("split", 0) == rec["splits"], rec
assert rec["skew_replans"] == rec["events"].get("skew_replan", 0), rec
print("routing smoke OK:", {k: rec[k] for k in
                            ("engines", "mispredict_rate", "splits")})
PY

# strict gate on speculative execution (ISSUE 11): cost-model straggler
# detection launching duplicates through the durable speculation ledger,
# first-completion-wins in both directions (the losing sibling's report
# dropped by the stale guards, never double-counted), primary-failure
# promotion of the in-flight duplicate, scheduler crash+restart recovering
# BOTH attempts from the ledger, deadline-aware (SLO) admission, the
# scale-normalized stage.run units, the end-to-end seeded-straggler
# rescue, and the speculation fuzz slice (random 2-stage plans under
# task.slow chaos, bit-identical to fault-free).
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_speculation.py \
    "tests/test_fuzz_device.py::test_fuzz_speculation_straggler"

# speculation bench smoke (ISSUE 11): seeded task.slow chaos in the
# closed-loop latency harness (multi-process client driver) — p99 with
# speculation ON must land STRICTLY below OFF, results bit-identical to
# the fault-free baseline in both modes, counters emitted, and the
# fault-free warm passes must launch nothing.
JAX_PLATFORMS=cpu BENCH_SPECULATION_ONLY=1 BENCH_SPEC_DURATION=4 \
    BENCH_SPEC_SLOW_MS=800 python bench.py > /tmp/_ballista_spec_smoke.json
python - /tmp/_ballista_spec_smoke.json <<'PY'
import json, sys
rec = json.load(open(sys.argv[1]))["speculation"]
assert rec is not None, "speculation scenario returned no record"
assert rec["bit_identical"], "speculation changed results"
on, off = rec["on"], rec["off"]
assert on["p99_ms"] < off["p99_ms"], (
    f"speculation ON p99 {on['p99_ms']}ms not below OFF {off['p99_ms']}ms")
assert on["speculation"].get("launched", 0) > 0, on
assert on["speculation"].get("won", 0) >= 1, on
assert off["speculation"].get("launched", 0) == 0, off
# fault-free runs launch nothing: both modes' warm passes stayed silent
assert on["warm_launched"] == 0 and off["warm_launched"] == 0, rec
print("speculation smoke OK:",
      {"on_p99_ms": on["p99_ms"], "off_p99_ms": off["p99_ms"],
       "p99_speedup": rec["p99_speedup"],
       "counters": on["speculation"]})
PY

# strict gate on shared-scan multi-query execution (ISSUE 13): batched
# dispatch bit-identical to solo on the same backend (evidence gate on/off,
# mixed compatible/incompatible groups, scheduler.batch chaos, one member's
# failure sparing its siblings, a mid-batch executor death, and the
# concurrent-distinct-queries fuzz slice), plus the straggler heap and the
# tuned h2d chunk size riding the same tier via their own suites above.
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_shared_scan.py

# shared-scan bench smoke (ISSUE 13): concurrent distinct aggregate queries
# over one table on a saturated single-slot cluster — batches must form,
# at least one member upload must be SAVED by the shared scan, and every
# batched result must be bit-identical to the never-batched reference.
JAX_PLATFORMS=cpu BENCH_SHAREDSCAN_ONLY=1 BENCH_SS_DURATION=6 \
    BENCH_SS_TENANTS=1,4 python bench.py > /tmp/_ballista_ss_smoke.json
python - /tmp/_ballista_ss_smoke.json <<'PY'
import json, sys
rec = json.load(open(sys.argv[1]))["shared_scan"]
assert rec is not None, "shared-scan scenario returned no record"
assert rec["bit_identical"], "shared-scan batching changed results"
by = {r["tenants"]: r for r in rec["sweep"]}
assert 4 in by, rec
ss = by[4]["shared_scan"]
assert ss.get("batches_formed", 0) >= 1, rec
assert ss.get("batched_stages", 0) >= 2, rec
assert ss.get("uploads_saved", 0) >= 1, rec
# solo tenants must never batch
assert by.get(1, {}).get("shared_scan", {}) == {}, rec
print("shared-scan smoke OK:",
      {"qps": {t: r["qps"] for t, r in by.items()},
       "counters": ss})
PY

# strict gate on the disaggregated shuffle tier + elastic fleet (ISSUE 15):
# shared-storage piece publish (atomic tmp-then-replace, shuffle.store
# write chaos tearing nothing visible), the storage-first reader ladder
# (storage -> Flight peer -> fetch_failed/lineage), executor death after
# map/job completion as a NON-EVENT (zero retries, zero lineage recomputes,
# vs nonzero on the local tier in the same harness), graceful
# scale-in-during-a-running-job bit-identical with zero retries, the
# backlog-driven autoscaler (grow under load, drain when idle), and the
# shared-tier fuzz slice (random 2-stage plans under shuffle.store +
# executor.death chaos, bit-identical to the local-tier fault-free
# baseline).
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_elastic_shuffle.py \
    "tests/test_fuzz_device.py::test_fuzz_shared_tier_chaos"

# elastic-fleet bench smoke (ISSUE 15): a burst of concurrent jobs on the
# shared tier against an autoscaled cluster — the fleet must GROW under
# the injected (cost-model-predicted) backlog, drain back to min when
# idle, fetch shuffle pieces from storage, and complete every job
# bit-identical with zero task retries.
JAX_PLATFORMS=cpu BENCH_ELASTIC_ONLY=1 python bench.py \
    > /tmp/_ballista_elastic_smoke.json
python - /tmp/_ballista_elastic_smoke.json <<'PY'
import json, sys
rec = json.load(open(sys.argv[1]))["elastic"]
assert rec is not None, "elastic scenario returned no record"
assert rec["bit_identical"], "elastic fleet changed results"
assert rec["fleet_peak"] > rec["fleet_min"], f"fleet never grew: {rec}"
assert rec["fleet_final"] == rec["fleet_min"], f"fleet never drained: {rec}"
assert rec["backlog_ms_peak"] > 0, rec
assert rec["task_retries"] == 0, rec
fl, tier = rec["fleet"], rec["shuffle_tier"]
assert fl.get("scale_up", 0) >= 1 and fl.get("scale_down", 0) >= 1, fl
assert fl.get("drain_completed", 0) >= fl.get("scale_down", 0), fl
assert tier.get("storage_publish", 0) > 0, tier
assert tier.get("storage_fetch", 0) > 0, tier
print("elastic smoke OK:",
      {"fleet_peak": rec["fleet_peak"], "fleet_final": rec["fleet_final"],
       "backlog_ms_peak": rec["backlog_ms_peak"],
       "storage_fetch": tier.get("storage_fetch"),
       "peer_fetch": tier.get("peer_fetch", 0)})
PY

# scale-in chaos e2e under the dynamic lock witness (ISSUE 15 satellite):
# the graceful drain/retire path — autoscaler decision machinery included,
# fleet.scale chaos armed — runs with every project lock asserting the
# declared order at acquisition time. Hard asserts: the test's own
# bit-identity + zero-retry contract, ZERO order violations, and ZERO
# runtime edges the static analyzer missed.
rm -f /tmp/_ballista_witness_elastic.json.*
JAX_PLATFORMS=cpu BALLISTA_LOCK_WITNESS=1 \
    BALLISTA_LOCK_WITNESS_OUT=/tmp/_ballista_witness_elastic.json \
    python -m pytest -q -p no:cacheprovider \
    "tests/test_elastic_shuffle.py::test_scale_in_during_running_job_bit_identical_zero_retries"
# env-armed dumps are per-process (<OUT>.<pid>, ISSUE 18 satellite): pass
# every dump and the edge sets merge before the static diff
WITNESS_ARGS=()
for f in /tmp/_ballista_witness_elastic.json.*; do
    WITNESS_ARGS+=(--check-witness "$f")
done
python -m dev.analysis "${WITNESS_ARGS[@]}" ballista_tpu

# strict gate on the concurrency analyzer (ISSUE 14): lock-order graph
# construction, cycle detection, manifest round-trip + enforcement
# semantics, the atomicity (check-then-act) sub-check, the dynamic lock
# witness (edge recording, inversion assert with both stacks, plan-tree
# nesting), the witness-vs-static diff, and --jobs parallel analysis with
# cache-identical deterministic output. (The lint run at the top of this
# script is the self-run acceptance gate: zero cycles, every edge declared
# in dev/analysis/lockorder.toml, suppressions within budget.)
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_lockorder.py

# strict gate on the durability analyzer (ISSUE 18): replica-coherence
# classification coverage (every SchedulerState/server attribute durable /
# derived / ephemeral), durable-mutation KV write-through, derived-rebuild
# reachability from recover(), attempt-guard discipline, ephemeral
# budgets, manifest agreement — plus the randomized crash-recovery
# property test (kill at a seeded accepted-status point, restart, every
# analyzer-classified derived attribute rebuilds equal to the
# never-crashed control).
JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/test_durability_analysis.py tests/test_durability_recovery.py

# witness smoke (ISSUE 14): one seeded chaos e2e — executor death mid-run
# plus a scheduler restart on the same store — under
# ballista.debug.lock_witness=1. Hard asserts: the death and the restart
# actually happened, ZERO declared-order violations were recorded at the
# moment of acquisition, and `--check-witness` reports ZERO runtime edges
# the static analyzer missed (stale declared-but-never-witnessed edges are
# reported, not fatal — one short run cannot visit every code path).
JAX_PLATFORMS=cpu python - <<'PY'
import os, sys, tempfile
sys.path.insert(0, os.getcwd())
import numpy as np, pyarrow as pa, pyarrow.parquet as pq
import ballista_tpu.scheduler.state as state_mod
from ballista_tpu.client import BallistaContext
from ballista_tpu.config import BallistaConfig
from ballista_tpu.executor.runtime import StandaloneCluster
from ballista_tpu.ops.runtime import recovery_stats
from ballista_tpu.utils import locks
from ballista_tpu.utils.chaos import ChaosInjector

def find_death_seed():
    for seed in range(2000):
        inj = ChaosInjector(seed, rate=0.005, sites={"executor.death"})
        def death_poll(eid, horizon):
            for n in range(1, horizon):
                if inj.should_inject("executor.death", f"{eid}/poll{n}"):
                    return n
            return None
        d0 = death_poll("local-0", 17)
        if d0 is not None and 4 <= d0 and death_poll("local-1", 400) is None:
            return seed
    raise SystemExit("no death seed in scan range")

tmp = tempfile.mkdtemp()
rng = np.random.default_rng(7)
n = 5000
pq.write_table(pa.table({
    "g": pa.array([f"k{v}" for v in rng.integers(0, 5, n)]),
    "v": pa.array(rng.integers(0, 100, n), type=pa.int64()),
}), os.path.join(tmp, "t.parquet"))
locks.reset_witness(); locks.enable_witness()
state_mod.EXECUTOR_LEASE_SECS = 1.0
recovery_stats(reset=True)
cluster = StandaloneCluster(n_executors=2, config=BallistaConfig({
    "ballista.debug.lock_witness": "1",
    "ballista.chaos.rate": "0.005",
    "ballista.chaos.seed": str(find_death_seed()),
    "ballista.chaos.sites": "executor.death",
    "ballista.rpc.retries": "20",
}))
cluster.scheduler_impl.lost_task_check_interval = 0.3
import time
ctx = BallistaContext(*cluster.scheduler_addr,
                      settings={"ballista.cache.results": "false"})
ctx.register_parquet("t", os.path.join(tmp, "t.parquet"))
sql = "select g, sum(v) as s, count(*) as c from t group by g order by g"
first = ctx.sql(sql).collect()
deadline = time.time() + 10
while time.time() < deadline and not recovery_stats().get("chaos_executor_death"):
    time.sleep(0.1)
cluster.restart_scheduler()
second = ctx.sql(sql).collect()
assert first.to_pydict() == second.to_pydict(), "restart changed results"
ctx.close(); cluster.shutdown()
stats = recovery_stats(reset=True)
assert stats.get("chaos_executor_death", 0) >= 1, stats
assert stats.get("scheduler_restart", 0) >= 1, stats
violations = locks.witness_violations()
assert violations == [], f"lock-order violations at runtime: {violations}"
out = "/tmp/_ballista_witness.json"
rec = locks.dump(out)
assert rec["edges"], "witness saw no edges - not armed?"
print("witness smoke: %d runtime edge(s), 0 violations -> %s"
      % (len(rec["edges"]), out))
PY
# the cross-check: exit 1 on any runtime edge the static analyzer missed
python -m dev.analysis --check-witness /tmp/_ballista_witness.json ballista_tpu

# latency harness smoke (ISSUE 8): tiny QPS, 2s budget per level — the
# p50/p99 + time-to-first-batch + dispatch/compile-counter pipeline is
# exercised end-to-end on CPU images even though the absolute numbers only
# mean something on chip. The jq-less assertion: the harness must emit a
# non-null latency record with zero poll dispatches and a warm compile-hit
# rate of 1.0.
JAX_PLATFORMS=cpu BENCH_LATENCY_ONLY=1 BENCH_LAT_DURATION=2 \
    BENCH_LAT_CLIENTS=1 python bench.py > /tmp/_ballista_lat_smoke.json
python - /tmp/_ballista_lat_smoke.json <<'PY'
import json, sys
rec = json.load(open(sys.argv[1]))["latency"]
assert rec is not None, "latency harness returned no record"
assert rec["sweep"], "empty QPS sweep"
for row in rec["sweep"]:
    for f in ("qps", "p50_ms", "p95_ms", "p99_ms", "ttfb_p50_ms"):
        assert f in row, f"sweep row missing {f}"
assert rec["dispatch_poll"] == 0, f"poll-dispatched tasks: {rec}"
assert rec["dispatch_push"] > 0, f"no push dispatches: {rec}"
assert rec["compile_trace"] == 0, f"warm sweep traced: {rec}"
assert rec["compile_hit_rate"] == 1.0, rec
print("latency smoke OK:", rec["sweep"][0])
PY

# HBM-resident exchange bench smoke (ISSUE 16): the 2-stage aggregation
# must actually SKIP re-uploads on the same-executor consume path
# (registry hits, not ladder reads), stay bit-identical to the
# exchange-off oracle, and degrade to the ladder with zero task retries
# when every consume-time probe is torn by seeded exchange.evict chaos.
JAX_PLATFORMS=cpu BENCH_EXCHANGE_ONLY=1 python bench.py \
    > /tmp/_ballista_exchange_smoke.json
python - /tmp/_ballista_exchange_smoke.json <<'PY'
import json, sys
rec = json.load(open(sys.argv[1]))["exchange"]
assert rec is not None, "exchange scenario returned no record"
assert rec["bit_identical"], "exchange tier changed results"
assert rec["reupload_skipped"] >= 1, rec
assert rec["h2d_bytes_saved"] > 0, rec
assert rec["off_stats_empty"], "exchange-off run touched the registry"
assert rec["task_retries"] == 0, rec
ch = rec["chaos"]
assert ch["evicted_chaos"] >= 1, ch
assert ch["injected"] >= 1, ch
assert ch["task_retries"] == 0, "registry loss caused task retries"
print("exchange smoke OK:",
      {"reupload_skipped": rec["reupload_skipped"],
       "h2d_bytes_saved": rec["h2d_bytes_saved"],
       "d2h_bytes_saved": rec["d2h_bytes_saved"],
       "chaos_evicted": ch["evicted_chaos"],
       "digest": rec["digest"]})
PY

# incremental-execution bench smoke (ISSUE 19): appending a file to a
# cached query's chunk set must (a) reload every existing chunk's tiles
# from the persisted layout store, (b) serve the new result by FOLDING
# delta partials into the cached aggregate state — strictly faster than a
# cold full run over the grown set and bit-identical to it, (c) decline
# to a full recompute when every advanced publish is torn by seeded
# cache.advance chaos, and (d) keep serving the advanced entry as a plain
# cache hit across a scheduler restart on a durable KV.
JAX_PLATFORMS=cpu BENCH_DELTA_ONLY=1 python bench.py \
    > /tmp/_ballista_delta_smoke.json
python - /tmp/_ballista_delta_smoke.json <<'PY'
import json, sys
rec = json.load(open(sys.argv[1]))["delta"]
assert rec is not None, "delta scenario returned no record"
assert rec["bit_identical"], "incremental execution changed results"
assert rec["chunks_reused"] >= 1, rec
assert rec["advance_hits"] >= 1, rec
assert rec["advance_ms"] < rec["cold_ms"], (
    f"advancement not faster than cold: {rec}")
ch = rec["chaos"]
assert ch["advance_hits"] == 0, "torn publish still served an advance"
assert ch["advance_declined"] >= 1, ch
assert rec["restart_advanced"] and rec["restart_cache_hit"], rec
print("delta smoke OK:",
      {"advance_ms": rec["advance_ms"], "cold_ms": rec["cold_ms"],
       "chunks_reused": rec["chunks_reused"],
       "advance_hits": rec["advance_hits"], "digest": rec["digest"]})
PY

# replicated control-plane bench smoke (ISSUE 20): closed-loop admission
# from 4 client processes, homed round-robin, against one scheduler and
# then two lease-sharded replicas over the same KV. Two replicas must
# admit strictly more completed queries per second, and the union of
# result digests must be IDENTICAL across both configs — the throughput
# win never rides a correctness regression.
JAX_PLATFORMS=cpu BENCH_REPLICA_ONLY=1 BENCH_REPLICA_DURATION=4 \
    python bench.py > /tmp/_ballista_replica_smoke.json
python - /tmp/_ballista_replica_smoke.json <<'PY'
import json, sys
rec = json.load(open(sys.argv[1]))["replica"]
assert rec is not None, "replica scenario returned no record"
assert rec["digests_identical"], "replicated admission changed results"
assert rec["n_digests"] >= 1, rec
assert rec["two"]["qps"] > rec["one"]["qps"], (
    f"2-replica admission not faster than 1-replica: {rec}")
print("replica smoke OK:",
      {"one_qps": rec["one"]["qps"], "two_qps": rec["two"]["qps"],
       "speedup": rec["speedup"], "n_digests": rec["n_digests"]})
PY

# full tier-1 under the dynamic lock witness (ISSUE 16 satellite): every
# fast test — the exchange registry, scheduler GC, chaos ladders, SPMD
# admission included — runs with each project lock asserting the declared
# order at acquisition, then --check-witness fails the tier on any runtime
# edge the static analyzer missed. This is the broadest coverage the
# witness gets: the targeted smokes above arm single paths; this lane arms
# everything tier-1 reaches.
rm -f /tmp/_ballista_witness_t1.json.*
JAX_PLATFORMS=cpu BALLISTA_LOCK_WITNESS=1 \
    BALLISTA_LOCK_WITNESS_OUT=/tmp/_ballista_witness_t1.json \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly
# tier-1 forks executor/cluster worker processes: each dumped its own
# <OUT>.<pid> witness; merge them all before the cross-check so an edge
# seen by ANY process counts against the static graph
WITNESS_ARGS=()
for f in /tmp/_ballista_witness_t1.json.*; do
    WITNESS_ARGS+=(--check-witness "$f")
done
python -m dev.analysis "${WITNESS_ARGS[@]}" ballista_tpu

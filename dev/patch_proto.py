#!/usr/bin/env python
"""Protoc-less protobuf binding maintenance.

The image ships no `protoc`, so descriptor edits are applied directly to the
serialized FileDescriptorProto embedded in the checked-in
`ballista_tpu/proto/ballista_pb2.py`: parse it with
`google.protobuf.descriptor_pb2`, mutate, re-serialize, re-emit the module.
Wire compatibility is preserved by construction — only field/message
ADDITIONS are expressible here; renumbering or retyping requires real protoc
(and a migration).

Each applied edit batch lives in a function below so the file doubles as the
edit history. `--check` re-derives the expected blob from the PRE-edit
baseline if available, else just verifies the module round-trips (imports,
builds messages, serializes).

Usage:
    python dev/patch_proto.py --check      # smoke-verify the checked-in module
"""

from __future__ import annotations

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

STR, U32, MSG, BOOL, BYTES = 9, 13, 11, 8, 12  # FieldDescriptorProto.Type
OPT, REP = 1, 3  # FieldDescriptorProto.Label

_HEADER = '''# -*- coding: utf-8 -*-
# Generated protocol buffer code for ballista.proto. DO NOT EDIT BY HAND.
#
# protoc is not part of this toolchain; this file is produced by
# dev/patch_proto.py, which parses the checked-in serialized
# FileDescriptorProto, applies the edits described in proto/ballista.proto,
# and re-serializes it (proto/README.md).
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'ballista_pb2', globals())
# @@protoc_insertion_point(module_scope)
'''


def add_field(msg, name, number, ftype, label=OPT, type_name=None, oneof=None):
    f = msg.field.add(name=name, number=number, label=label, type=ftype)
    if type_name:
        f.type_name = type_name
    if oneof is not None:
        f.oneof_index = oneof
    return f


def edit_issue5_failure_recovery(fdp) -> None:
    """ISSUE 5: bounded task retries + lineage-based shuffle recovery.

    Adds (all wire-compatible field/message additions):
    - FailedTask.executor_id (blacklist the failing executor on retry)
    - TaskAttempt message (per-attempt history line)
    - FetchFailedTask message (fetch failure naming the lost map location)
    - TaskStatus: fetch_failed into the status oneof; attempt + history
      outside it (survive requeues; stale-report rejection)
    - TaskDefinition.attempt (echoed in statuses; chaos key rotation)
    """
    msgs = {m.name: m for m in fdp.message_type}
    add_field(msgs["FailedTask"], "executor_id", 2, STR)

    ta = fdp.message_type.add()
    ta.name = "TaskAttempt"
    add_field(ta, "attempt", 1, U32)
    add_field(ta, "executor_id", 2, STR)
    add_field(ta, "error", 3, STR)

    ff = fdp.message_type.add()
    ff.name = "FetchFailedTask"
    add_field(ff, "error", 1, STR)
    add_field(ff, "executor_id", 2, STR)
    add_field(ff, "map_stage_id", 3, U32)
    add_field(ff, "map_partition_id", 4, U32)
    add_field(ff, "map_executor_id", 5, STR)
    add_field(ff, "path", 6, STR)

    ts = msgs["TaskStatus"]
    add_field(ts, "fetch_failed", 5, MSG, type_name=".ballista.FetchFailedTask", oneof=0)
    add_field(ts, "attempt", 6, U32)
    add_field(ts, "history", 7, MSG, label=REP, type_name=".ballista.TaskAttempt")

    add_field(msgs["TaskDefinition"], "attempt", 4, U32)


def edit_issue5_orphan_reconcile(fdp) -> None:
    """ISSUE 5 review follow-up: PollWorkParams.running_tasks — executors
    echo their in-flight task ids so the scheduler can requeue assignments
    whose PollWork response was lost in transit (the RPC is retried on
    UNAVAILABLE and is not idempotent; without reconciliation a lost
    response orphans the task in Running forever)."""
    msgs = {m.name: m for m in fdp.message_type}
    add_field(
        msgs["PollWorkParams"], "running_tasks", 4, MSG,
        label=REP, type_name=".ballista.PartitionId",
    )


def edit_issue6_scheduler_restart(fdp) -> None:
    """ISSUE 6: scheduler crash tolerance.

    Adds (all wire-compatible field/message additions):
    - Assignment message: the durable assignment-ledger value stored under
      /ballista/{ns}/assignments/{job}/{stage}/{part} — a restarted
      scheduler reloads in-flight assignments from it
    - RunningTaskEcho message + PollWorkParams.running_echo: the
      attempt-enriched form of the running_tasks echo, so reconciliation
      (and restart re-adoption) can match the ECHOED attempt against the
      ledger instead of vouching for any attempt of the task
    - ReportLostPartitionParams/Result + the ReportLostPartition RPC: a
      client that hits a fetch failure against a COMPLETED job's result
      partition reports the lost location; the scheduler restarts the lost
      final-stage tasks through the normal lineage/retry path
    """
    msgs = {m.name: m for m in fdp.message_type}

    asg = fdp.message_type.add()
    asg.name = "Assignment"
    add_field(asg, "executor_id", 1, STR)
    add_field(asg, "attempt", 2, U32)

    echo = fdp.message_type.add()
    echo.name = "RunningTaskEcho"
    add_field(echo, "partition_id", 1, MSG, type_name=".ballista.PartitionId")
    add_field(echo, "attempt", 2, U32)

    add_field(
        msgs["PollWorkParams"], "running_echo", 5, MSG,
        label=REP, type_name=".ballista.RunningTaskEcho",
    )

    rp = fdp.message_type.add()
    rp.name = "ReportLostPartitionParams"
    add_field(rp, "job_id", 1, STR)
    add_field(rp, "executor_id", 2, STR)
    add_field(rp, "stage_id", 3, U32)
    add_field(rp, "partition_id", 4, U32)
    add_field(rp, "path", 5, STR)

    rr = fdp.message_type.add()
    rr.name = "ReportLostPartitionResult"
    add_field(rr, "restarted", 1, 8)  # 8 = TYPE_BOOL
    add_field(rr, "tasks_restarted", 2, U32)

    svc = {s.name: s for s in fdp.service}.get("SchedulerGrpc")
    if svc is not None:
        m = svc.method.add()
        m.name = "ReportLostPartition"
        m.input_type = ".ballista.ReportLostPartitionParams"
        m.output_type = ".ballista.ReportLostPartitionResult"


def edit_issue7_multitenant(fdp) -> None:
    """ISSUE 7: multi-tenant serving.

    Adds (all wire-compatible field/message additions):
    - ExecuteQueryParams.tenant/.priority: the submitting tenant (and its
      job priority) ride the submission itself, not just the settings map,
      so admission control keys off a first-class field
    - JobTenant message: the durable per-job tenant record stored under
      /ballista/{ns}/tenants/{job} — admission quotas and fairness
      accounting survive a scheduler restart
    - ResultCacheEntry message: the plan-fingerprint result cache value
      stored under /ballista/{ns}/resultcache/{fp} — the completed result
      partition locations a repeated identical query is served from
    - CompletedJob.cached: marks a job completed FROM the result cache
      (zero executor tasks ran), so clients/bench can count hits without
      scheduler introspection
    """
    msgs = {m.name: m for m in fdp.message_type}
    DBL, BOOL = 1, 8  # FieldDescriptorProto.Type

    eq = msgs["ExecuteQueryParams"]
    add_field(eq, "tenant", 4, STR)
    add_field(eq, "priority", 5, U32)

    jt = fdp.message_type.add()
    jt.name = "JobTenant"
    add_field(jt, "tenant", 1, STR)
    add_field(jt, "priority", 2, U32)

    rc = fdp.message_type.add()
    rc.name = "ResultCacheEntry"
    add_field(
        rc, "partition_location", 1, MSG,
        label=REP, type_name=".ballista.PartitionLocation",
    )
    add_field(rc, "created_at", 2, DBL)
    add_field(rc, "fingerprint", 3, STR)

    add_field(msgs["CompletedJob"], "cached", 2, BOOL)


def edit_issue8_latency_tier(fdp) -> None:
    """ISSUE 8: low-latency serving tier.

    Adds (all wire-compatible field/message/method additions):
    - SubscribeWorkParams message + the server-streaming SubscribeWork RPC:
      an executor opens the stream once and the scheduler pushes
      TaskDefinitions the moment assignment picks them, instead of waiting
      for the executor's next 250ms PollWork. `slots` seeds the scheduler's
      per-subscriber credit (how many tasks may be in flight unacknowledged)
    - RunningJob.partial_location: final-stage result partitions completed
      SO FAR, published while the job still runs — the client's streaming
      collect starts fetching (and yielding batches) before the last
      partition lands
    - ResultCacheEntry.last_hit: LRU recency for the result-cache
      size/TTL eviction (ISSUE 8 satellite; survives scheduler restarts
      because it lives in the KV value itself)
    """
    msgs = {m.name: m for m in fdp.message_type}
    DBL = 1  # FieldDescriptorProto.Type

    sw = fdp.message_type.add()
    sw.name = "SubscribeWorkParams"
    add_field(sw, "metadata", 1, MSG, type_name=".ballista.ExecutorMetadata")
    add_field(sw, "slots", 2, U32)

    add_field(
        msgs["RunningJob"], "partial_location", 1, MSG,
        label=REP, type_name=".ballista.PartitionLocation",
    )

    add_field(msgs["ResultCacheEntry"], "last_hit", 4, DBL)

    svc = {s.name: s for s in fdp.service}.get("SchedulerGrpc")
    if svc is not None:
        m = svc.method.add()
        m.name = "SubscribeWork"
        m.input_type = ".ballista.SubscribeWorkParams"
        m.output_type = ".ballista.TaskDefinition"
        m.server_streaming = True


def edit_issue11_speculation(fdp) -> None:
    """ISSUE 11: speculative execution + SLOs + push job-status.

    Adds (all wire-compatible field/method additions):
    - TaskDefinition.speculative + TaskStatus.speculative: attempt
      provenance — the scheduler marks a duplicate (speculative) dispatch
      and the executor echoes the mark in its reported status, so logs,
      counters, and the first-completion-wins bookkeeping can tell the
      duplicate from the primary without decoding attempt arithmetic
    - JobTenant.created_at: job submission time, the anchor for the
      per-tenant SLO deadline (ballista.tenant.slo_ms) that feeds
      deadline-aware admission ordering and the slo_misses counter
    - the server-streaming SubscribeJobStatus RPC (mirroring
      SubscribeWork): the scheduler pushes a GetJobStatusResult on every
      job-status transition, replacing the client's 5ms-floor status poll
      (which stays as the automatic fallback)
    """
    msgs = {m.name: m for m in fdp.message_type}
    DBL, BOOL = 1, 8  # FieldDescriptorProto.Type

    add_field(msgs["TaskDefinition"], "speculative", 5, BOOL)
    add_field(msgs["TaskStatus"], "speculative", 8, BOOL)
    add_field(msgs["JobTenant"], "created_at", 3, DBL)

    svc = {s.name: s for s in fdp.service}.get("SchedulerGrpc")
    if svc is not None:
        m = svc.method.add()
        m.name = "SubscribeJobStatus"
        m.input_type = ".ballista.GetJobStatusParams"
        m.output_type = ".ballista.GetJobStatusResult"
        m.server_streaming = True


def edit_issue13_shared_scan(fdp) -> None:
    """ISSUE 13: shared-scan multi-query execution.

    Adds (wire-compatible field addition):
    - TaskDefinition.siblings: the OTHER member tasks of a shared-scan
      batch group, each a full TaskDefinition (own task_id / attempt /
      plan / settings). A batched dispatch carries the primary member in
      the outer message plus its siblings here; the executor runs the
      group as one shared-scan device launch and reports one TaskStatus
      per member, so every existing status/ledger/recovery path sees N
      independent tasks. Solo dispatches leave the field empty — an
      executor that ignored it would simply never receive batches (the
      scheduler only batches what one TaskDefinition can carry).
    """
    msgs = {m.name: m for m in fdp.message_type}
    add_field(
        msgs["TaskDefinition"], "siblings", 6, MSG,
        label=REP, type_name=".ballista.TaskDefinition",
    )


def edit_issue15_disaggregated_shuffle(fdp) -> None:
    """ISSUE 15: disaggregated shuffle tier.

    Adds (wire-compatible field additions):
    - CompletedTask.storage_uri: non-empty when the task's shuffle pieces
      were published to SHARED storage (ballista.shuffle.tier = shared)
      rather than the executor's private work dir. The piece set's home is
      then a PATH, not a process: the scheduler's lost-task sweep keeps the
      completed output when the executor dies, and readers resolve the
      pieces from storage first with the Flight peer fetch as fallback.
    - PartitionLocation.storage_uri: the same home, propagated onto every
      location record — bound shuffle-reader plans (serde), the partial/
      completed result locations clients fetch from, and the result-cache
      entries whose liveness no longer depends on the producing executor's
      lease when the data is storage-homed.
    """
    msgs = {m.name: m for m in fdp.message_type}
    add_field(msgs["CompletedTask"], "storage_uri", 4, STR)
    add_field(msgs["PartitionLocation"], "storage_uri", 5, STR)


def edit_issue16_resident_exchange(fdp) -> None:
    """ISSUE 16: HBM-resident cross-stage exchange.

    Adds (wire-compatible field additions):
    - CompletedTask.resident: the producing executor ALSO registered this
      task's shuffle pieces in its in-memory exchange registry — a HINT
      only (the disk/storage piece stays the authoritative home); the
      scheduler folds it into consumer-stage shuffle locations.
    - PartitionLocation.resident: the same hint on every location record,
      so bound shuffle-reader plans carry it to executors and the
      scheduler's locality preference can read it off the bound plan. A
      stale hint (evicted entry, dead producer) silently degrades to the
      storage -> Flight peer -> lineage ladder.
    """
    msgs = {m.name: m for m in fdp.message_type}
    add_field(msgs["CompletedTask"], "resident", 5, BOOL)
    add_field(msgs["PartitionLocation"], "resident", 6, BOOL)


def edit_issue19_delta(fdp) -> None:
    """ISSUE 19: incremental execution (result-cache advancement).

    Adds (all wire-compatible field additions):
    - TaskDefinition.delta_for: non-empty on tasks of an internal delta
      job — the user job id whose cached result the delta's output
      advances. Provenance only: executors run the task like any other;
      logs and telemetry can attribute the work to the advancement.
    - CompletedJob.inline_result: the job's final result as one Arrow IPC
      stream, served when the result cache holds advanced (folded)
      aggregate state instead of executor-homed partition locations.
      Clients must check it BEFORE treating an empty location list as an
      empty result.
    - ResultCacheEntry.content_key: the plan's content identity (the
      result_key minus file facts) — the advancement probe matches
      same-content entries whose file set the new submission grew.
    - ResultCacheEntry.scan_fact: the (path|mtime|size) fact of every
      scan file the entry's result covers, so the probe can check the
      strict-superset relation fact-by-fact.
    - ResultCacheEntry.state_ipc: resumable aggregate state (Arrow IPC)
      for advanced entries; self-contained, so their liveness no longer
      depends on any executor lease.
    - ResultCacheEntry.advance_epoch: how many advancements produced this
      entry (0 = cold run) — observability + fold-chain depth in logs.
    """
    msgs = {m.name: m for m in fdp.message_type}
    add_field(msgs["TaskDefinition"], "delta_for", 7, STR)
    add_field(msgs["CompletedJob"], "inline_result", 3, BYTES)
    rc = msgs["ResultCacheEntry"]
    add_field(rc, "content_key", 5, STR)
    add_field(rc, "scan_fact", 6, STR, label=REP)
    add_field(rc, "state_ipc", 7, BYTES)
    add_field(rc, "advance_epoch", 8, U32)


def edit_issue20_replication(fdp) -> None:
    """ISSUE 20: replicated control plane (lease-sharded scheduler replicas).

    Adds (all wire-compatible field/message additions):
    - JobLease message: the durable leases/{job} ownership record — which
      replica owns the job (replica_id), the fencing generation (fence,
      bumped on every ownership transfer so a deposed owner's remembered
      lease value can never match again), and the owner's advertised
      host:port (addr) for client/executor redirects. Minted atomically
      with the planning commit; TTL-renewed by the owner's heartbeat.
    - GetJobStatusResult.owner_addr: non-empty when the serving replica is
      NOT the job's owner — the owner's host:port, so a client can re-home
      its push subscription (and an executor its poll) after a failover.
    """
    lease = fdp.message_type.add()
    lease.name = "JobLease"
    add_field(lease, "replica_id", 1, STR)
    add_field(lease, "fence", 2, U32)
    add_field(lease, "addr", 3, STR)

    msgs = {m.name: m for m in fdp.message_type}
    add_field(msgs["GetJobStatusResult"], "owner_addr", 2, STR)


# edits already baked into the checked-in ballista_pb2.py, oldest first
APPLIED = [
    edit_issue5_failure_recovery,
    edit_issue5_orphan_reconcile,
    edit_issue6_scheduler_restart,
    edit_issue7_multitenant,
    edit_issue8_latency_tier,
    edit_issue11_speculation,
    edit_issue13_shared_scan,
    edit_issue15_disaggregated_shuffle,
    edit_issue16_resident_exchange,
    edit_issue19_delta,
    edit_issue20_replication,
]


def emit(blob: bytes, out_path: str) -> None:
    with open(out_path, "w") as f:
        f.write(_HEADER.format(blob=blob))


def apply_edits(names) -> int:
    """Apply the named edit batches (functions above) to the serialized
    FileDescriptorProto embedded in the checked-in ballista_pb2.py and
    re-emit the module. Batches already baked into the blob must NOT be
    re-applied (duplicate fields would corrupt the descriptor) — pass only
    the NEW batch names, then append them to APPLIED."""
    from google.protobuf import descriptor_pb2

    from ballista_tpu.proto import ballista_pb2 as pb

    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.ParseFromString(pb.DESCRIPTOR.serialized_pb)
    table = {f.__name__: f for f in APPLIED}
    for name in names:
        if name not in table:
            print(f"unknown edit batch {name!r}; known: {sorted(table)}")
            return 2
        table[name](fdp)
    out = __file__.rsplit("/", 2)[0] + "/ballista_tpu/proto/ballista_pb2.py"
    emit(fdp.SerializeToString(), out)
    print(f"applied {list(names)} -> {out}")
    return 0


def check() -> int:
    from ballista_tpu.proto import ballista_pb2 as pb

    t = pb.TaskStatus()
    t.attempt = 1
    h = t.history.add()
    h.attempt = 0
    h.executor_id = "e1"
    h.error = "boom"
    t.fetch_failed.map_stage_id = 2
    t.fetch_failed.map_executor_id = "e2"
    t.fetch_failed.path = "/x"
    rt = pb.TaskStatus()
    rt.ParseFromString(t.SerializeToString())
    assert rt.WhichOneof("status") == "fetch_failed"
    assert rt.attempt == 1 and rt.history[0].executor_id == "e1"
    td = pb.TaskDefinition()
    td.attempt = 3
    assert pb.TaskDefinition.FromString(td.SerializeToString()).attempt == 3
    ft = pb.FailedTask(error="x", executor_id="e9")
    assert pb.FailedTask.FromString(ft.SerializeToString()).executor_id == "e9"
    print("ballista_pb2.py: failure-recovery fields present, round-trips OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", action="store_true", help="verify the module")
    ap.add_argument(
        "--apply", nargs="+", metavar="EDIT",
        help="apply the named NEW edit batches to the checked-in blob and "
        "re-emit ballista_pb2.py (do not name batches already baked in)",
    )
    args = ap.parse_args()
    if args.apply:
        return apply_edits(args.apply)
    if args.check:
        return check()
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())

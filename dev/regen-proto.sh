#!/usr/bin/env bash
# Regenerate the checked-in protobuf bindings from the wire contract
# (the role the reference's build.rs/tonic-build codegen plays,
# rust/core/build.rs:15-23). Run after editing proto/ballista.proto.
set -euo pipefail
cd "$(dirname "$0")/../ballista_tpu/proto"
protoc --python_out=. ballista.proto
python - <<'PY'
import sys
sys.path.insert(0, "../..")
from ballista_tpu.proto import ballista_pb2 as pb
n = pb.PhysicalPlanNode()
print("regenerated ballista_pb2.py; smoke import ok:", bool(n.DESCRIPTOR))
PY
# If protoc is unavailable on this image, apply descriptor-level additions
# with dev/patch_proto.py instead (see proto/README.md).

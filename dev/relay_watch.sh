#!/bin/bash
# Poll the TPU relay; the moment it answers, run a full bench capture and
# exit.  Relay windows are scarce (observed: live <1h at a time) — evidence
# capture must not wait for a human.  bench.py auto-persists the result to
# benchmarks/results/session_auto_*.json, so this script's stdout is
# best-effort only.  A capture that only emitted the stale fallback (relay
# dropped between probe and bench) does NOT count: keep watching.
cd /root/repo || exit 1
mkdir -p benchmarks/results
while true; do
  if timeout 35 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) relay LIVE — starting capture"
    while [ -f /tmp/ballista_prepop.lock ]; do
      pid=$(cat /tmp/ballista_prepop.lock 2>/dev/null)
      if [ -z "$pid" ] || ! kill -0 "$pid" 2>/dev/null; then
        echo "stale prepopulation lock (pid ${pid:-?} gone) — proceeding"
        rm -f /tmp/ballista_prepop.lock
        break
      fi
      echo "waiting for layout prepopulation (pid $pid) to finish"
      sleep 30
    done
    BENCH_PROBE_BUDGET=60 BENCH_MAX_SECONDS=4800 timeout 7200 \
      python bench.py \
      > benchmarks/results/watch_capture.out \
      2> benchmarks/results/watch_capture.err
    rc=$?
    echo "$(date -u +%FT%TZ) capture done rc=$rc"
    if [ "$rc" -eq 0 ] && ! grep -q '"stale": true' benchmarks/results/watch_capture.out; then
      echo "fresh capture recorded"
      exit 0
    fi
    echo "no fresh capture (rc=$rc, possibly stale fallback) — keep watching"
  else
    echo "$(date -u +%FT%TZ) relay down"
  fi
  sleep 240
done

#!/bin/bash
# Poll the TPU relay; the moment it answers, run a full bench capture and
# exit.  Relay windows are scarce (observed: live <1h at a time) — evidence
# capture must not wait for a human.  bench.py auto-persists the result to
# benchmarks/results/session_auto_*.json, so this script's stdout is
# best-effort only.  A capture that only emitted the stale fallback (relay
# dropped between probe and bench) does NOT count: keep watching.
cd /root/repo || exit 1
mkdir -p benchmarks/results
while true; do
  if timeout 35 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) relay LIVE — starting capture"
    # flock held by a live prepopulate process (released on its death; no
    # staleness handling needed); the pid-content check also covers a
    # holder started before the flock scheme. Never unlink here.
    prepop_busy() {
      [ -f /tmp/ballista_prepop.lock ] || return 1
      flock -n /tmp/ballista_prepop.lock true || return 0
      pid=$(cat /tmp/ballista_prepop.lock 2>/dev/null)
      [ -n "$pid" ] && kill -0 "$pid" 2>/dev/null
    }
    while prepop_busy; do
      echo "waiting for layout prepopulation to finish"
      sleep 30
    done
    BENCH_PROBE_BUDGET=60 BENCH_MAX_SECONDS=4800 timeout 7200 \
      python bench.py \
      > benchmarks/results/watch_capture.out \
      2> benchmarks/results/watch_capture.err
    rc=$?
    echo "$(date -u +%FT%TZ) capture done rc=$rc"
    if [ "$rc" -eq 0 ] && ! grep -q '"stale": true' benchmarks/results/watch_capture.out; then
      echo "fresh capture recorded"
      exit 0
    fi
    echo "no fresh capture (rc=$rc, possibly stale fallback) — keep watching"
  else
    echo "$(date -u +%FT%TZ) relay down"
  fi
  sleep 240
done

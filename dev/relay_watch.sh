#!/bin/bash
# Poll the TPU relay; the moment it answers, run a full bench capture and
# exit.  Relay windows are scarce (observed: live <1h at a time) — evidence
# capture must not wait for a human.  bench.py auto-persists the result to
# benchmarks/results/session_auto_*.json, so this script's stdout is
# best-effort only.
cd /root/repo || exit 1
mkdir -p benchmarks/results
while true; do
  if timeout 35 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) relay LIVE — starting capture"
    BENCH_PROBE_BUDGET=60 BENCH_MAX_SECONDS=4800 timeout 7200 \
      python bench.py \
      > benchmarks/results/watch_capture.out \
      2> benchmarks/results/watch_capture.err
    echo "$(date -u +%FT%TZ) capture done rc=$?"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) relay down"
  sleep 240
done

"""Distributed planner: split a physical plan into a DAG of query stages.

Generalizes the reference's rule set (rust/scheduler/src/planner.rs:114-198:
split at MergeExec / final HashAggregate / partition-count change) to one
rule: every exchange operator (RepartitionExec, MergeExec) becomes a stage
boundary — the child pipeline ends in a ShuffleWriterExec, the parent reads
it through UnresolvedShuffleExec until the scheduler substitutes concrete
locations (ref remove_unresolved_shuffles, planner.rs:236-269).

Parallel final aggregation arrives via the physical planner emitting
Partial -> Repartition(hash keys) -> Final, so here the exchange rule covers
the reference's aggregate rule too.
"""

from __future__ import annotations

from typing import Dict, List

from ballista_tpu.distributed.stages import (
    ShuffleLocation,
    ShuffleReaderExec,
    ShuffleWriterExec,
    UnresolvedShuffleExec,
)
from ballista_tpu.physical.basic import MergeExec
from ballista_tpu.physical.plan import ExecutionPlan
from ballista_tpu.physical.repartition import RepartitionExec


class DistributedPlanner:
    def __init__(self, config=None) -> None:
        self._next_stage_id = 0
        self._config = config

    def _new_stage_id(self) -> int:
        self._next_stage_id += 1
        return self._next_stage_id

    def plan_query_stages(
        self, job_id: str, plan: ExecutionPlan
    ) -> List[ShuffleWriterExec]:
        """Returns stages in dependency order; the last is the job's root
        (its shuffle output is the query result, one piece per partition)."""
        if self._config is not None and self._config.tpu_spmd():
            plan = self._fuse_spmd_aggregates(plan)
        stages: List[ShuffleWriterExec] = []
        root = self._visit(plan, job_id, stages)
        final = ShuffleWriterExec(job_id, self._new_stage_id(), root, None)
        stages.append(final)
        return stages

    def _fuse_spmd_aggregates(self, node: ExecutionPlan) -> ExecutionPlan:
        """Config-gated TPU restructuring (SURVEY §7 step 5):

        - a HashAggregate(Final) <- Repartition(hash) <- HashAggregate(
          Partial) subtree — which the exchange rule below would split into
          two stages plus a materialized shuffle — becomes ONE
          SpmdAggregateExec stage whose exchange is a psum over the mesh;
        - a co-partitionable HashJoin (INNER/LEFT, no residual filter)
          becomes ONE SpmdJoinExec stage whose hash exchange is
          lax.all_to_all over the mesh (SURVEY §2.8's RepartitionExec
          mapping) instead of two materialized shuffles.

        Both keep the untouched subtree inside for serde + host fallback."""
        from ballista_tpu.logical.plan import JoinType
        from ballista_tpu.parallel.spmd_join import SpmdJoinExec
        from ballista_tpu.parallel.spmd_stage import SpmdAggregateExec
        from ballista_tpu.physical.aggregate import AggregateMode, HashAggregateExec
        from ballista_tpu.physical.join import HashJoinExec

        children = [self._fuse_spmd_aggregates(c) for c in node.children()]
        if children:
            node = node.with_children(children)
        if (
            isinstance(node, HashAggregateExec)
            and node.mode == AggregateMode.FINAL
            and isinstance(node.input, RepartitionExec)
            and isinstance(node.input.input, HashAggregateExec)
            and node.input.input.mode == AggregateMode.PARTIAL
        ):
            return SpmdAggregateExec(node)
        if (
            isinstance(node, HashJoinExec)
            and node.partitioned  # only fuse when there IS an exchange pair
            and node.join_type in (JoinType.INNER, JoinType.LEFT)
            and node.filter is None
        ):
            return SpmdJoinExec(node)
        return node

    def _visit(
        self, node: ExecutionPlan, job_id: str, stages: List[ShuffleWriterExec]
    ) -> ExecutionPlan:
        children = [self._visit(c, job_id, stages) for c in node.children()]
        if isinstance(node, RepartitionExec):
            child = children[0]
            stage = ShuffleWriterExec(
                job_id, self._new_stage_id(), child, node.partitioning
            )
            stages.append(stage)
            return UnresolvedShuffleExec(
                stage.stage_id, node.schema(), node.partitioning.partition_count()
            )
        if isinstance(node, MergeExec):
            child = children[0]
            stage = ShuffleWriterExec(job_id, self._new_stage_id(), child, None)
            stages.append(stage)
            reader = UnresolvedShuffleExec(
                stage.stage_id,
                node.schema(),
                child.output_partitioning().partition_count(),
                identity=True,
            )
            return MergeExec(reader)
        if children:
            return node.with_children(children)
        return node


def find_unresolved_shuffles(plan: ExecutionPlan) -> List[UnresolvedShuffleExec]:
    out: List[UnresolvedShuffleExec] = []
    if isinstance(plan, UnresolvedShuffleExec):
        out.append(plan)
    for c in plan.children():
        out.extend(find_unresolved_shuffles(c))
    return out


def remove_unresolved_shuffles(
    plan: ExecutionPlan, locations_by_stage: Dict[int, List[ShuffleLocation]]
) -> ExecutionPlan:
    """Substitute concrete ShuffleReaderExec for each placeholder
    (ref planner.rs:236-269)."""
    if isinstance(plan, UnresolvedShuffleExec):
        locs = locations_by_stage.get(plan.stage_id)
        if locs is None:
            raise KeyError(f"no locations for stage {plan.stage_id}")
        return ShuffleReaderExec(
            locs, plan.schema(), plan.partition_count, identity=plan.identity
        )
    children = [
        remove_unresolved_shuffles(c, locations_by_stage) for c in plan.children()
    ]
    if children:
        return plan.with_children(children)
    return plan

"""Distributed execution operators.

The reference's stage-stitching operator trio
(rust/core/src/execution_plans/): QueryStageExec -> here ShuffleWriterExec
(with map-side hash split, the design later Ballista versions adopted),
ShuffleReaderExec (fetch materialized partitions from peers), and
UnresolvedShuffleExec (placeholder until upstream stages complete,
ref unresolved_shuffle.rs:34-91).

Shuffle file layout under an executor's work dir:
    {work_dir}/{job_id}/{stage_id}/{input_partition}/{output_partition}.arrow
CompletedTask.path points at the {input_partition} directory; readers derive
piece paths from it (ref flight_service.rs:104-126 wrote a single data.arrow).

Disaggregated shuffle tier (ISSUE 15): with ballista.shuffle.tier=shared the
SAME layout roots at ballista.shuffle.dir instead of the executor's private
work dir, published with the same atomic tmp-then-os.replace discipline. A
piece's home is then a path, not a process — CompletedTask/PartitionLocation
carry it as `storage_uri` — so executor death after map completion loses
nothing, and readers resolve storage-homed pieces from the shared dir FIRST,
with the Flight peer fetch as the local-tier path and the fallback ladder
(storage read -> peer fetch -> fetch_failed/lineage recompute).
"""

from __future__ import annotations

import os
from typing import Callable, Iterator, List, Optional, Tuple

import pyarrow as pa
import pyarrow.ipc

from ballista_tpu.errors import ExecutionError, InternalError
from ballista_tpu.physical.expr import PhysicalExpr
from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
)
from ballista_tpu.physical.repartition import hash_rows
from ballista_tpu.physical.expr import _as_array


class PartitionStats:
    """Row/batch/byte counts for a materialized partition
    (ref utils.rs:49-84 PartitionStats accumulation)."""

    def __init__(self, num_rows: int = 0, num_batches: int = 0, num_bytes: int = 0) -> None:
        self.num_rows = num_rows
        self.num_batches = num_batches
        self.num_bytes = num_bytes

    def __repr__(self) -> str:
        return f"PartitionStats(rows={self.num_rows}, batches={self.num_batches}, bytes={self.num_bytes})"


def _ipc_options(codec: Optional[str]) -> Optional[pa.ipc.IpcWriteOptions]:
    """Shuffle piece compression (ballista.shuffle.codec: "", zstd, lz4).
    Readers decompress transparently — the frame carries the codec."""
    if not codec:
        return None
    return pa.ipc.IpcWriteOptions(compression=codec)


def _piece_tmp_path(path: str) -> str:
    """Writer-unique temp name beside the final piece. Pieces are published
    by os.replace so a reader (or a concurrent duplicate execution — e.g. a
    client retrying an execute_partition whose first run is still going)
    never sees a half-written or interleaved file: last complete writer
    wins atomically."""
    import threading

    return f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"


def write_stream_to_disk(
    batches: Iterator[pa.RecordBatch], schema: pa.Schema, path: str,
    codec: Optional[str] = None, pre_publish=None,
) -> PartitionStats:
    """Arrow IPC file writer with stats (ref utils.rs write_stream_to_disk).
    Writes to a temp name and atomically publishes on success. `pre_publish`
    (shared tier, ISSUE 15) runs after the temp file closed clean and before
    the os.replace — a raise there is a TORN write: the temp is discarded
    and nothing was published, exactly the failure the shuffle.store chaos
    site rehearses."""
    stats = PartitionStats()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = _piece_tmp_path(path)
    try:
        with pa.ipc.new_file(tmp, schema, options=_ipc_options(codec)) as w:
            for b in batches:
                w.write_batch(b)
                stats.num_rows += b.num_rows
                stats.num_batches += 1
                stats.num_bytes += b.nbytes
        if pre_publish is not None:
            pre_publish()
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return stats


def shuffle_output_base(
    ctx: TaskContext, job_id: str, stage_id: int, partition: int
) -> Tuple[str, str]:
    """(piece-set base dir, storage_uri) for one map task's output.

    Shared tier: the base roots at ballista.shuffle.dir and doubles as the
    storage_uri — the location's home is the path itself, so any node with
    the mount resolves the pieces without the producing executor. Local
    tier: the executor's private work dir, storage_uri empty (peers fetch
    over Flight, the reference design)."""
    root = ctx.config.shuffle_storage_root()
    if root:
        base = os.path.join(root, job_id, str(stage_id), str(partition))
        return base, base
    if ctx.work_dir is None:
        raise ExecutionError("shuffle write requires a work_dir")
    return os.path.join(ctx.work_dir, job_id, str(stage_id), str(partition)), ""


def read_ipc_file(path: str) -> Iterator[pa.RecordBatch]:
    with pa.ipc.open_file(path) as r:
        for i in range(r.num_record_batches):
            yield r.get_batch(i)


class _ExchangeCapture:
    """Producer-side tee for the HBM-resident exchange tier (ISSUE 16):
    accumulates the batches streaming through a shuffle write, per output
    piece, until ballista.tpu.residency_budget_bytes says stop — the write
    itself is untouched (the disk piece stays the authoritative home), and
    an over-budget capture is abandoned wholesale rather than registering a
    partial piece. Published to ops/exchange.py only AFTER the atomic
    os.replace, so the registry never advertises bytes the piece ladder
    cannot also produce."""

    def __init__(self, ctx: TaskContext, job_id: str, stage_id: int,
                 map_partition: int, attempt: int) -> None:
        self.executor_id = ctx.executor_id
        self.job_id = job_id
        self.stage_id = stage_id
        self.map_partition = map_partition
        self.attempt = attempt
        self.budget = ctx.config.residency_budget()
        # per-tenant residency cap (ISSUE 19 satellite): captured from the
        # job's config here so the registry's leaf lock never reads config
        self.tenant = ctx.config.tenant()
        self.tenant_budget = ctx.config.tenant_residency_budget()
        self.nbytes = 0
        self.overflow = False
        self.pieces: dict = {}  # piece idx -> [RecordBatch]

    @staticmethod
    def for_task(ctx: TaskContext, job_id: str, stage_id: int,
                 partition: int) -> "Optional[_ExchangeCapture]":
        """A capture when the exchange tier is on AND this context runs on
        a real executor (empty executor_id = in-process/local engine, where
        a process-global registry would fake same-executor locality)."""
        if not ctx.executor_id or not ctx.config.tpu_exchange():
            return None
        return _ExchangeCapture(ctx, job_id, stage_id, partition, ctx.attempt)

    def add(self, piece: int, batch: pa.RecordBatch) -> None:
        if self.overflow or not batch.num_rows:
            return
        self.nbytes += batch.nbytes
        if self.nbytes > self.budget:
            self.overflow = True
            self.pieces = {}
            return
        self.pieces.setdefault(piece, []).append(batch)

    def publish(self, schema: pa.Schema, finals: dict) -> bool:
        """Register the captured pieces; `finals` maps piece idx -> the
        published on-disk path. Returns whether anything was kept."""
        from ballista_tpu.ops import exchange
        from ballista_tpu.ops.runtime import record_exchange

        if self.overflow:
            record_exchange("skipped_budget")
            return False
        kept = False
        for piece, batches in self.pieces.items():
            kept |= exchange.publish(
                self.executor_id, self.job_id, self.stage_id,
                self.map_partition, piece, batches, schema,
                self.attempt, finals[piece], self.budget,
                tenant=self.tenant, tenant_budget=self.tenant_budget,
            )
        return kept


class ShuffleWriterExec(ExecutionPlan):
    """Stage-top operator: executes one input partition of its child and
    materializes it, hash/round-robin split across output partitions."""

    def __init__(
        self,
        job_id: str,
        stage_id: int,
        input: ExecutionPlan,
        output_partitioning: Optional[Partitioning] = None,
    ) -> None:
        self.job_id = job_id
        self.stage_id = stage_id
        self.input = input
        # None -> passthrough (one output piece per input partition)
        self.shuffle_output_partitioning = output_partitioning

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def output_partitioning(self) -> Partitioning:
        # tasks are per INPUT partition
        return self.input.output_partitioning()

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "ShuffleWriterExec":
        return ShuffleWriterExec(
            self.job_id, self.stage_id, children[0], self.shuffle_output_partitioning
        )

    def out_partition_count(self) -> int:
        if self.shuffle_output_partitioning is None:
            return self.input.output_partitioning().partition_count()
        return self.shuffle_output_partitioning.partition_count()

    # ------------------------------------------------------------------
    def _storage_publish_chaos(self, partition: int, ctx: TaskContext):
        """Pre-publish hook for the shared tier: a `shuffle.store` write
        verdict (keyed on plan coordinates + attempt, so the retried
        attempt draws fresh) raises AFTER the temp pieces closed clean and
        BEFORE any os.replace — a torn publish that leaves nothing visible.
        None on the local tier (the site is about the storage tier)."""
        from ballista_tpu.utils.chaos import chaos_from_config

        chaos = chaos_from_config(ctx.config)
        if chaos is None:
            return None

        def pre_publish() -> None:
            from ballista_tpu.ops.runtime import record_shuffle_tier
            from ballista_tpu.utils.chaos import ChaosInjected

            try:
                chaos.maybe_fail(
                    "shuffle.store",
                    f"w{self.stage_id}/{partition}@a{ctx.attempt}",
                )
            except ChaosInjected:
                record_shuffle_tier("storage_publish_torn")
                raise

        return pre_publish

    def execute_shuffle_write(self, partition: int, ctx: TaskContext) -> PartitionStats:
        """Run the child partition and write the split pieces; returns
        aggregate stats. Piece paths: {base}/{m}.arrow with {base} from
        shuffle_output_base — the executor work dir (local tier) or the
        shared storage dir (shared tier, same atomic publish)."""
        from ballista_tpu.ops.runtime import record_shuffle_tier

        base, storage_uri = shuffle_output_base(
            ctx, self.job_id, self.stage_id, partition
        )
        schema = self.schema()
        pscheme = self.shuffle_output_partitioning
        total = PartitionStats()
        codec = ctx.config.shuffle_codec()
        pre_publish = (
            self._storage_publish_chaos(partition, ctx) if storage_uri else None
        )
        capture = _ExchangeCapture.for_task(
            ctx, self.job_id, self.stage_id, partition
        )
        if pscheme is None:
            piece_path = os.path.join(base, "0.arrow")

            def teed() -> Iterator[pa.RecordBatch]:
                for b in self.input.execute(partition, ctx):
                    if capture is not None:
                        capture.add(0, b)
                    yield b

            stats = write_stream_to_disk(
                teed(), schema, piece_path, codec=codec,
                pre_publish=pre_publish,
            )
            record_shuffle_tier(
                "storage_publish" if storage_uri else "local_publish"
            )
            if capture is not None:
                # only after the atomic publish: the registry must never
                # advertise a piece the ladder cannot also produce
                capture.publish(schema, {0: piece_path})
            return stats
        n_out = pscheme.partition_count()
        writers = []
        os.makedirs(base, exist_ok=True)
        opts = _ipc_options(codec)
        finals = [os.path.join(base, f"{m}.arrow") for m in range(n_out)]
        tmps = [_piece_tmp_path(p) for p in finals]
        for tmp in tmps:
            sink = pa.OSFile(tmp, "wb")
            writers.append((sink, pa.ipc.new_file(sink, schema, options=opts)))
        ok = False
        try:
            import numpy as np

            from ballista_tpu.physical.repartition import split_by_partition

            for batch in self.input.execute(partition, ctx):
                if pscheme.scheme == "hash":
                    keys = [
                        _as_array(e.evaluate(batch), batch.num_rows)
                        for e in pscheme.exprs
                    ]
                    ids = hash_rows(keys, n_out)
                else:
                    ids = np.arange(batch.num_rows, dtype=np.int64) % n_out
                for m, piece in enumerate(split_by_partition(batch, ids, n_out)):
                    if piece.num_rows:
                        writers[m][1].write_batch(piece)
                        if capture is not None:
                            capture.add(m, piece)
                        total.num_rows += piece.num_rows
                        total.num_bytes += piece.nbytes
                total.num_batches += 1
            if pre_publish is not None:
                # shared-tier torn-write seam: raising here leaves ok=False,
                # so every temp piece is discarded and nothing publishes
                pre_publish()
            ok = True
        finally:
            for sink, w in writers:
                w.close()
                sink.close()
            if ok:
                # publish atomically only after EVERY piece closed clean —
                # readers (and concurrent duplicate executions) never see a
                # partial or interleaved piece
                for tmp, final in zip(tmps, finals):
                    os.replace(tmp, final)
            else:
                for tmp in tmps:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
        if ok:
            record_shuffle_tier(
                "storage_publish" if storage_uri else "local_publish"
            )
            if capture is not None:
                capture.publish(schema, dict(enumerate(finals)))
        return total

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        # in-process fallback: write then read back the pieces concatenated
        self.execute_shuffle_write(partition, ctx)
        base, _storage = shuffle_output_base(
            ctx, self.job_id, self.stage_id, partition
        )
        for name in sorted(os.listdir(base)):
            # only PUBLISHED pieces: a concurrent duplicate execution's
            # in-flight *.tmp-* files are not readable IPC yet
            if name.endswith(".arrow"):
                yield from read_ipc_file(os.path.join(base, name))

    def fmt(self) -> str:
        return (
            f"ShuffleWriterExec: job={self.job_id}, stage={self.stage_id}, "
            f"out={self.shuffle_output_partitioning!r}"
        )


class ShuffleLocation:
    """Where one completed map task's output lives. stage_id/map_partition
    name the producing map task (lineage): a reduce task that fails to fetch
    from here reports them in its fetch_failed status so the scheduler can
    recompute exactly that map partition.

    storage_uri (ISSUE 15): non-empty when the piece set lives in the
    SHARED storage tier — the home is then the path itself, readers resolve
    it from the mount first, and the executor coordinates degrade to a
    fallback transport rather than the data's single point of failure.

    resident (ISSUE 16): a HINT that the producing executor also registered
    this piece set in its HBM-resident exchange registry — a same-executor
    consumer resolves it with zero decode and zero re-upload, and the
    scheduler prefers placing consumers where their inputs are resident.
    Never load-bearing: a stale hint (evicted entry, dead producer) just
    falls through to the authoritative piece ladder."""

    def __init__(
        self,
        executor_id: str,
        host: str,
        port: int,
        path: str,
        stage_id: int = 0,
        map_partition: int = 0,
        storage_uri: str = "",
        resident: bool = False,
        nbytes: int = 0,
    ) -> None:
        self.executor_id = executor_id
        self.host = host
        self.port = port
        self.path = path  # base dir containing {m}.arrow pieces
        self.stage_id = stage_id
        self.map_partition = map_partition
        self.storage_uri = storage_uri
        self.resident = resident
        # total piece-set bytes (PartitionStats.num_bytes): sizes the
        # scheduler's predicted transfer saving for locality ordering
        self.nbytes = nbytes

    def __repr__(self) -> str:
        home = f", storage={self.storage_uri}" if self.storage_uri else ""
        return (
            f"ShuffleLocation({self.executor_id}@{self.host}:{self.port}, "
            f"{self.path}, map={self.stage_id}/{self.map_partition}{home})"
        )


class ShuffleReaderExec(ExecutionPlan):
    """Leaf reading previously materialized shuffle output
    (ref shuffle_reader.rs:33-100). For output partition m it fetches piece m
    from every map task's location — local disk read or Flight fetch via
    ctx.shuffle_fetcher."""

    def __init__(
        self,
        locations: List[ShuffleLocation],
        schema: pa.Schema,
        num_partitions: int,
        identity: bool = False,
    ) -> None:
        self.locations = locations
        self._schema = schema
        self.num_partitions = num_partitions
        # identity mapping: output partition m is exactly map task m's single
        # piece (a passthrough/merge boundary, no re-split)
        self.identity = identity

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(self.num_partitions)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        if self.identity:
            loc = self.locations[partition]
            yield from self._read_piece(loc, 0, ctx)
            return
        workers = ctx.config.tpu_ingest_workers()
        if workers <= 0 or len(self.locations) <= 1:
            for loc in self.locations:
                yield from self._read_piece(loc, partition, ctx)
            return
        # per-location fetches are independent (local disk read or a Flight
        # round-trip to the owning executor, each with its own client):
        # fetch up to `workers` pieces concurrently so reduce stages overlap
        # network with decode, but yield pieces in location order — batch
        # order must match the serial loop exactly. Tradeoff vs the serial
        # loop: overlapping requires buffering, so up to ingest_depth + 1
        # WHOLE pieces are resident at once (a piece is one map task's
        # output for this reduce partition, i.e. ~1/num_partitions of a map
        # task) where the serial path streams batch-by-batch; set
        # ingest_workers=0 to restore the streaming read if pieces are huge.
        from ballista_tpu.ops.runtime import ordered_map

        def fetch(loc: ShuffleLocation) -> List[pa.RecordBatch]:
            return list(self._read_piece(loc, partition, ctx))

        for piece_batches in ordered_map(
            fetch, self.locations, workers, ctx.config.tpu_ingest_depth()
        ):
            yield from piece_batches

    def _read_piece(
        self, loc: ShuffleLocation, piece_idx: int, ctx: TaskContext
    ) -> Iterator[pa.RecordBatch]:
        from ballista_tpu.errors import RpcError, ShuffleFetchError
        from ballista_tpu.utils.chaos import ChaosInjected, chaos_from_config

        piece = os.path.join(loc.path, f"{piece_idx}.arrow")
        chaos = chaos_from_config(ctx.config)
        if chaos is not None:
            try:
                # keyed on PLAN coordinates (map stage/partition + piece) +
                # the consuming attempt — never on job id or paths, which
                # are random per run: the same seed injects the same faults
                # every run, and the retry after a lineage recompute draws a
                # fresh verdict instead of failing forever
                chaos.maybe_fail(
                    "flight.fetch",
                    f"{loc.stage_id}/{loc.map_partition}/piece{piece_idx}"
                    f"@a{ctx.attempt}",
                )
            except ChaosInjected as e:
                # surface exactly like a real lost fetch so the injected
                # fault drives the fetch_failed -> lineage-recompute path
                raise ShuffleFetchError(
                    f"shuffle fetch of {piece} from {loc.executor_id}: {e}",
                    executor_id=loc.executor_id,
                    host=loc.host,
                    port=loc.port,
                    path=loc.path,
                    stage_id=loc.stage_id,
                    map_partition=loc.map_partition,
                ) from e
        if (
            ctx.executor_id
            and loc.executor_id == ctx.executor_id
            and ctx.config.tpu_exchange()
        ):
            # HBM-resident exchange (ISSUE 16): this executor produced the
            # piece, so resolve its OWN residency registry first — zero
            # decode, zero re-upload. Every miss (evicted, over budget,
            # chaos, never registered) falls through to the authoritative
            # ladder below, bit-identical by construction. The probe keys
            # on ctx.executor_id, so a StandaloneCluster's co-resident
            # executors never see false "local" hits.
            from ballista_tpu.ops import exchange
            from ballista_tpu.ops.runtime import record_exchange

            if chaos is not None and chaos.should_inject(
                "exchange.evict",
                f"{loc.stage_id}/{loc.map_partition}/piece{piece_idx}"
                f"@a{ctx.attempt}",
            ):
                # seeded eviction between produce and consume: drop the
                # entry and take the ladder — a cache going cold is never
                # a task failure, so zero retries by construction
                from ballista_tpu.ops.runtime import record_recovery

                record_recovery("chaos_injected")
                if exchange.evict(
                    ctx.executor_id, ctx.job_id, loc.stage_id,
                    loc.map_partition, piece_idx,
                ):
                    record_exchange("evicted_chaos")
            hit = exchange.resolve(
                ctx.executor_id, ctx.job_id, loc.stage_id,
                loc.map_partition, piece_idx,
            )
            if hit is not None:
                batches, nbytes = hit
                record_exchange("reupload_skipped")
                record_exchange("h2d_bytes_saved", nbytes)
                yield from batches
                return
            record_exchange("miss")
        if loc.storage_uri:
            # disaggregated tier (ISSUE 15): the piece's home is a PATH —
            # resolve it from the shared mount first. A shuffle.store READ
            # verdict (keyed like flight.fetch on plan coordinates + the
            # consuming attempt) makes the published piece unreadable for
            # this attempt, exercising the fallback ladder: Flight peer
            # fetch below, then fetch_failed -> lineage recompute — the
            # recomputed map republishes and the requeued consumer's fresh
            # attempt draws a fresh verdict.
            from ballista_tpu.ops.runtime import (
                record_recovery,
                record_shuffle_tier,
            )

            torn = chaos is not None and chaos.should_inject(
                "shuffle.store",
                f"r{loc.stage_id}/{loc.map_partition}/piece{piece_idx}"
                f"@a{ctx.attempt}",
            )
            if torn:
                record_recovery("chaos_injected")
                record_shuffle_tier("storage_read_torn")
            else:
                resolved = self._storage_read_path(piece, ctx)
                if resolved is not None and os.path.exists(resolved):
                    record_shuffle_tier("storage_fetch")
                    yield from read_ipc_file(resolved)
                    return
            record_shuffle_tier("storage_fallback_peer")
            if not loc.host or not loc.port:
                # no live peer to fall back to (the producing executor is
                # gone and its metadata never bound): the piece is LOST for
                # this attempt — name it so lineage recomputes exactly it
                raise ShuffleFetchError(
                    f"storage-homed shuffle piece {piece} unreadable and "
                    f"no peer fallback (producer {loc.executor_id} gone)",
                    executor_id=loc.executor_id,
                    host=loc.host,
                    port=loc.port,
                    path=loc.path,
                    stage_id=loc.stage_id,
                    map_partition=loc.map_partition,
                )
        resolved = self._local_read_path(piece, ctx)
        if resolved is not None and os.path.exists(resolved):
            yield from read_ipc_file(resolved)
        elif ctx.shuffle_fetcher is not None:
            from ballista_tpu.ops.runtime import record_shuffle_tier

            record_shuffle_tier("peer_fetch")
            try:
                yield from ctx.shuffle_fetcher(loc, piece_idx)
            except ShuffleFetchError:
                raise
            except RpcError as e:
                # attach the lineage of the lost location: the executor's
                # task runner turns this into a fetch_failed status and the
                # scheduler recomputes ONLY loc's map partition
                raise ShuffleFetchError(
                    f"shuffle fetch of {piece} from "
                    f"{loc.executor_id}@{loc.host}:{loc.port} failed: {e}",
                    executor_id=loc.executor_id,
                    host=loc.host,
                    port=loc.port,
                    path=loc.path,
                    stage_id=loc.stage_id,
                    map_partition=loc.map_partition,
                ) from e
        else:
            raise ExecutionError(
                f"shuffle piece not found locally and no fetcher: {piece}"
            )

    @staticmethod
    def _storage_read_path(piece: str, ctx: TaskContext):
        """Resolved shared-storage path for a storage-homed piece, or None
        when this reader has no storage access (no ballista.shuffle.dir —
        e.g. a local-tier consumer handed a storage-homed location by a
        mixed deployment; the Flight fallback still works). Confined to the
        READER'S OWN configured storage root, exactly like the work-dir
        shortcut: the location path arrived over the wire and must not be
        able to name arbitrary host files."""
        from ballista_tpu.executor.confine import resolve_contained

        root = ctx.config.shuffle_dir()
        if not root:
            return None
        return resolve_contained(piece, root)

    @staticmethod
    def _local_read_path(piece: str, ctx: TaskContext):
        """Resolved path for the local-disk shortcut, or None to use the
        Flight fetcher. The shortcut is only for THIS task's own job
        directory: a wire plan can carry arbitrary ShuffleLocation paths,
        and reading them from local disk would let a peer exfiltrate
        another job's shuffle pieces (or any host .arrow file) — those go
        through the fetcher instead, where the OWNING executor confines the
        path to its work_dir. The RESOLVED path is returned and opened (not
        the raw one), so a symlink swapped after the check cannot escape.
        A trusted in-process context (no work_dir, no fetcher) keeps the
        direct read."""
        from ballista_tpu.executor.confine import resolve_contained

        if ctx.work_dir is None:
            return piece if ctx.shuffle_fetcher is None else None
        root = (
            os.path.join(ctx.work_dir, ctx.job_id) if ctx.job_id else ctx.work_dir
        )
        return resolve_contained(piece, root)

    def fmt(self) -> str:
        return f"ShuffleReaderExec: partitions={self.num_partitions}, maps={len(self.locations)}"


class UnresolvedShuffleExec(ExecutionPlan):
    """Placeholder for a dependency stage whose outputs don't exist yet
    (ref unresolved_shuffle.rs). Refuses to execute."""

    def __init__(self, stage_id: int, schema: pa.Schema, partition_count: int,
                 identity: bool = False) -> None:
        self.stage_id = stage_id
        self._schema = schema
        self.partition_count = partition_count
        self.identity = identity

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(self.partition_count)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        raise InternalError(
            f"UnresolvedShuffleExec(stage={self.stage_id}) cannot execute; "
            "the scheduler must substitute a ShuffleReaderExec"
        )

    def fmt(self) -> str:
        return f"UnresolvedShuffleExec: stage={self.stage_id}, partitions={self.partition_count}"

"""Daemon configuration with the reference's precedence chain.

The reference generates config parsing from TOML specs via configure_me
(rust/executor/executor_config_spec.toml, rust/scheduler/scheduler_config_spec.toml)
with precedence: defaults < env (BALLISTA_SCHEDULER_*/BALLISTA_EXECUTOR_*)
< config file (/etc/ballista/*.toml or --config-file) < CLI
(docs/user-guide/src/configuration.md:1-16).
"""

from __future__ import annotations

import argparse
import os

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: same API under the PyPI name
    import tomli as tomllib
from typing import Any, Dict, List, Optional, Tuple

SCHEDULER_SPEC: List[Tuple[str, Any, str]] = [
    # (name, default, help)
    ("namespace", "ballista", "cluster namespace"),
    ("config_backend", "standalone", "standalone | sqlite | etcd"),
    ("sqlite_path", "/tmp/ballista-scheduler.db", "sqlite backend db path"),
    ("etcd_urls", "localhost:2379", "etcd endpoints (etcd backend)"),
    ("bind_host", "0.0.0.0", "bind address"),
    ("port", 50050, "grpc port"),
    ("data_roots", "", "comma-separated dirs wire-plan scans may read ('' = any)"),
]

EXECUTOR_SPEC: List[Tuple[str, Any, str]] = [
    ("namespace", "ballista", "cluster namespace"),
    ("scheduler_host", "localhost", "scheduler hostname"),
    ("scheduler_port", 50050, "scheduler grpc port"),
    ("local", False, "spin an in-process scheduler (single-node mode)"),
    ("bind_host", "0.0.0.0", "flight bind address"),
    ("external_host", "localhost", "address peers use to reach this executor"),
    ("port", 50051, "flight port"),
    ("work_dir", "", "shuffle work dir (default: temp dir)"),
    ("concurrent_tasks", 4, "max concurrent tasks"),
    ("backend", "cpu", "kernel backend: cpu | tpu"),
    ("data_roots", "", "comma-separated dirs wire-plan scans may read ('' = any)"),
    # disaggregated shuffle tier (ISSUE 15): 'shared' publishes pieces to
    # shuffle_dir (a mount every node sees) instead of the private work
    # dir, so executor loss/retirement destroys no shuffle data
    ("shuffle_tier", "local", "shuffle piece home: local | shared"),
    ("shuffle_dir", "", "shared-storage root for the shared shuffle tier"),
]


def load_config(
    spec: List[Tuple[str, Any, str]],
    env_prefix: str,
    default_file: str,
    argv: Optional[List[str]] = None,
    prog: str = "ballista",
) -> Dict[str, Any]:
    values: Dict[str, Any] = {name: default for name, default, _ in spec}
    types = {name: type(default) for name, default, _ in spec}

    def coerce(name: str, raw: Any) -> Any:
        t = types[name]
        if t is bool and isinstance(raw, str):
            return raw.lower() in ("1", "true", "yes")
        return t(raw)

    # 1. environment
    for name in values:
        env = f"{env_prefix}{name.upper()}"
        if env in os.environ:
            values[name] = coerce(name, os.environ[env])

    # CLI pre-pass for --config-file
    ap = argparse.ArgumentParser(prog=prog)
    ap.add_argument("--config-file")
    for name, default, help_ in spec:
        flag = "--" + name.replace("_", "-")
        if types[name] is bool:
            ap.add_argument(flag, action="store_true", default=None, help=help_)
        else:
            ap.add_argument(flag, default=None, help=help_)
    args = ap.parse_args(argv)

    # 2. config file
    path = args.config_file or default_file
    if path and os.path.isfile(path):
        with open(path, "rb") as f:
            file_cfg = tomllib.load(f)
        for name, raw in file_cfg.items():
            key = name.replace("-", "_")
            if key in values:
                values[key] = coerce(key, raw)

    # 3. CLI wins
    for name in values:
        raw = getattr(args, name, None)
        if raw is not None:
            values[name] = coerce(name, raw)
    return values

"""Convenience re-exports (the reference's prelude, rust/client/src/prelude.rs).

    from ballista_tpu.prelude import *
"""

from ballista_tpu.client import BallistaContext, BallistaDataFrame  # noqa: F401
from ballista_tpu.client.flight import BallistaClient  # noqa: F401
from ballista_tpu.config import BallistaConfig  # noqa: F401
from ballista_tpu.engine import DataFrame, ExecutionContext  # noqa: F401
from ballista_tpu.errors import BallistaError  # noqa: F401
from ballista_tpu.logical import col, lit  # noqa: F401
from ballista_tpu.logical.expr import functions  # noqa: F401

__all__ = [
    "BallistaContext",
    "BallistaDataFrame",
    "BallistaClient",
    "BallistaConfig",
    "DataFrame",
    "ExecutionContext",
    "BallistaError",
    "col",
    "lit",
    "functions",
]

"""SPMD co-partitioned join: the hash-repartition exchange as ONE mesh
program over ICI.

The reference feeds a partitioned join through two materialized hash
shuffles (RepartitionExec -> ShuffleWriter/Reader pairs,
rust/core/proto/ballista.proto:415-422, rust/scheduler/src/planner.rs:114-148)
and joins partition pairs on the CPU. The TPU-native restructuring (SURVEY
§2.8's RepartitionExec -> lax.all_to_all mapping): key-hash buckets are
exchanged between mesh shards with `lax.all_to_all` inside one shard_map
program, and each shard matches its key range with sort + searchsorted —
the same regular, scatter-free shape the device join kernel uses
(ops/join.py).

What travels over the mesh is (dense key code, row id) per side — the
matching plane. Payload columns do NOT ride the ICI exchange: on a
single-host mesh every payload row is already host-local, so the final
assembly is a zero-copy Arrow take on the matched row-id pairs the program
returns (sending payloads through the chip would add two transfers for
data the host already holds). On a multi-host pod the payload legs ride
the host data plane (Arrow Flight, client/flight.py) exactly like the
reference's shuffle pieces; the ICI program still eliminates the
materialize-sort-merge of the key-matching plane.

Key coding is shared with the host join (physical/joinutil.py): any Arrow
key type, composite keys, nulls -> -1 (never match). Coding is dense, so
bucket ownership `splitmix(code) % n_dev` balances shards and codes fit
int32 for the device sort.

Duplicate build keys run ON the mesh: each shard computes per-probe match
run-lengths with paired searchsorted (side='left'/'right') and materializes
them through a bounded-width gather whose static width is the smallest
admission tier (ops/kernels.py::JOIN_MULTIPLICITY_TIERS) covering the
build side's observed maximum key multiplicity — the same M:N program
shape as the single-chip device join (ops/join.py).

Decline-to-host (the wrapped subplan is the untouched original subtree):
non-INNER/LEFT join types, residual filters, multiplicity past the top
admission tier (steps aside to the inline host join), or any device
error. Every outcome is recorded via runtime.record_join_path so bench's
per-config join counters stay truthful.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np
import pyarrow as pa

from ballista_tpu.logical.plan import JoinType
from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
    collect_all,
)
from ballista_tpu.physical.repartition import RepartitionExec, _splitmix64


def _strip_repartition(node: ExecutionPlan) -> ExecutionPlan:
    """The mesh program IS the exchange: read the repartition's input."""
    return node.input if isinstance(node, RepartitionExec) else node


class SpmdJoinExec(ExecutionPlan):
    """Executes HashJoin(Repartition(L), Repartition(R)) as one mesh program.

    Mirrors SpmdAggregateExec's contract: single output partition, the
    wrapped subplan serialized whole (serde + host fallback), `last_path`
    records whether the mesh actually ran.
    """

    def __init__(self, subplan) -> None:
        from ballista_tpu.physical.join import HashJoinExec

        assert isinstance(subplan, HashJoinExec)
        self.subplan = subplan  # the HashJoinExec, kept whole for serde
        self._mesh = None
        self._program = None
        self._program_key = None
        self.last_path: Optional[str] = None

    # ------------------------------------------------------------------
    def schema(self) -> pa.Schema:
        return self.subplan.schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> List[ExecutionPlan]:
        return []  # serialized/traversed whole; must stay one stage

    def with_children(self, children: List[ExecutionPlan]) -> "SpmdJoinExec":
        assert not children
        return self

    def fmt(self) -> str:
        on = ", ".join(f"{l} = {r}" for l, r in self.subplan.on)
        return (
            f"SpmdJoinExec: type={self.subplan.join_type.value}, on=[{on}], "
            "all_to_all exchange as one mesh program"
        )

    # ------------------------------------------------------------------
    def _build_mesh(self, ctx: TaskContext):
        import jax

        from ballista_tpu.parallel.mesh import build_mesh

        if self._mesh is not None:
            return self._mesh
        shape = ctx.config.mesh_shape() or None
        try:
            self._mesh = build_mesh(shape)
        except ValueError:
            self._mesh = build_mesh({"data": len(jax.devices())})
        return self._mesh

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        from ballista_tpu.utils import tracing

        assert partition == 0
        if ctx.backend != "tpu":
            yield from self._execute_host(ctx)
            return
        try:
            self._inline_host = False
            self._mesh_cost = (None, None)
            out = self._execute_mesh(ctx)
            self.last_path = "host-inline" if self._inline_host else "mesh"
            tracing.incr(
                "spmd.join_host_inline" if self._inline_host
                else "spmd.join_mesh"
            )
            if not self._inline_host:
                from ballista_tpu.ops.runtime import (
                    record_join_path,
                    record_routing,
                )

                predicted, observed = self._mesh_cost
                record_join_path("device")
                record_routing(
                    "device", "join.mesh",
                    predicted_s=predicted, observed_s=observed,
                )
        except Exception:
            import logging
            import sys

            from ballista_tpu.ops.runtime import (
                UnsupportedOnDevice,
                record_join_path,
            )

            exc = sys.exc_info()[1]
            tracing.incr("spmd.join_host_fallback")
            # reasoned declines carry their (bounded) reason text; arbitrary
            # errors record only the exception type, or a long-lived
            # executor's reason map would grow one key per distinct message
            record_join_path(
                "host_fallback",
                f"mesh join: {exc}" if isinstance(exc, UnsupportedOnDevice)
                else f"mesh join error: {type(exc).__name__}",
            )
            from ballista_tpu.ops.runtime import record_routing

            record_routing("host", "join.mesh")
            if not isinstance(exc, UnsupportedOnDevice):
                logging.getLogger("ballista.spmd").warning(
                    "mesh join failed, host fallback: %s", exc
                )
            self.last_path = "host"
            yield from self._execute_host(ctx)
            return
        yield from batch_table(out, ctx.batch_size)

    def _execute_host(self, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        yield from batch_table(collect_all(self.subplan, ctx), ctx.batch_size)

    # ------------------------------------------------------------------
    def _execute_mesh(self, ctx: TaskContext) -> pa.Table:
        import jax
        import jax.numpy as jnp

        from ballista_tpu.ops.runtime import UnsupportedOnDevice, readback
        from ballista_tpu.physical.joinutil import (
            combined_key_codes,
            take_table,
        )
        from ballista_tpu.physical.joinutil import _refactorize

        if jax.process_count() > 1:
            # pod runs: collect_all below reads HOST-LOCAL rows, but the
            # mesh spans every process — shard_map would feed each host's
            # partial arrays to a global program (wrong results or a hang).
            # The aggregate path has a multihost protocol; the join does
            # not yet — decline to the host join.
            raise UnsupportedOnDevice("mesh join v1 is single-host")

        join = self.subplan
        if join.join_type not in (JoinType.INNER, JoinType.LEFT):
            raise UnsupportedOnDevice(f"mesh join type {join.join_type.value}")
        if join.filter is not None:
            raise UnsupportedOnDevice("mesh join residual filter")

        mesh = self._build_mesh(ctx)
        n_dev = int(np.prod(list(mesh.shape.values())))

        # the mesh replaces the hash exchange: read the repartition inputs
        left = collect_all(_strip_repartition(join.left), ctx)
        right = collect_all(_strip_repartition(join.right), ctx)
        if max(left.num_rows, right.num_rows) >= (1 << 31):
            raise UnsupportedOnDevice("row ids exceed int32")

        lkeys = [n for n, _ in join.on]
        rkeys = [n for _, n in join.on]
        bcodes, pcodes = combined_key_codes(
            [left.column(k) for k in lkeys], [right.column(k) for k in rkeys]
        )
        if left.num_rows == 0 or right.num_rows == 0:
            # no mesh work to do; join inline over what was collected
            return self._host_join_collected(
                left, right, bcodes, pcodes, reason="empty join side"
            )
        hi = max(int(bcodes.max()), int(pcodes.max()))
        if hi >= (1 << 31):
            # dense re-map: distinct count <= row count < 2^31. _refactorize
            # assigns the -1 null sentinel a dense code too — restore it, or
            # null keys would match each other on the mesh
            bnull, pnull = bcodes < 0, pcodes < 0
            bcodes, pcodes, _ = _refactorize(bcodes, pcodes)
            bcodes = np.where(bnull, -1, bcodes)
            pcodes = np.where(pnull, -1, pcodes)
        # build-key multiplicity bounds the static gather width: the staging
        # pass below already touches every code, so the max duplicate count
        # comes from one host bincount-equivalent over the valid build keys
        valid_b = bcodes >= 0
        if valid_b.any():
            _, dup_counts = np.unique(bcodes[valid_b], return_counts=True)
            max_mult = int(dup_counts.max())
        else:
            max_mult = 0

        # ---- host staging: bucket (code, rowid) by key ownership ------
        def stage_side(codes: np.ndarray):
            """Rows -> per-(source shard, dest shard) buckets, padded to a
            common capacity C. Source shard = row % n_dev (each shard would
            read its own partitions on a pod); dest = splitmix(code) % n_dev.
            Returns (codes [n_dev * n_dev*C], rowids same, C)."""
            n = len(codes)
            src = np.arange(n, dtype=np.int64) % n_dev
            dest = (_splitmix64(np.maximum(codes, 0)) % np.uint64(n_dev)).astype(np.int64)
            # bucket sizes per (src, dest)
            flat = src * n_dev + dest
            counts = np.bincount(flat, minlength=n_dev * n_dev)
            C = max(1, int(counts.max()))
            B = n_dev * C
            out_codes = np.full(n_dev * B, -1, dtype=np.int32)
            out_rows = np.full(n_dev * B, -1, dtype=np.int32)
            order = np.argsort(flat, kind="stable")
            sorted_flat = flat[order]
            starts = np.searchsorted(sorted_flat, np.arange(n_dev * n_dev))
            ends = np.searchsorted(sorted_flat, np.arange(n_dev * n_dev), side="right")
            for s in range(n_dev):
                for d in range(n_dev):
                    lo, hi_ = int(starts[s * n_dev + d]), int(ends[s * n_dev + d])
                    rows = order[lo:hi_]
                    base = s * B + d * C
                    out_codes[base: base + len(rows)] = codes[rows]
                    out_rows[base: base + len(rows)] = rows
            return out_codes, out_rows, C

        lc, lr, C_l = stage_side(bcodes)
        pc_, pr, C_p = stage_side(pcodes)

        # admission: smallest static gather width covering the build-key
        # multiplicity; past the ladder the mesh declines to the inline
        # host join (the sides are already collected and coded — no subplan
        # re-execution, no shuffle materialization). host_fallback, not
        # step_aside: the join leaves the device entirely, there is no next
        # device rung — only bench's join_paths kind keeps the admission-
        # tier distinction
        from ballista_tpu.ops import costmodel
        from ballista_tpu.ops.kernels import host_fallback, join_multiplicity_tier

        costmodel.configure(ctx.config)
        width, why = join_multiplicity_tier(max_mult, n_dev * n_dev * C_p)
        if width is None:
            host_fallback(why)
            return self._host_join_collected(
                left, right, bcodes, pcodes, kind="step_aside", reason=why
            )

        # admission rides the cost model (ISSUE 16 satellite): with BOTH
        # the mesh exchange and the inline host join warm for this shape,
        # skip the mesh — and its program compile — when the model says
        # the host wins. Cold on either side → admit, exactly the static
        # ladder above; the mesh path's check_mispredict below keeps its
        # rate honest, and join.host keeps averaging on every inline run,
        # so a side that grows past the host's sweet spot flips back.
        mesh_units = n_dev * n_dev * C_p * width
        mesh_pred = costmodel.predict("join.mesh", mesh_units)
        host_pred = costmodel.predict(
            "join.host", len(bcodes) + len(pcodes), engine="host"
        )
        if (
            mesh_pred is not None
            and host_pred is not None
            and mesh_pred > host_pred
        ):
            return self._host_join_collected(
                left, right, bcodes, pcodes, kind="host_declined",
                reason=(
                    f"cost model: mesh {mesh_pred:.4f}s > "
                    f"host {host_pred:.4f}s"
                ),
            )

        program = self._get_program(
            mesh, n_dev, C_l * n_dev, C_p * n_dev, width,
            want_left_bitmap=join.join_type == JoinType.LEFT,
        )
        # the mesh program's cost lands in the SAME store the single-chip
        # ladder consults (ISSUE 10): one ledger, every device join path.
        # The store is consulted, not just fed — a gross mispredict
        # re-tiers the bucket exactly like the single-chip gather, so the
        # mesh rate tracks the current machine too.
        import time as _time

        predicted = mesh_pred
        t_mesh0 = _time.perf_counter()
        outs = program(
            jnp.asarray(lc), jnp.asarray(lr), jnp.asarray(pc_), jnp.asarray(pr)
        )
        # the matching plane comes back over d2h: account for it, or the
        # bench readback fields undercount the mesh-join path
        # matched build rows per probe slot [n_dev * B_p, width], -1 = no match
        matched = readback(outs[0], rows=outs[0].shape[0])
        recv_prow = readback(outs[1])  # [n_dev * B_p] int32, -1 = pad
        dt_mesh = _time.perf_counter() - t_mesh0
        costmodel.observe("join.mesh", mesh_units, dt_mesh)
        costmodel.check_mispredict("join.mesh", mesh_units, predicted, dt_mesh)
        # hand predicted/observed back to execute()'s decision record so
        # mesh device decisions count toward the bench mispredict accounting
        self._mesh_cost = (predicted, dt_mesh)

        # flatten probe-slot-major: pad/null slots have all-(-1) rows, so
        # their repeat count is 0 and they vanish from the selection
        hits = matched >= 0
        lidx = matched[hits].astype(np.int64)
        ridx = np.repeat(recv_prow, hits.sum(axis=1)).astype(np.int64)
        left_out = take_table(left, lidx)
        right_out = take_table(right, ridx)
        if join.join_type == JoinType.LEFT:
            lmatched = readback(outs[2])  # bool over exchanged left slots
            recv_lrow = readback(outs[3])
            un = recv_lrow[(recv_lrow >= 0) & ~lmatched].astype(np.int64)
            if len(un):
                left_un = take_table(left, un)
                nulls = pa.table(
                    [pa.nulls(len(un), type=f.type) for f in right.schema],
                    schema=right.schema,
                )
                left_out = pa.concat_tables([left_out, left_un])
                right_out = pa.concat_tables([right_out, nulls])
        cols = list(left_out.columns) + list(right_out.columns)
        return pa.table(cols, schema=self.schema())

    def _host_join_collected(
        self, left: pa.Table, right: pa.Table,
        bcodes: np.ndarray, pcodes: np.ndarray,
        kind: str = "host_fallback", reason: str = "",
    ) -> pa.Table:
        """Vectorized host join over the already-collected sides — the
        decline path for shapes the mesh program cannot take (multiplicity
        past the admission tiers, empty sides). Costs one collect + one
        join pass, like the broadcast join these plans had before SPMD
        co-partitioning; no shuffle materialization, no re-execution."""
        from ballista_tpu.ops import costmodel
        from ballista_tpu.ops.runtime import record_join_path, record_routing
        from ballista_tpu.physical.joinutil import join_indices, take_table

        # every inline-host decline is one host routing decision, whatever
        # the reason — recorded here so no caller can forget it
        record_routing("host", "join.mesh")
        record_join_path(kind, reason or None)
        self._inline_host = True
        how = "inner" if self.subplan.join_type == JoinType.INNER else "left"
        with costmodel.timed("join.host", len(bcodes) + len(pcodes),
                             engine="host", predictive=False):
            li, ri = join_indices(bcodes, pcodes, how)
        lt = take_table(left, li)
        rt = take_table(right, ri)
        return pa.table(
            list(lt.columns) + list(rt.columns), schema=self.schema()
        )

    # ------------------------------------------------------------------
    def _get_program(self, mesh, n_dev: int, B_l: int, B_p: int, width: int,
                     want_left_bitmap: bool):
        """shard_map program, jitted once per (capacities, gather width,
        join shape): all_to_all exchange of (code, rowid) for both sides,
        then per-shard sort + paired searchsorted run-lengths + a
        bounded-width gather (M:N multiplicity). Outputs stay sharded
        (P('data')); every shard owns a disjoint key range, so its matches
        are global."""
        key = (n_dev, B_l, B_p, width, want_left_bitmap)
        if self._program_key == key:
            return self._program

        import jax
        import jax.numpy as jnp
        from ballista_tpu.ops.join import gather_matches, match_runs
        from ballista_tpu.parallel.meshcompat import shard_map
        from jax.sharding import PartitionSpec as P

        def a2a(x):
            return jax.lax.all_to_all(
                x, "data", split_axis=0, concat_axis=0, tiled=True
            )

        def per_shard(lcode, lrow, pcode, prow):
            # the exchange: every shard sends bucket d of its slice to
            # shard d and receives all buckets it owns — over ICI, no
            # materialized shuffle
            lcode, lrow = a2a(lcode), a2a(lrow)
            pcode, prow = a2a(pcode), a2a(prow)
            order = jnp.argsort(lcode, stable=True)
            sl = lcode[order]
            slrow = lrow[order]
            # shared M:N core (ops/join.py): per-probe run-lengths +
            # bounded-width gather of the matched build row ids
            starts, counts = match_runs(sl, pcode)
            matched = gather_matches(slrow, starts, counts, width)
            outs = [matched, prow]
            if want_left_bitmap:
                # a left slot is matched iff its key occurs among this
                # shard's probe codes — binary search over the sorted probe
                # plane (duplicate-safe, unlike a one-match scatter)
                sp = jnp.sort(pcode)
                lo = jnp.searchsorted(sp, sl, side="left")
                hi = jnp.searchsorted(sp, sl, side="right")
                hit_sorted = (hi > lo) & (sl >= 0)
                lmatched = jnp.zeros(B_l, dtype=bool).at[order].set(hit_sorted)
                outs.extend([lmatched, lrow])
            return tuple(outs)

        fn = shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(P("data"), P("data"), P("data"), P("data")),
            out_specs=tuple(
                P("data") for _ in range(4 if want_left_bitmap else 2)
            ),
            check_vma=False,
        )
        self._program = jax.jit(fn)
        self._program_key = key
        return self._program

"""SPMD stage programs: distributed aggregation and shuffle over a Mesh.

The reference's two distributed primitives map to in-program collectives
(SURVEY §2.8):

- partial/final aggregation (HashAggregateExec split + shuffle,
  reference rust/scheduler/src/planner.rs:149-171):
  per-shard masked segment-sum partials, merged with lax.psum over ICI —
  no materialize-then-fetch.
- repartition exchange (ShuffleWriter -> Flight fetch -> ShuffleReader,
  reference rust/executor/src/flight_service.rs:104-126 +
  rust/core/src/execution_plans/shuffle_reader.rs:77-99):
  rows bucketed by key ownership and exchanged with lax.all_to_all, then
  aggregated locally on the owning shard.

Programs are built once per (shapes, mesh) and jit-cached by XLA.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Sequence, Tuple


def build_psum_aggregate(mesh, num_groups: int,
                         mask_fn: Callable, value_fns: Sequence[Callable]):
    """Aggregation with replicated output: each shard computes masked
    per-group partial sums from its rows; lax.psum merges over the mesh.

    Inputs to the returned fn: per-column arrays sharded on axis 'data'
    (row dimension), plus a codes array (group id per row, also sharded).
    Returns [1 + n_values, num_groups]: row 0 = counts, then one row per
    value expression. Replicated on all shards.
    """
    import jax
    import jax.numpy as jnp
    from ballista_tpu.parallel.meshcompat import shard_map
    from jax.sharding import PartitionSpec as P

    def per_shard(codes, *cols):
        mask = mask_fn(*cols)
        maskf = mask.astype(jnp.float32)
        safe = jnp.where(mask, codes, num_groups)  # dump slot
        outs = [jax.ops.segment_sum(maskf, safe, num_segments=num_groups + 1)]
        for vf in value_fns:
            v = vf(*cols).astype(jnp.float32)
            outs.append(
                jax.ops.segment_sum(v * maskf, safe, num_segments=num_groups + 1)
            )
        stacked = jnp.stack(outs)[:, :num_groups]  # drop dump slot
        return jax.lax.psum(stacked, "data")

    fn = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P("data"),) + tuple(P("data") for _ in range(n_values_in(value_fns, mask_fn))),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def n_values_in(value_fns, mask_fn) -> int:
    """Number of column inputs — taken from fn arity (they all share the
    same positional column tuple)."""
    import inspect

    return len(inspect.signature(mask_fn).parameters)


def build_all_to_all_exchange_aggregate(mesh, axis: str = "data"):
    """Shuffle-by-key aggregation: each shard buckets its rows by owning
    shard (key % n_dev), exchanges buckets with lax.all_to_all, and the
    owner aggregates its received rows with a local segment-sum.

    Returns fn(keys[data-sharded], values[data-sharded], groups_per_shard)
    -> (owned_sums [n_dev * groups_per_shard] replicated-by-concat layout:
    each shard's slice holds sums for keys with key % n_dev == shard and
    key // n_dev < groups_per_shard).
    """
    import jax
    import jax.numpy as jnp
    from ballista_tpu.parallel.meshcompat import shard_map
    from jax.sharding import PartitionSpec as P

    n_dev = mesh.shape[axis]

    def per_shard(keys, values, groups_per_shard: int):
        s = keys.shape[0]
        tgt = jnp.mod(keys, n_dev).astype(jnp.int32)
        order = jnp.argsort(tgt)
        keys_s = keys[order]
        vals_s = values[order]
        tgt_s = tgt[order]
        onehot = jax.nn.one_hot(tgt_s, n_dev, dtype=jnp.int32)
        pos = (jnp.cumsum(onehot, axis=0) - onehot)
        pos = jnp.take_along_axis(pos, tgt_s[:, None], axis=1)[:, 0]
        # fixed-capacity buckets (worst case: all rows to one target)
        bk = jnp.full((n_dev, s), -1, dtype=keys.dtype)
        bv = jnp.zeros((n_dev, s), dtype=values.dtype)
        bk = bk.at[tgt_s, pos].set(keys_s)
        bv = bv.at[tgt_s, pos].set(vals_s)
        # the exchange: shard i sends bucket j to shard j
        rk = jax.lax.all_to_all(bk, axis, split_axis=0, concat_axis=0, tiled=True)
        rv = jax.lax.all_to_all(bv, axis, split_axis=0, concat_axis=0, tiled=True)
        rk = rk.reshape(-1)
        rv = rv.reshape(-1)
        valid = rk >= 0
        local_group = jnp.where(valid, rk // n_dev, groups_per_shard)
        sums = jax.ops.segment_sum(
            jnp.where(valid, rv, 0.0), local_group, num_segments=groups_per_shard + 1
        )
        return sums[:groups_per_shard]

    def wrapped(keys, values, groups_per_shard: int):
        f = shard_map(
            functools.partial(per_shard, groups_per_shard=groups_per_shard),
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=P(axis),
            check_vma=False,
        )
        return f(keys, values)

    return jax.jit(wrapped, static_argnums=(2,))


def build_q1_style_step(mesh, num_groups: int, cutoff_days: int):
    """The flagship distributed stage: TPC-H q1's pipeline as one SPMD
    program — filter mask, four derived measures, masked per-group partials,
    psum over ICI. Column layout: (codes, qty, price, disc, tax, shipdate)."""
    import jax.numpy as jnp

    def mask_fn(qty, price, disc, tax, ship):
        return ship <= cutoff_days

    value_fns = [
        lambda qty, price, disc, tax, ship: qty,
        lambda qty, price, disc, tax, ship: price,
        lambda qty, price, disc, tax, ship: price * (1.0 - disc),
        lambda qty, price, disc, tax, ship: price * (1.0 - disc) * (1.0 + tax),
        lambda qty, price, disc, tax, ship: disc,
    ]
    return build_psum_aggregate(mesh, num_groups, mask_fn, value_fns)

"""Multi-host mesh utilities: the host-boundary decomposition of an SPMD
stage (SURVEY §2.8: partitions -> shards of a pod mesh).

Contract (the "multi-host story" spmd_stage.py's per-shard decomposition is
written against):

  - input partition p belongs to mesh shard ``p % n_shards``; a host reads
    ONLY partitions whose shard lives on one of its local devices (batches
    may balance freely among a host's OWN shards — that stays host-local).
  - shards exchange only their DISTINCT group keys; every host ranks the
    gathered union identically (same input, same deterministic sort), so
    global group ids agree with no central coordinator.
  - any decline (unsupported shape, overflow risk) must be COLLECTIVE:
    hosts agree with an all-reduce before diverging onto the host path,
    or one host would enter the mesh program alone and hang the pod.

Process topology comes from ``jax.distributed.initialize`` (the reference
reaches multi-host scale with one executor process per node and NCCL/MPI
underneath; here the same SPMD program spans hosts and XLA's collectives
ride ICI/DCN — Gloo on the CPU test backend)."""

from __future__ import annotations

from typing import List

import numpy as np


def local_shard_ids(mesh) -> List[int]:
    """Flat mesh-shard indices owned by THIS process."""
    import jax

    pid = jax.process_index()
    return [
        i for i, d in enumerate(mesh.devices.flat) if d.process_index == pid
    ]


def partition_shard(p: int, n_shards: int) -> int:
    """The host-boundary read-ownership rule: partition -> shard."""
    return p % n_shards


def owned_partitions(n_parts: int, mesh) -> List[int]:
    """Partitions THIS process must read (its shards' partitions)."""
    n_shards = int(np.prod(list(mesh.shape.values())))
    mine = set(local_shard_ids(mesh))
    return [p for p in range(n_parts) if partition_shard(p, n_shards) in mine]


def allgather_rows(x: np.ndarray) -> np.ndarray:
    """Gather variable-length per-process 1-D arrays; returns the
    concatenation (identical on every process). Lengths are exchanged
    first, then data padded to the max."""
    import jax
    from jax.experimental import multihost_utils as mhu

    # normalize bool -> int64 up front: every return path (single-process
    # passthrough, padded gather, empty) must agree on dtype, or one host's
    # empty-bool input concatenates against another's int64 pad buffer
    x = np.asarray(x)
    if x.dtype == np.bool_:
        x = x.astype(np.int64)
    if jax.process_count() == 1:
        return x
    lens = mhu.process_allgather(np.array([len(x)], dtype=np.int64))
    lens = np.asarray(lens).reshape(-1)
    pad = int(lens.max()) if len(lens) else 0
    padded = np.zeros(pad, dtype=x.dtype)
    padded[: len(x)] = x
    gathered = np.asarray(mhu.process_allgather(padded))
    return np.concatenate(
        [gathered[i, : int(lens[i])] for i in range(len(lens))]
    ) if pad else np.zeros(0, dtype=x.dtype)


def agree(ok: bool) -> bool:
    """Collective AND across processes — declines must be unanimous."""
    import jax
    from jax.experimental import multihost_utils as mhu

    if jax.process_count() == 1:
        return ok
    flags = np.asarray(
        mhu.process_allgather(np.array([1 if ok else 0], dtype=np.int64))
    )
    return bool(flags.min() == 1)


def global_max(v: int) -> int:
    import jax
    from jax.experimental import multihost_utils as mhu

    if jax.process_count() == 1:
        return int(v)
    vals = np.asarray(
        mhu.process_allgather(np.array([int(v)], dtype=np.int64))
    )
    return int(vals.max())


def make_sharded(mesh, blocks: dict, total_len: int, dtype) -> object:
    """Assemble a globally-sharded array (1-D or N-D, sharded on axis 0)
    from this process's per-shard blocks. blocks: flat shard id ->
    np.ndarray with total_len // n leading rows (trailing dims equal on
    every block). Every shard id this process owns must be present."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = int(np.prod(list(mesh.shape.values())))
    block = total_len // n
    mine = local_shard_ids(mesh)
    trailing = blocks[mine[0]].shape[1:] if mine else ()
    sharding = NamedSharding(mesh, P(tuple(mesh.shape.keys())[0]))
    devs = list(mesh.devices.flat)
    arrays = []
    for i in mine:
        b = blocks[i]
        assert b.shape == (block,) + trailing, (b.shape, block, trailing)
        arrays.append(jax.device_put(b.astype(dtype, copy=False), devs[i]))
    return jax.make_array_from_single_device_arrays(
        (total_len,) + trailing, sharding, arrays
    )

"""jax `shard_map` compatibility shim.

Newer jax exports the stable `jax.shard_map` (replication checking under
the `check_vma` keyword); 0.4.x ships the same transform as
`jax.experimental.shard_map.shard_map` with the older `check_rep` keyword.
Mesh call sites import `shard_map` from here and always pass `check_vma` —
without this shim every mesh program on a 0.4.x image died at import time
and silently fell back to the host path (observed: the whole spmd suite
red on the CI image while results stayed "correct" via fallback).
"""

from __future__ import annotations


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy

    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)

"""SPMD aggregation stage: Partial -> exchange -> Final as ONE mesh program.

The reference executes a distributed aggregation as independent
per-partition partial tasks, a materialized hash shuffle, and final tasks
(rust/scheduler/src/planner.rs:149-171 + the ShuffleWriter/Reader pair).
The TPU-native restructuring (SURVEY §2.8, §7 step 5): partitions map to
shards of a jax.sharding.Mesh, the partial phase is the fused-stage program
on each shard, and the exchange is lax.psum over the mesh's ICI — no
materialize-then-fetch, one XLA program for the whole
Partial->shuffle->Final pipeline.

SpmdAggregateExec is emitted by the DistributedPlanner (config
`ballista.tpu.spmd_stages` = true) in place of the
HashAggregate(Final) <- Repartition(hash) <- HashAggregate(Partial)
subtree, collapsing what would be two stages + a shuffle into one stage.
The per-shard program is driven by FusedAggregateStage's compiled
filter/value functions — the same expression compiler the single-chip
backend uses — not a hand-written kernel.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
    collect_all,
)


class SpmdAggregateExec(ExecutionPlan):
    """Executes Final(Repartition(Partial(input))) as one mesh program.

    Falls back to executing the wrapped subplan on the host when the mesh
    can't be built or the stage doesn't lower (high cardinality, exprs the
    device path declines, non-TPU backend) — the wrapped subplan is the
    untouched original subtree, so behavior is identical minus the fusion.
    """

    def __init__(self, subplan: ExecutionPlan) -> None:
        # subplan = HashAggregateExec(FINAL) over RepartitionExec over
        # HashAggregateExec(PARTIAL); kept whole for serde + fallback
        from ballista_tpu.physical.aggregate import AggregateMode, HashAggregateExec
        from ballista_tpu.physical.repartition import RepartitionExec

        assert isinstance(subplan, HashAggregateExec)
        assert subplan.mode == AggregateMode.FINAL
        self.subplan = subplan
        repart = subplan.input
        assert isinstance(repart, RepartitionExec)
        partial = repart.input
        assert isinstance(partial, HashAggregateExec)
        assert partial.mode == AggregateMode.PARTIAL
        self.final = subplan
        self.partial = partial
        self._stage = None
        self._mesh = None
        self._program = None
        self._program_key = None
        # introspection: "mesh" or "host" after each execute (the dryrun and
        # tests assert the mesh path actually ran, since the host fallback
        # produces identical results)
        self.last_path: Optional[str] = None

    # ------------------------------------------------------------------
    def schema(self) -> pa.Schema:
        return self.subplan.schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> List[ExecutionPlan]:
        # the subplan is serialized/traversed whole; no planner recursion
        # into it (it must stay one stage)
        return []

    def with_children(self, children: List[ExecutionPlan]) -> "SpmdAggregateExec":
        assert not children
        return self

    def fmt(self) -> str:
        return "SpmdAggregateExec: partial+exchange+final as one mesh program"

    # ------------------------------------------------------------------
    def _build_mesh(self, ctx: TaskContext):
        from ballista_tpu.parallel.mesh import build_mesh

        import jax

        if self._mesh is not None:
            return self._mesh
        shape = ctx.config.mesh_shape() or None
        try:
            self._mesh = build_mesh(shape)
        except ValueError:
            # fewer devices than the configured mesh: use all local devices
            self._mesh = build_mesh({"data": len(jax.devices())})
        return self._mesh

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        assert partition == 0
        if ctx.backend != "tpu":
            yield from self.subplan.execute(partition, ctx)
            return
        try:
            out = self._execute_mesh(ctx)
            self.last_path = "mesh"
        except Exception:  # device decline of any kind -> host subplan
            from ballista_tpu.ops.runtime import UnsupportedOnDevice
            import logging
            import sys

            exc = sys.exc_info()[1]
            if not isinstance(exc, UnsupportedOnDevice):
                logging.getLogger("ballista.spmd").warning(
                    "mesh aggregation failed, host fallback: %s", exc
                )
            self.last_path = "host"
            yield from self.subplan.execute(partition, ctx)
            return
        yield from batch_table(out, ctx.batch_size)

    # ------------------------------------------------------------------
    def _execute_mesh(self, ctx: TaskContext) -> pa.Table:
        import jax
        import jax.numpy as jnp

        from ballista_tpu.ops.runtime import UnsupportedOnDevice, bucket_rows, pad_to
        from ballista_tpu.ops.stage import FusedAggregateStage, MAX_GROUPS

        if self._stage is None:
            self._stage = FusedAggregateStage(self.partial)
        stage = self._stage
        mesh = self._build_mesh(ctx)
        n_dev = int(np.prod(list(mesh.shape.values())))

        # host: read every input partition, compute GLOBAL group codes so a
        # group id means the same thing on every shard
        parts = stage.scan.output_partitioning().partition_count()
        batches = []
        for p in range(parts):
            batches.extend(b for b in stage._scan_batches(p, ctx) if b.num_rows)
        if not batches:
            return self.schema().empty_table()
        table = pa.Table.from_batches(batches).combine_chunks()
        batch = table.to_batches(max_chunksize=table.num_rows)[0]
        codes, key_values, n_groups = stage._group_codes(batch)
        if n_groups == 0:
            return self.schema().empty_table()
        if n_groups > MAX_GROUPS:
            raise UnsupportedOnDevice("mesh path uses unrolled reductions")
        npcols = stage._lower_columns(batch)
        stage._check_int_ranges(npcols, batch.num_rows)

        # shard rows across the mesh: equal-size padded shards
        n = batch.num_rows
        shard = bucket_rows(-(-n // n_dev))
        total = shard * n_dev
        cols: Dict[int, object] = {}
        for idx, npcol in npcols.items():
            fill = False if npcol.dtype == np.bool_ else 0
            cols[idx] = jnp.asarray(pad_to(npcol, total, fill))
        codes_pad = jnp.asarray(pad_to(codes.astype(np.int32), total, 0))
        row_valid = np.zeros(total, dtype=np.bool_)
        row_valid[:n] = True
        row_valid = jnp.asarray(row_valid)
        aux = [jnp.asarray(a) for a in stage.compiler.build_aux()]

        seg = int(bucket_rows(n_groups, 16)) + 1  # +1 dump slot
        program = self._get_program(mesh, stage, seg, set(cols.keys()), len(aux))
        stacked = np.asarray(program(cols, aux, codes_pad, row_valid))

        rows = stage._decode_stacked(stacked)
        counts = rows[0][:n_groups]
        outputs = [r[:n_groups] for r in rows[1:]]
        partial_table = stage._assemble_partial(outputs, counts, key_values, n_groups)
        return self.final._final(partial_table)

    def _get_program(self, mesh, stage, seg: int, col_keys, n_aux: int):
        """shard_map(per-shard fused partials) + psum, jitted once per
        (segment bucket, column set); the mesh is built once per exec."""
        key = (seg, tuple(sorted(col_keys)), n_aux)
        if self._program_key == key:
            return self._program

        import jax
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from ballista_tpu.ops.stage import jnp_unpack_i32

        core = stage._unrolled_core()
        int_rows = stage._int_rows
        folds = stage._folds
        collectives = {"sum": jax.lax.psum, "min": jax.lax.pmin,
                       "max": jax.lax.pmax}

        def per_shard(cols, aux, codes, row_valid):
            stacked = core(seg, cols, aux, codes, row_valid)
            # the exchange: merge shard partials over ICI instead of a
            # materialized hash shuffle. Rows reduce with their own
            # collective (sum/min/max); int32 rows are hi/lo packed (see
            # stage.py::_stack_rows), so decode -> exact int32 collective
            # -> re-encode.
            outs = []
            p = 0
            for is_int, fold in zip(int_rows, folds):
                red = collectives[fold]
                if is_int:
                    v = red(jnp_unpack_i32(stacked[p], stacked[p + 1]), "data")
                    outs.append((v >> 16).astype(jnp.float32))
                    outs.append((v & 0xFFFF).astype(jnp.float32))
                    p += 2
                else:
                    outs.append(red(stacked[p], "data"))
                    p += 1
            return jnp.stack(outs)

        fn = shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(
                {k: P("data") for k in col_keys},
                [P() for _ in range(n_aux)],
                P("data"),
                P("data"),
            ),
            out_specs=P(),
            check_vma=False,
        )
        self._program = jax.jit(fn)
        self._program_key = key
        return self._program

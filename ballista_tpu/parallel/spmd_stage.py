"""SPMD aggregation stage: Partial -> exchange -> Final as ONE mesh program.

The reference executes a distributed aggregation as independent
per-partition partial tasks, a materialized hash shuffle, and final tasks
(rust/scheduler/src/planner.rs:149-171 + the ShuffleWriter/Reader pair).
The TPU-native restructuring (SURVEY §2.8, §7 step 5): partitions map to
shards of a jax.sharding.Mesh, the partial phase is the fused-stage program
on each shard, and the exchange is lax.psum over the mesh's ICI — no
materialize-then-fetch, one XLA program for the whole
Partial->shuffle->Final pipeline.

Distributed structure (nothing is globally gathered in row space):

  1. per-shard reads — input partition p belongs to mesh shard
     p % n_devices; each shard scans, encodes, and group-codes only its
     own rows (on a multi-host mesh each host would run this for the
     shards it owns — the per-shard decomposition is the multi-host story).
  2. two-pass global key coding — shards exchange only their DISTINCT key
     rows; the union is dense-ranked once (host work proportional to
     distinct-key count, not row count) and each shard remaps its local
     codes through its slice of the ranking. No central row dictionary.
  3. one mesh program — per-shard fused partials, then the exchange:
       G <= 1024: unrolled per-group reductions + psum/pmin/pmax.
       G  > 1024: per-shard sorted chunked-segment tiles (ops/layout.py)
       -> per-chunk partials -> in-program segment fold to dense [G]
       (owners are sorted, V is small) -> psum/pmin/pmax over the mesh.
     Either way ONE compiled program and ONE device->host readback.

SpmdAggregateExec is emitted by the DistributedPlanner (config
`ballista.tpu.spmd_stages` = true) in place of the
HashAggregate(Final) <- Repartition(hash) <- HashAggregate(Partial)
subtree, collapsing what would be two stages + a shuffle into one stage.
The per-shard program is driven by FusedAggregateStage's compiled
filter/value functions — the same expression compiler the single-chip
backend uses — not a hand-written kernel.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
    collect_all,
)

def _rank_rows(columns):
    """Dense-rank the rows of a small key table (the union of per-shard
    distinct keys). Returns (rank per input row [int32], per-column unique
    key arrays in rank order, n_groups). Work is O(K log K) in the number
    of distinct-key candidates, never in the number of data rows."""
    import pyarrow.compute as pc

    from ballista_tpu.ops.stage import dense_rank

    if not columns:
        return np.zeros(0, dtype=np.int32), [], 1
    encoded = []
    for arr in columns:
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        d = arr if isinstance(arr, pa.DictionaryArray) else pc.dictionary_encode(arr)
        encoded.append(
            (d.indices.to_numpy(zero_copy_only=False).astype(np.int64), d)
        )
    inv, first_idx, n_uniq = dense_rank(
        [(codes_i, len(d.dictionary)) for codes_i, d in encoded]
    )
    take = pa.array(first_idx.astype(np.int64))
    uniq_rows = []
    for arr, (_c, d) in zip(columns, encoded):
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if isinstance(arr, pa.DictionaryArray):
            uniq_rows.append(d.dictionary.take(d.indices.take(take)))
        else:
            uniq_rows.append(arr.take(take))
    return inv.astype(np.int32), uniq_rows, n_uniq


def _key_as_i64(a) -> np.ndarray:
    """Key column -> int64 numpy for the multi-host union allgather."""
    from ballista_tpu.ops.runtime import UnsupportedOnDevice

    if isinstance(a, pa.ChunkedArray):
        a = a.combine_chunks()
    if not isinstance(a, pa.Array):
        a = pa.array(a)
    t = a.type
    if pa.types.is_date32(t):
        a = a.cast(pa.int32())
    elif pa.types.is_boolean(t):
        a = a.cast(pa.int8())
    elif not pa.types.is_integer(t):
        raise UnsupportedOnDevice(
            "multi-host key union requires integer-like keys"
        )
    return a.cast(pa.int64()).to_numpy(zero_copy_only=False).astype(np.int64)


def _rebuild_key_arrays(stage, gathered: List[np.ndarray],
                        first_idx: np.ndarray, n_keys: int) -> List[pa.Array]:
    """Group key values in rank order, cast from the int64 wire form back
    to each key expression's Arrow type."""
    gkv = []
    for j in range(n_keys):
        target = stage.group_exprs[j][0].data_type(stage.scan_schema)
        vals = gathered[j][first_idx]
        arr = pa.array(vals)
        if arr.type != target:
            if pa.types.is_date32(target):
                arr = arr.cast(pa.int32()).cast(target)
            elif pa.types.is_boolean(target):
                arr = arr.cast(pa.int8()).cast(target)
            else:
                arr = arr.cast(target)
        gkv.append(arr)
    return gkv


def _np_dtype_for(dtype: pa.DataType) -> np.dtype:
    """The numpy dtype column_to_numpy produces for an Arrow type —
    derived by lowering a ZERO-LENGTH column through column_to_numpy
    itself, so there is one source of truth: an empty host's blocks always
    dtype-match its data-bearing peers' (one shared jit program)."""
    from ballista_tpu.ops.runtime import ColumnDictionary, column_to_numpy

    d = (
        ColumnDictionary()
        if pa.types.is_string(dtype) or pa.types.is_large_string(dtype)
        else None
    )
    return column_to_numpy(pa.array([], type=dtype), dtype, d).dtype


class SpmdAggregateExec(ExecutionPlan):
    """Executes Final(Repartition(Partial(input))) as one mesh program.

    Falls back to executing the wrapped subplan on the host when the mesh
    can't be built or the stage doesn't lower (high cardinality, exprs the
    device path declines, non-TPU backend) — the wrapped subplan is the
    untouched original subtree, so behavior is identical minus the fusion.
    """

    def __init__(self, subplan: ExecutionPlan) -> None:
        # subplan = HashAggregateExec(FINAL) over RepartitionExec over
        # HashAggregateExec(PARTIAL); kept whole for serde + fallback
        from ballista_tpu.physical.aggregate import AggregateMode, HashAggregateExec
        from ballista_tpu.physical.repartition import RepartitionExec

        assert isinstance(subplan, HashAggregateExec)
        assert subplan.mode == AggregateMode.FINAL
        self.subplan = subplan
        repart = subplan.input
        assert isinstance(repart, RepartitionExec)
        partial = repart.input
        assert isinstance(partial, HashAggregateExec)
        assert partial.mode == AggregateMode.PARTIAL
        self.final = subplan
        self.partial = partial
        self._stage = None
        self._mesh = None
        self._program = None
        self._program_key = None
        # introspection: "mesh" or "host" after each execute (the dryrun and
        # tests assert the mesh path actually ran, since the host fallback
        # produces identical results)
        self.last_path: Optional[str] = None

    # ------------------------------------------------------------------
    def schema(self) -> pa.Schema:
        return self.subplan.schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> List[ExecutionPlan]:
        # the subplan is serialized/traversed whole; no planner recursion
        # into it (it must stay one stage)
        return []

    def with_children(self, children: List[ExecutionPlan]) -> "SpmdAggregateExec":
        assert not children
        return self

    def fmt(self) -> str:
        return "SpmdAggregateExec: partial+exchange+final as one mesh program"

    # ------------------------------------------------------------------
    def _build_mesh(self, ctx: TaskContext):
        from ballista_tpu.parallel.mesh import build_mesh

        import jax

        if self._mesh is not None:
            return self._mesh
        shape = ctx.config.mesh_shape() or None
        try:
            self._mesh = build_mesh(shape)
        except ValueError:
            # fewer devices than the configured mesh: use all local devices
            self._mesh = build_mesh({"data": len(jax.devices())})
        return self._mesh

    def fingerprint(self) -> str:
        """Stable short id of the fused subtree, for fallback diagnostics."""
        import hashlib

        def walk(n):
            yield n.fmt()
            for c in n.children():
                yield from walk(c)

        text = "\n".join(walk(self.subplan))
        return hashlib.sha1(text.encode()).hexdigest()[:12]

    _warned_fingerprints: set = set()

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        from ballista_tpu.utils import tracing

        assert partition == 0
        if ctx.backend != "tpu":
            yield from self._execute_host(ctx)
            return
        # mesh aggregate cost feeds the same store the single-chip ladder
        # consults (ISSUE 10), keyed on this stage's identity; the decision
        # lands in the routing accumulator either way
        from ballista_tpu.ops import costmodel

        costmodel.configure(ctx.config)
        op = "mesh.agg|" + self.fingerprint()[:12]
        host_op = "mesh.agg.host|" + self.fingerprint()[:12]
        # admission rides the cost model (ISSUE 16 satellite): with BOTH
        # paths warm for this stage shape and the mesh predicted slower
        # (compile + collective overhead on small inputs), decline to the
        # host up front instead of paying the launch to learn it again.
        # Cold on either side → admit, exactly the pre-model ladder; the
        # host run below stays predictive, so a stage that outgrew its
        # host rate grossly mispredicts, re-tiers, and earns the mesh
        # back on its next admission check.
        mesh_pred = costmodel.predict(op, 1.0)
        host_pred = costmodel.predict(host_op, 1.0, engine="host")
        if (
            mesh_pred is not None
            and host_pred is not None
            and mesh_pred > host_pred
        ):
            from ballista_tpu.ops.runtime import record_routing

            record_routing("host", "mesh.agg", mesh_pred, None)
            tracing.incr("spmd.host_declined")
            self.last_path = "host"
            with costmodel.timed(host_op, engine="host"):
                out = collect_all(self.subplan, ctx)
            yield from batch_table(out, ctx.batch_size)
            return
        try:
            with costmodel.timed(op, routing_op="mesh.agg"):
                out = self._execute_mesh(ctx)
            self.last_path = "mesh"
            tracing.incr("spmd.mesh")
        except Exception:  # device decline of any kind -> host subplan
            from ballista_tpu.ops.runtime import UnsupportedOnDevice
            import logging
            import sys

            exc = sys.exc_info()[1]
            tracing.incr("spmd.host_fallback")
            if not isinstance(exc, UnsupportedOnDevice):
                tracing.incr("spmd.host_fallback_error")
                fp = self.fingerprint()
                if fp not in self._warned_fingerprints:
                    self._warned_fingerprints.add(fp)
                    logging.getLogger("ballista.spmd").warning(
                        "mesh aggregation failed (stage %s), host fallback: %s",
                        fp, exc,
                    )
            from ballista_tpu.ops.runtime import record_routing

            record_routing("host", "mesh.agg")
            self.last_path = "host"
            # the forced fallback still warms the host-side rate the
            # admission check above compares against (predictive=False: a
            # run the mesh error forced must not re-tier on surprise)
            with costmodel.timed(host_op, engine="host", predictive=False):
                out = collect_all(self.subplan, ctx)
            yield from batch_table(out, ctx.batch_size)
            return
        yield from batch_table(out, ctx.batch_size)

    def _execute_host(self, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        """Run the untouched subtree on the host. The Final aggregate above
        the hash Repartition spreads groups over ALL its output partitions —
        this single-partition stage must drain every one of them."""
        yield from batch_table(collect_all(self.subplan, ctx), ctx.batch_size)

    # ------------------------------------------------------------------
    def _execute_mesh(self, ctx: TaskContext) -> pa.Table:
        import jax
        import jax.numpy as jnp

        from ballista_tpu.ops.runtime import UnsupportedOnDevice, bucket_rows
        from ballista_tpu.ops.stage import FusedAggregateStage, MAX_GROUPS

        from ballista_tpu.physical.aggregate import needs_exact_float_minmax

        if needs_exact_float_minmax(self.partial):
            # q2-shape decorrelated MIN(float): the f32 mesh pmin would be
            # equality-joined against exact f64 values — host subplan instead
            raise UnsupportedOnDevice("exact float min/max required")
        if self._stage is None:
            # float_bits=False: the mesh exchange folds rows independently
            # (per-row psum/pmin/pmax collectives), which cannot express the
            # lexicographic hi/lo f64 key-plane pair — this path keeps its
            # documented f32 float min/max semantics (the exact-float decline
            # above already routes q2-shape queries to the host subplan)
            self._stage = FusedAggregateStage(self.partial, float_bits=False)
        stage = self._stage
        mesh = self._build_mesh(ctx)
        n_dev = int(np.prod(list(mesh.shape.values())))
        if jax.process_count() > 1:
            # pod path: per-host shard reads, collective key exchange, the
            # SAME shard_map program over the global mesh
            return self._execute_mesh_multihost(ctx, stage, mesh, n_dev)

        # ---- 1. per-shard reads: each shard scans and group-codes ONLY its
        # own rows. Batches go to the least-loaded shard (batches are finer
        # than partitions, so skewed or few partitions still balance — shard
        # blocks are padded to the largest shard, so balance is wall-time)
        parts = stage.scan.output_partitioning().partition_count()
        shard_batches: List[List[pa.RecordBatch]] = [[] for _ in range(n_dev)]
        shard_rows = [0] * n_dev
        for p in range(parts):
            for b in stage._scan_batches(p, ctx):
                if not b.num_rows:
                    continue
                si = shard_rows.index(min(shard_rows))
                shard_batches[si].append(b)
                shard_rows[si] += b.num_rows
        shards: List[Optional[dict]] = []
        for bs in shard_batches:
            if not bs:
                shards.append(None)  # empty shard: identity contribution
                continue
            t = pa.Table.from_batches(bs).combine_chunks()
            batch = t.to_batches(max_chunksize=t.num_rows)[0]
            codes, kv, g = stage._group_codes(batch)
            shards.append({"batch": batch, "codes": codes, "kv": kv, "g": g})
        live = [d for d in shards if d is not None]
        if not live:
            return self.schema().empty_table()

        # ---- 2. global key coding from per-shard DISTINCTS only
        n_keys = len(stage.group_exprs)
        if n_keys == 0:
            n_groups, gkv = 1, []
            for d in live:
                d["gcodes"] = d["codes"]
        else:
            union_cols = []
            for j in range(n_keys):
                parts_j = []
                for d in live:
                    a = d["kv"][j]
                    parts_j.append(
                        a.combine_chunks() if isinstance(a, pa.ChunkedArray) else a
                    )
                union_cols.append(
                    pa.chunked_array(parts_j).combine_chunks()
                    if len(parts_j) > 1 else parts_j[0]
                )
            inv, gkv, n_groups = _rank_rows(union_cols)
            off = 0
            for d in live:
                mapping = inv[off:off + d["g"]]
                off += d["g"]
                d["gcodes"] = mapping[d["codes"]]
        if n_groups == 0:
            return self.schema().empty_table()

        # ---- 3. lower columns per shard; global int32-sum overflow check
        # (psum adds across shards, so the bound spans ALL rows)
        for d in live:
            d["npcols"] = stage._lower_columns(d["batch"])
        total_n = sum(d["batch"].num_rows for d in live)
        stage._check_int_ranges([d["npcols"] for d in live], total_n)

        aux = [jnp.asarray(a) for a in stage.compiler.build_aux()]
        if n_groups <= MAX_GROUPS:
            counts, outputs = self._run_unrolled_mesh(
                mesh, stage, shards, n_groups, n_dev, aux
            )
        else:
            counts, outputs = self._run_sorted_mesh(
                mesh, stage, shards, n_groups, n_dev, aux
            )
        partial_table = stage._assemble_partial(outputs, counts, gkv, n_groups)
        return self.final._final(partial_table)

    def _execute_mesh_multihost(self, ctx, stage, mesh, n_dev) -> pa.Table:
        """Multi-process mesh execution (jax.distributed): this process
        reads ONLY the partitions its local shards own (multihost.py's
        host-boundary contract), every host ranks the allgathered
        distinct-key union identically, local shard blocks assemble into
        globally-sharded arrays, and the SAME jitted shard_map program the
        single-host path uses runs over the pod mesh. Every decline is
        collective (multihost.agree): a unilateral fallback would leave
        the other hosts blocked inside the program's collectives.

        Scope (collectively enforced): integer/date/bool group keys (the
        key union rides an int64 allgather) and no string columns anywhere
        in the stage (per-host dictionary growth would diverge the aux
        shapes). Both the unrolled (G <= MAX_GROUPS) and the sorted
        chunked-segment (any G) programs run at pod scale. The reference
        reaches multi-node scale with one executor process per node over
        NCCL/MPI; this is the mesh-native equivalent."""
        import jax
        import jax.numpy as jnp

        from ballista_tpu.ops.runtime import UnsupportedOnDevice, bucket_rows
        from ballista_tpu.ops.stage import MAX_GROUPS, dense_rank
        from ballista_tpu.parallel import multihost as mh

        # ---- per-host reads: only partitions owned by local shards ----
        parts = stage.scan.output_partitioning().partition_count()
        my_shards = mh.local_shard_ids(mesh)
        shard_batches = {i: [] for i in my_shards}
        shard_rows = {i: 0 for i in my_shards}
        n_keys = len(stage.group_exprs)
        local: Dict[int, dict] = {}
        ok = True
        my_distinct: List[np.ndarray] = [
            np.zeros(0, dtype=np.int64) for _ in range(n_keys)
        ]
        try:
            if any(
                pa.types.is_string(t) or pa.types.is_large_string(t)
                for t in stage.compiler.used_columns.values()
            ):
                raise UnsupportedOnDevice(
                    "multi-host v1: string columns diverge per-host dictionaries"
                )
            for p in mh.owned_partitions(parts, mesh):
                for b in stage._scan_batches(p, ctx):
                    if not b.num_rows:
                        continue
                    # balance batches among THIS host's own shards only
                    si = min(shard_rows, key=shard_rows.get)
                    shard_batches[si].append(b)
                    shard_rows[si] += b.num_rows
            for si, bs in shard_batches.items():
                if not bs:
                    continue
                t = pa.Table.from_batches(bs).combine_chunks()
                batch = t.to_batches(max_chunksize=t.num_rows)[0]
                codes, kv, g = stage._group_codes(batch)
                local[si] = {"batch": batch, "codes": codes, "kv": kv, "g": g}
            # this host's distinct key tuples as parallel int64 columns
            # (shards in local-iteration order; rows stay tuple-aligned)
            cols_j: List[List[np.ndarray]] = [[] for _ in range(n_keys)]
            for d in local.values():
                for j in range(n_keys):
                    cols_j[j].append(_key_as_i64(d["kv"][j]))
            for j in range(n_keys):
                if cols_j[j]:
                    my_distinct[j] = np.concatenate(cols_j[j])
            for d in local.values():
                d["npcols"] = stage._lower_columns(d["batch"])
        except (UnsupportedOnDevice, MemoryError, OSError, pa.ArrowException):
            # the read/lower fence must catch host-side failures too (a
            # missing file is OSError, an OOM during decode MemoryError, a
            # truncated/corrupt parquet ArrowInvalid — which subclasses
            # ValueError, not OSError): the decline has to be COLLECTIVE,
            # or the healthy peers block forever in the allgather below
            # waiting for this host
            ok = False
        if not mh.agree(ok):
            raise UnsupportedOnDevice("multi-host mesh declined collectively")

        my_rows = sum(d["batch"].num_rows for d in local.values())
        all_rows = mh.allgather_rows(np.array([my_rows], dtype=np.int64))
        if int(all_rows.sum()) == 0:
            return self.schema().empty_table()

        # ---- collective key union; identical ranking on every host ----
        if n_keys == 0:
            n_groups, gkv = 1, []
            for d in local.values():
                d["gcodes"] = d["codes"]
        else:
            gathered = [mh.allgather_rows(c) for c in my_distinct]
            encoded = []
            for col in gathered:
                uniq, inv = np.unique(col, return_inverse=True)
                encoded.append((inv.astype(np.int64), len(uniq)))
            inv_all, first_idx, n_groups = dense_rank(encoded)
            # this host's slice of the gathered ranking
            my_count = sum(d["g"] for d in local.values())
            counts = mh.allgather_rows(
                np.array([my_count], dtype=np.int64)
            )
            pos = int(counts[: jax.process_index()].sum())
            for d in local.values():
                mapping = inv_all[pos: pos + d["g"]]
                pos += d["g"]
                d["gcodes"] = mapping[d["codes"]].astype(np.int32)
            gkv = _rebuild_key_arrays(stage, gathered, first_idx, n_keys)

        # ---- int-overflow check over the GLOBAL row count --------------
        ok = True
        try:
            stage._check_int_ranges(
                [d["npcols"] for d in local.values()],
                max(int(all_rows.sum()), 1),
            )
        except UnsupportedOnDevice:
            ok = False
        if not mh.agree(ok):
            raise UnsupportedOnDevice("multi-host int-range decline")

        if n_groups > MAX_GROUPS:
            # n_groups derives from the SAME gathered union on every host,
            # so the path choice needs no extra agreement
            return self._multihost_sorted(
                ctx, stage, mesh, n_dev, local, gkv, n_groups
            )

        # ---- assemble globally-sharded blocks; run the SAME program ----
        local_max = max(
            [d["batch"].num_rows for d in local.values()], default=1
        )
        S = mh.global_max(int(bucket_rows(local_max)))
        col_ids = sorted(stage.compiler.used_columns)
        aux = [jnp.asarray(a) for a in stage.compiler.build_aux()]
        cols: Dict[int, object] = {}
        for idx in col_ids:
            np_dtype = _np_dtype_for(stage.compiler.used_columns[idx])
            blocks = {}
            for si in my_shards:
                big = np.zeros(S, dtype=np_dtype)
                d = local.get(si)
                if d is not None:
                    npcol = d["npcols"][idx].astype(np_dtype, copy=False)
                    big[: len(npcol)] = npcol
                blocks[si] = big
            cols[idx] = mh.make_sharded(mesh, blocks, S * n_dev, np_dtype)
        codes_blocks, valid_blocks = {}, {}
        for si in my_shards:
            cb = np.zeros(S, dtype=np.int32)
            vb = np.zeros(S, dtype=np.bool_)
            d = local.get(si)
            if d is not None:
                n = d["batch"].num_rows
                cb[:n] = d["gcodes"]
                vb[:n] = True
            codes_blocks[si] = cb
            valid_blocks[si] = vb
        codes_g = mh.make_sharded(mesh, codes_blocks, S * n_dev, np.int32)
        valid_g = mh.make_sharded(mesh, valid_blocks, S * n_dev, np.bool_)

        from ballista_tpu.ops.runtime import readback

        seg = int(bucket_rows(n_groups, 16)) + 1
        program = self._get_program(mesh, stage, seg, set(cols.keys()), len(aux))
        stacked = readback(program(cols, aux, codes_g, valid_g))
        rows = stage._decode_stacked(stacked)
        counts_np = rows[0][:n_groups]
        outputs = [r[:n_groups] for r in rows[1:]]
        partial_table = stage._assemble_partial(outputs, counts_np, gkv, n_groups)
        return self.final._final(partial_table)

    def _multihost_sorted(self, ctx, stage, mesh, n_dev, local, gkv,
                          n_groups) -> pa.Table:
        """Pod path for G > MAX_GROUPS: per-shard sorted chunked-segment
        tiles built host-locally, tile widths (L1) and chunk counts (V)
        unified with collective maxima so every shard's [V_pad, L1] blocks
        stack into one globally-sharded array, then the SAME jitted sorted
        shard_map program (segment fold + psum/pmin/pmax) runs over the
        global mesh — the cardinality-independent layout at pod scale."""
        import jax.numpy as jnp

        from ballista_tpu.ops.layout import SortedSegmentLayout
        from ballista_tpu.ops.runtime import UnsupportedOnDevice, bucket_rows
        from ballista_tpu.parallel import multihost as mh

        my_shards = mh.local_shard_ids(mesh)
        # fallible per-host work is fenced with collective agreement BEFORE
        # the next collective (multihost.py's invariant): a unilateral
        # raise here (oversized shard, MemoryError while materializing)
        # would strand the other hosts inside the collectives below
        ok = True
        layouts: Dict[int, SortedSegmentLayout] = {}
        try:
            for si, d in local.items():
                layouts[si] = SortedSegmentLayout(
                    d["gcodes"], n_groups, min_one_chunk=False
                )
        except (UnsupportedOnDevice, MemoryError):
            ok = False
        if not mh.agree(ok):
            raise UnsupportedOnDevice("multi-host sorted layout decline")
        my_L1 = max((l.L1 for l in layouts.values()), default=8)
        L1 = mh.global_max(my_L1)
        my_V = 1
        col_ids = sorted(stage.compiler.used_columns)
        ok = True
        col_blocks: Dict[int, Dict[int, np.ndarray]] = {}
        clen_blocks: Dict[int, np.ndarray] = {}
        owner_blocks: Dict[int, np.ndarray] = {}
        try:
            for si in list(layouts):
                if layouts[si].L1 != L1:
                    layouts[si] = SortedSegmentLayout(
                        local[si]["gcodes"], n_groups, force_L1=L1,
                        min_one_chunk=False,
                    )
            my_V = max((l.V for l in layouts.values()), default=1)
        except (UnsupportedOnDevice, MemoryError):
            ok = False
        if not mh.agree(ok):
            raise UnsupportedOnDevice("multi-host sorted rebuild decline")
        V_pad = mh.global_max(int(bucket_rows(my_V, 8)))
        G_pad = int(bucket_rows(n_groups, 16))
        ok = True
        try:
            for idx in col_ids:
                np_dtype = _np_dtype_for(stage.compiler.used_columns[idx])
                blocks = {}
                for si in my_shards:
                    big = np.zeros((V_pad, L1), dtype=np_dtype)
                    l = layouts.get(si)
                    if l is not None and l.V:
                        big[: l.V] = l.materialize(
                            local[si]["npcols"][idx].astype(
                                np_dtype, copy=False
                            )
                        )
                    blocks[si] = big
                col_blocks[idx] = blocks
            for si in my_shards:
                cb = np.zeros(V_pad, dtype=np.int16)
                # padding chunks carry identity partials (clen=0); G_pad-1
                # keeps each shard's owner slice sorted
                # (indices_are_sorted=True)
                ob = np.full(V_pad, G_pad - 1, dtype=np.int32)
                l = layouts.get(si)
                if l is not None and l.V:
                    cb[: l.V] = l.clen
                    ob[: l.V] = l.owner
                clen_blocks[si] = cb
                owner_blocks[si] = ob
        except (UnsupportedOnDevice, MemoryError):
            ok = False
        if not mh.agree(ok):
            raise UnsupportedOnDevice("multi-host tile materialization decline")

        aux = [jnp.asarray(a) for a in stage.compiler.build_aux()]
        cols: Dict[int, object] = {}
        for idx in col_ids:
            np_dtype = _np_dtype_for(stage.compiler.used_columns[idx])
            cols[idx] = mh.make_sharded(
                mesh, col_blocks.pop(idx), V_pad * n_dev, np_dtype
            )
        clen_g = mh.make_sharded(mesh, clen_blocks, V_pad * n_dev, np.int16)
        owner_g = mh.make_sharded(mesh, owner_blocks, V_pad * n_dev, np.int32)

        from ballista_tpu.ops.runtime import readback

        program = self._get_sorted_program(
            mesh, stage, G_pad, L1, set(cols.keys()), len(aux)
        )
        stacked = readback(program(cols, aux, clen_g, owner_g))
        rows = stage._decode_stacked(stacked)
        counts_np = rows[0][:n_groups]
        outputs = [r[:n_groups] for r in rows[1:]]
        partial_table = stage._assemble_partial(outputs, counts_np, gkv, n_groups)
        return self.final._final(partial_table)

    def _run_unrolled_mesh(self, mesh, stage, shards, n_groups, n_dev, aux):
        """G <= MAX_GROUPS: per-shard unrolled reductions + psum exchange.
        Shard blocks are padded to a common size and laid out contiguously,
        so shard d's rows live exactly in block d of the sharded arrays."""
        import jax.numpy as jnp

        from ballista_tpu.ops.runtime import bucket_rows, readback

        live_ns = [d["batch"].num_rows for d in shards if d is not None]
        S = int(bucket_rows(max(live_ns)))
        total = S * n_dev
        col_ids = sorted(stage.compiler.used_columns)
        cols: Dict[int, object] = {}
        for idx in col_ids:
            ref = next(d["npcols"][idx] for d in shards if d is not None)
            big = np.zeros(total, dtype=ref.dtype)
            for si, d in enumerate(shards):
                if d is not None:
                    npcol = d["npcols"][idx]
                    big[si * S: si * S + len(npcol)] = npcol
            cols[idx] = jnp.asarray(big)
        codes_big = np.zeros(total, dtype=np.int32)
        valid_big = np.zeros(total, dtype=np.bool_)
        for si, d in enumerate(shards):
            if d is None:
                continue
            n = d["batch"].num_rows
            codes_big[si * S: si * S + n] = d["gcodes"]
            valid_big[si * S: si * S + n] = True

        seg = int(bucket_rows(n_groups, 16)) + 1  # +1 dump slot
        program = self._get_program(mesh, stage, seg, set(cols.keys()), len(aux))
        stacked = readback(
            program(cols, aux, jnp.asarray(codes_big), jnp.asarray(valid_big))
        )
        rows = stage._decode_stacked(stacked)
        return rows[0][:n_groups], [r[:n_groups] for r in rows[1:]]

    def _run_sorted_mesh(self, mesh, stage, shards, n_groups, n_dev, aux):
        """G > MAX_GROUPS: per-shard sorted chunked-segment tiles, chunk
        partials folded to dense [G] in-program (sorted segment ops over a
        small V), then psum/pmin/pmax over the mesh. Cardinality-independent:
        device work is O(rows + G), never O(G) serial passes."""
        import jax.numpy as jnp

        from ballista_tpu.ops.layout import SortedSegmentLayout
        from ballista_tpu.ops.runtime import bucket_rows, readback

        layouts: List[Optional[SortedSegmentLayout]] = []
        for d in shards:
            layouts.append(
                None if d is None else SortedSegmentLayout(
                    d["gcodes"], n_groups, min_one_chunk=False
                )
            )
        live_layouts = [l for l in layouts if l is not None]
        L1 = max(l.L1 for l in live_layouts)
        for i, (d, l) in enumerate(zip(shards, layouts)):
            if l is not None and l.L1 != L1:
                layouts[i] = SortedSegmentLayout(
                    d["gcodes"], n_groups, force_L1=L1, min_one_chunk=False
                )
        V_pad = int(bucket_rows(max(l.V for l in layouts if l is not None), 8))
        G_pad = int(bucket_rows(n_groups, 16))

        col_ids = sorted(stage.compiler.used_columns)
        cols: Dict[int, object] = {}
        for idx in col_ids:
            ref = next(d["npcols"][idx] for d in shards if d is not None)
            big = np.zeros((n_dev * V_pad, L1), dtype=ref.dtype)
            for si, (d, l) in enumerate(zip(shards, layouts)):
                if d is not None and l.V:
                    big[si * V_pad: si * V_pad + l.V] = l.materialize(
                        d["npcols"][idx]
                    )
            cols[idx] = jnp.asarray(big)
        clen_big = np.zeros(n_dev * V_pad, dtype=np.int16)
        # padding chunks carry identity partials (clen=0 -> empty mask), so
        # any segment may absorb them — use G_pad-1 to keep each shard's
        # owner slice SORTED (segment ops run indices_are_sorted=True)
        owner_big = np.full(n_dev * V_pad, G_pad - 1, dtype=np.int32)
        for si, l in enumerate(layouts):
            if l is not None and l.V:
                clen_big[si * V_pad: si * V_pad + l.V] = l.clen
                owner_big[si * V_pad: si * V_pad + l.V] = l.owner

        program = self._get_sorted_program(
            mesh, stage, G_pad, L1, set(cols.keys()), len(aux)
        )
        stacked = readback(
            program(cols, aux, jnp.asarray(clen_big), jnp.asarray(owner_big))
        )
        rows = stage._decode_stacked(stacked)
        return rows[0][:n_groups], [r[:n_groups] for r in rows[1:]]

    def _get_program(self, mesh, stage, seg: int, col_keys, n_aux: int):
        """shard_map(per-shard fused partials) + psum, jitted once per
        (segment bucket, column set); the mesh is built once per exec."""
        key = (seg, tuple(sorted(col_keys)), n_aux)
        if self._program_key == key:
            return self._program

        import jax
        import jax.numpy as jnp
        from ballista_tpu.parallel.meshcompat import shard_map
        from jax.sharding import PartitionSpec as P

        from ballista_tpu.ops.stage import jnp_unpack_i32

        core = stage._unrolled_core()
        int_rows = stage._int_rows
        folds = stage._folds
        collectives = {"sum": jax.lax.psum, "min": jax.lax.pmin,
                       "max": jax.lax.pmax}

        def per_shard(cols, aux, codes, row_valid):
            stacked = core(seg, cols, aux, codes, row_valid)
            # the exchange: merge shard partials over ICI instead of a
            # materialized hash shuffle. Rows reduce with their own
            # collective (sum/min/max); int32 rows are hi/lo packed (see
            # stage.py::_stack_rows), so decode -> exact int32 collective
            # -> re-encode.
            outs = []
            p = 0
            for is_int, fold in zip(int_rows, folds):
                red = collectives[fold]
                if is_int:
                    v = red(jnp_unpack_i32(stacked[p], stacked[p + 1]), "data")
                    outs.append((v >> 16).astype(jnp.float32))
                    outs.append((v & 0xFFFF).astype(jnp.float32))
                    p += 2
                else:
                    outs.append(red(stacked[p], "data"))
                    p += 1
            return jnp.stack(outs)

        fn = shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(
                {k: P("data") for k in col_keys},
                [P() for _ in range(n_aux)],
                P("data"),
                P("data"),
            ),
            out_specs=P(),
            check_vma=False,
        )
        self._program = jax.jit(fn)
        self._program_key = key
        return self._program

    def _get_sorted_program(self, mesh, stage, G_pad: int, L1: int, col_keys,
                            n_aux: int):
        """shard_map(per-shard tile partials -> sorted segment fold to dense
        [G_pad]) + psum/pmin/pmax exchange, jitted once per (group bucket,
        column set). Chunk owners are sorted within each shard, and V is
        orders of magnitude smaller than the row count, so the in-program
        segment ops stay cheap even though XLA lowers them to scatter."""
        key = ("sorted", G_pad, L1, tuple(sorted(col_keys)), n_aux)
        if self._program_key == key:
            return self._program

        import jax
        import jax.numpy as jnp
        from ballista_tpu.parallel.meshcompat import shard_map
        from jax.sharding import PartitionSpec as P

        from ballista_tpu.ops.stage import jnp_unpack_i32

        core = stage._sorted_core()
        int_rows = stage._int_rows
        folds = stage._folds
        seg_ops = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
                   "max": jax.ops.segment_max}
        collectives = {"sum": jax.lax.psum, "min": jax.lax.pmin,
                       "max": jax.lax.pmax}

        def per_shard(cols, aux, clen, owner):
            stacked = core(L1, cols, aux, clen)  # [R_packed, V] chunk partials
            outs = []
            p = 0
            for is_int, fold in zip(int_rows, folds):
                if is_int:
                    v = jnp_unpack_i32(stacked[p], stacked[p + 1])
                    p += 2
                else:
                    v = stacked[p]
                    p += 1
                # chunk -> dense group vector (segment identity covers
                # groups this shard never saw), then the mesh exchange
                dense = seg_ops[fold](
                    v, owner, num_segments=G_pad, indices_are_sorted=True
                )
                dense = collectives[fold](dense, "data")
                if is_int:
                    dense = dense.astype(jnp.int32)
                    outs.append((dense >> 16).astype(jnp.float32))
                    outs.append((dense & 0xFFFF).astype(jnp.float32))
                else:
                    outs.append(dense)
            return jnp.stack(outs)

        fn = shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(
                {k: P("data") for k in col_keys},
                [P() for _ in range(n_aux)],
                P("data"),
                P("data"),
            ),
            out_specs=P(),
            check_vma=False,
        )
        self._program = jax.jit(fn)
        self._program_key = key
        return self._program

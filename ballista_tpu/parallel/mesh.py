"""Device mesh construction.

The reference scales by running N independent executor processes, one task
per partition (docs/architecture.md:17-18). The TPU-native equivalent: one
SPMD program over a jax.sharding.Mesh, partitions mapping to mesh shards,
exchanges to XLA collectives over ICI (SURVEY §2.8 mapping table).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def build_mesh(shape: Optional[Dict[str, int]] = None, devices=None):
    """Build a Mesh. shape e.g. {"data": 8}; defaults to all devices on one
    'data' axis (row parallelism — a query engine's natural axis)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if not shape:
        shape = {"data": len(devices)}
    total = int(np.prod(list(shape.values())))
    if total > len(devices):
        raise ValueError(f"mesh {shape} needs {total} devices, have {len(devices)}")
    devs = np.array(devices[:total]).reshape(tuple(shape.values()))
    return Mesh(devs, tuple(shape.keys()))

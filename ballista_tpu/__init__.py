"""ballista_tpu: a TPU-native distributed query framework.

A from-scratch re-design of the capability surface of ballista-compute/ballista
(distributed SQL / DataFrame engine on Arrow) for TPU hardware:

- Arrow (pyarrow / Arrow C++) is the host memory substrate and wire format,
  playing the role arrow-rs plays for the reference.
- The query-engine layer (the role DataFusion plays for the reference:
  logical plans, SQL, optimizer, physical operators) is built here, with two
  interchangeable kernel backends: a host Arrow backend (correctness oracle,
  default) and a JAX/XLA backend that lowers operators onto TPU.
- The distributed layer mirrors the reference's split (scheduler control plane
  over gRPC + executor data plane over Arrow Flight, reference
  rust/scheduler/src/lib.rs, rust/executor/src/flight_service.rs) but
  restructures *execution* around XLA's SPMD model: a query stage can compile
  to ONE pjit program over a jax.sharding.Mesh, with repartition exchanges
  expressed as in-program all_to_all collectives over ICI instead of
  materialize-then-fetch.
"""

BALLISTA_TPU_VERSION = "0.1.0"


def print_version() -> None:
    # Reference: rust/core/src/lib.rs:26-31
    print(f"Ballista-TPU version: {BALLISTA_TPU_VERSION}")


from ballista_tpu.errors import BallistaError  # noqa: E402,F401

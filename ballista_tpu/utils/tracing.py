"""Lightweight tracing spans + optional XLA profiler hook.

The reference only has coarse Instant-based timings around planning and
per-partition execution (SURVEY §5); this gives named nested spans with a
queryable log, plus jax.profiler integration for device traces.

    with span("physical_planning"):
        ...
    print(report())

Env BALLISTA_TRACE_DIR enables jax.profiler.trace into that directory for
spans marked device=True (view in TensorBoard / xprof).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple
from ballista_tpu.utils.locks import make_lock

_local = threading.local()
_all_spans: List[Tuple[str, float, int]] = []  # (path, seconds, depth); guarded-by: _mu
_counters: Dict[str, int] = {}  # guarded-by: _mu
_mu = make_lock("utils.tracing._mu")


def _stack() -> List[str]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


@contextlib.contextmanager
def span(name: str, device: bool = False) -> Iterator[None]:
    stack = _stack()
    stack.append(name)
    path = "/".join(stack)
    trace_dir = os.environ.get("BALLISTA_TRACE_DIR")
    ctx = contextlib.nullcontext()
    if device and trace_dir:
        import jax

        ctx = jax.profiler.trace(trace_dir)
    t0 = time.perf_counter()
    try:
        with ctx:
            yield
    finally:
        dt = time.perf_counter() - t0
        with _mu:
            _all_spans.append((path, dt, len(stack) - 1))
        stack.pop()


def report(reset: bool = False) -> str:
    with _mu:
        lines = [
            f"{'  ' * depth}{path.split('/')[-1]}: {dt * 1000:.2f} ms"
            for path, dt, depth in _all_spans
        ]
        if reset:
            _all_spans.clear()
    return "\n".join(lines)


def spans() -> List[Tuple[str, float, int]]:
    with _mu:
        return list(_all_spans)


def incr(name: str, by: int = 1) -> None:
    """Monotonic named counter (e.g. spmd.mesh vs spmd.host_fallback, so a
    permanently-broken mesh path is visible in ops, not just test asserts)."""
    with _mu:
        _counters[name] = _counters.get(name, 0) + by


def counters() -> Dict[str, int]:
    with _mu:
        return dict(_counters)


def reset() -> None:
    with _mu:
        _all_spans.clear()
        _counters.clear()

"""Deterministic fault-injection harness.

At "heavy traffic from millions of users" scale, transient executor death
and flaky fetches are the steady state — the recovery machinery
(scheduler/state.py retries + lineage recompute, rpc backoff) must be
exercisable in CI without wall-clock or RNG flake. Every injection point is

- **registered**: a site name from SITES, checked at call time (and by the
  ballista-lint failure-discipline rule: no ad-hoc `random` raises);
- **site-addressable**: enabled per-site via ``ballista.chaos.sites``;
- **deterministic**: the verdict for (seed, site, key) is a pure function —
  sha256 of the triple against ``ballista.chaos.rate`` — so a chaos run is
  reproducible regardless of thread interleaving, and retried attempts
  rotate the key (attempt number is part of it) to draw a fresh verdict.

Wired through the existing seams (TaskContext/config plumbing), never by
monkeypatching: chaos tests run whole SQL jobs under injected faults and
assert results are bit-identical to the fault-free run.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Optional

from ballista_tpu.errors import RpcError

log = logging.getLogger("ballista.chaos")

# The registered injection sites. Adding a site means adding it HERE first;
# call sites naming anything else raise (and fail ballista-lint).
SITES = (
    "flight.fetch",          # shuffle piece fetch (distributed/stages.py)
    "rpc.call",              # scheduler gRPC client call (scheduler/rpc.py)
    "task.execute",          # task execution on the executor (execution_loop.py)
    "kv.put",                # scheduler KV write (scheduler/state.py)
    "executor.death",        # executor hard-death (execution_loop.py run loop)
    "scheduler.plan_write",  # staged planning write (scheduler/state.py
                             # JobPlanBatch) — aborts the whole atomic plan
                             # publish; planning retries with a rotated key
    "scheduler.crash",       # scheduler hard-death mid-PollWork
                             # (scheduler/server.py) — keyed on the accepted-
                             # status sequence so the crash lands mid-job
    "cache.put",             # result-cache publish (scheduler/state.py) —
                             # tears the cache write of a completed job; the
                             # job still completes (the cache is best-effort)
                             # and later identical queries just miss
    "scheduler.admit",       # admission decision (scheduler/state.py
                             # assignment) — aborts the PollWork handing a
                             # task out BEFORE the Running flip; the executor
                             # retries its poll and the next admission draws
                             # a fresh verdict (rotated sequence key)
    "scheduler.push",        # push-dispatch delivery (scheduler/server.py
                             # pump) — the assignment is ALREADY written when
                             # the delivery is torn, and the subscriber's
                             # stream is killed with it: exactly a stream
                             # drop after the Running flip. The executor
                             # falls back to polling + re-subscribes; the
                             # undelivered task requeues through the
                             # orphaned-assignment grace reconciliation.
    "aot.load",              # AOT program-cache disk load (ops/aotcache.py)
                             # — a torn load is recorded with a reason and
                             # falls back to a fresh trace/compile, like a
                             # corrupted or version-mismatched artifact
    "scheduler.batch",       # shared-scan batch formation (ISSUE 13,
                             # scheduler/state.py form_shared_batch): tears
                             # the grouping BEFORE any sibling's Running
                             # flip is written, so the primary dispatches
                             # SOLO — a degraded (unbatched) dispatch, never
                             # a torn one. Results are bit-identical by
                             # construction; keyed on a generation-rotated
                             # per-process sequence so a restarted scheduler
                             # draws fresh verdicts.
    "shuffle.store",         # shared-shuffle-storage tier (ISSUE 15,
                             # distributed/stages.py). Two seams, both keyed
                             # on plan coordinates + attempt: a WRITE verdict
                             # tears the atomic publish of a map task's piece
                             # set (the task fails and retries — a retried
                             # attempt draws fresh), and a READ verdict makes
                             # a published piece unreadable from storage for
                             # that consuming attempt — the reader degrades
                             # down the fallback ladder (Flight peer fetch,
                             # then fetch_failed -> lineage recompute),
                             # bit-identical by construction.
    "fleet.scale",           # autoscaler decision (ISSUE 15,
                             # executor/runtime.py): a torn verdict skips
                             # that evaluation's scale action entirely — the
                             # fleet stays at its current size and the next
                             # evaluation draws fresh (sequence-keyed). Never
                             # tears a drain mid-way: the decision aborts
                             # BEFORE any executor is touched.
    "exchange.evict",        # HBM-resident exchange registry (ISSUE 16,
                             # distributed/stages.py). A verdict at CONSUME
                             # time — keyed on plan coordinates + the
                             # consuming attempt, like flight.fetch — evicts
                             # the produced-but-not-yet-consumed registry
                             # entry, rehearsing "residency lost between
                             # produce and consume": the reader silently
                             # falls through to the authoritative piece
                             # (storage -> Flight peer -> lineage ladder),
                             # bit-identical by construction and with ZERO
                             # task retries (nothing failed, only a cache
                             # went cold).
    "cache.advance",         # result-cache advancement publish (ISSUE 19,
                             # scheduler/state.py result_cache_put_advanced).
                             # Fires BEFORE any KV write, keyed on the
                             # advanced entry's result_key: a torn publish
                             # declines the advancement — the user job falls
                             # back to a FULL recompute through the ordinary
                             # planning path, so results stay bit-identical
                             # by construction (the fold is an accelerator,
                             # never the only correct path).
    "scheduler.lease",       # ownership-lease heartbeat renewal (ISSUE 20,
                             # scheduler/server.py housekeeping): a torn
                             # renewal round skips renewing this replica's
                             # job leases, rehearsing a stalled heartbeat —
                             # the leases may expire and a peer may adopt
                             # the jobs mid-flight. Safe BY FENCING: the
                             # deposed owner's later writes carry the stale
                             # lease value and are rejected by the CAS in
                             # put_all, so a spurious expiry costs at most
                             # an ownership migration, never corruption.
                             # Keyed on a generation-rotated per-process
                             # renewal-round sequence (g{gen}/renew{n}).
    "kv.lease",              # lease write/renew KV op (ISSUE 20,
                             # scheduler/state.py lease mint + renewal
                             # seam): the op itself fails as if the store
                             # dropped the request — a torn MINT aborts the
                             # planning commit (retried like kv.put), a
                             # torn RENEWAL is indistinguishable from
                             # scheduler.lease's stalled round. Keyed like
                             # kv.put on a generation-rotated per-process
                             # op sequence.
    "task.slow",             # deterministic straggler injection (ISSUE 11,
                             # execution_loop.py): a task whose (stage,
                             # partition, attempt) coordinate draws a slow
                             # verdict sleeps ballista.chaos.slow_ms before
                             # executing — the seeded tail the speculation
                             # subsystem must beat. Non-raising: the task
                             # still completes correctly, just late, so
                             # results stay bit-identical by construction.
)

_DENOM = float(1 << 64)


class ChaosInjected(RpcError):
    """Synthetic fault raised by a registered injection site. Subclasses
    RpcError so transport-shaped seams treat it exactly like the real
    failure they are rehearsing."""

    def __init__(self, site: str, key: str) -> None:
        super().__init__(f"chaos[{site}] injected fault (key={key})")
        self.site = site
        self.key = key


class ChaosInjector:
    """Seeded, site-addressable fault decisions (see module docstring)."""

    def __init__(self, seed: int, rate: float, sites=None) -> None:
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"chaos rate must be in [0, 1], got {rate}")
        unknown = set(sites or ()) - set(SITES)
        if unknown:
            raise ValueError(
                f"unregistered chaos sites {sorted(unknown)}; known: {SITES}"
            )
        self.seed = int(seed)
        self.rate = float(rate)
        self.sites = frozenset(sites) if sites else frozenset(SITES)

    def should_inject(self, site: str, key: str) -> bool:
        """Deterministic verdict for (seed, site, key); no state mutated."""
        if site not in SITES:
            raise ValueError(f"unregistered chaos site {site!r}; known: {SITES}")
        if site not in self.sites or self.rate <= 0.0:
            return False
        h = hashlib.sha256(f"{self.seed}:{site}:{key}".encode()).digest()
        return int.from_bytes(h[:8], "big") / _DENOM < self.rate

    def maybe_fail(self, site: str, key: str) -> None:
        """Raise ChaosInjected iff should_inject — the one raising seam."""
        if self.should_inject(site, key):
            from ballista_tpu.ops.runtime import record_recovery

            record_recovery("chaos_injected")
            log.warning("chaos[%s] injecting fault (key=%s)", site, key)
            raise ChaosInjected(site, key)


def chaos_from_config(config) -> Optional[ChaosInjector]:
    """Build an injector from ballista.chaos.* settings; None when disarmed
    (rate == 0) so hot paths stay a single attribute check."""
    rate = config.chaos_rate()
    if rate <= 0.0:
        return None
    return ChaosInjector(config.chaos_seed(), rate, config.chaos_sites())

"""Project lock factory + dynamic lock-order witness (ISSUE 14).

Every project lock is created through ``make_lock(name)`` / ``make_rlock``
with its CANONICAL name — the same `<module>.<attr>` identity the static
analyzer (dev/analysis/rules_lockorder.py) derives, so the runtime and the
static lock-order graph speak one vocabulary (the analyzer meta-checks the
literal against the derived name).

Normally a lock is a thin proxy over ``threading.Lock``/``RLock`` whose
acquire fast-path is one module-global flag check. In **witness mode**
(``ballista.debug.lock_witness`` / env ``BALLISTA_LOCK_WITNESS=1``) every
acquisition is checked against a thread-local stack of held locks:

- each acquired-while-held pair records an edge (with both acquisition
  stacks the first time it is seen);
- an edge that INVERTS the canonical order declared in
  dev/analysis/lockorder.toml raises ``LockOrderViolation`` at the moment
  it happens, naming both locks and carrying both stacks — and is also
  recorded in the dump, so a daemon thread swallowing the raise cannot
  hide it from CI;
- re-acquiring the same OBJECT is legal for rlocks and fatal for plain
  locks (that thread would deadlock for real one line later); distinct
  instances of an ``instance_tree`` lock class (e.g. a plan tree's join
  build locks) may nest.

``dump()`` writes the observed edges + violations as JSON for
``python -m dev.analysis --check-witness``: runtime edges the static
analyzer missed are analyzer bugs; declared edges never witnessed are
flagged stale. ``BALLISTA_LOCK_WITNESS_OUT=<path>`` dumps at interpreter
exit.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

_ENABLED = False
# witness bookkeeping — internal, leaf-only (never held while taking a
# project lock), deliberately a raw threading.Lock so it cannot recurse
# into the witness itself
_wmu = threading.Lock()
_edges: Dict[Tuple[str, str], dict] = {}  # guarded-by: _wmu
_violations: List[dict] = []  # guarded-by: _wmu
_ranks: Optional[Dict[str, int]] = None  # guarded-by: _wmu
_tree_locks: frozenset = frozenset()  # instance/plan-tree classes; guarded-by: _wmu
_plan_locks: frozenset = frozenset()  # plan_tree classes; guarded-by: _wmu
_held = threading.local()  # per-thread stack of _Held entries


class LockOrderViolation(AssertionError):
    """A lock acquisition inverted the canonical order declared in
    dev/analysis/lockorder.toml, observed as it happened."""


class _Held:
    __slots__ = ("name", "obj_id", "reentrant", "stack")

    def __init__(self, name: str, obj_id: int, reentrant: bool, stack: str):
        self.name = name
        self.obj_id = obj_id
        self.reentrant = reentrant
        self.stack = stack


def _stack() -> str:
    # drop the witness's own frames (last two)
    return "".join(traceback.format_stack(limit=16)[:-2])


def _load_manifest() -> Tuple[Dict[str, int], frozenset, frozenset]:
    """(ranks, instance-tree lock names, plan-tree lock names) from
    dev/analysis/lockorder.toml; empty when the repo layout (or tomllib) is
    absent — edges still record, only the declared-order assertion is
    disarmed."""
    try:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.path.join(root, "dev", "analysis", "lockorder.toml")
        if not os.path.exists(path):
            return {}, frozenset(), frozenset()
        try:
            import tomllib as toml  # py3.11+
        except ImportError:  # pragma: no cover - py3.10 fallback
            import tomli as toml  # type: ignore
        with open(path, "rb") as f:
            data = toml.load(f)
        ranks = {n: i for i, n in enumerate(data.get("order", ()))}
        locks = data.get("locks", {})
        plan = frozenset(
            n for n, attrs in locks.items() if attrs.get("plan_tree")
        )
        tree = plan | frozenset(
            n for n, attrs in locks.items() if attrs.get("instance_tree")
        )
        return ranks, tree, plan
    except Exception:
        return {}, frozenset(), frozenset()


def _held_stack() -> list:
    s = getattr(_held, "stack", None)
    if s is None:
        s = _held.stack = []
    return s


def _on_acquired(name: str, obj_id: int, reentrant: bool) -> None:
    """Record edges/violations for one successful acquisition and push it
    onto the thread's held stack. Called only in witness mode."""
    global _ranks, _tree_locks, _plan_locks
    held = _held_stack()
    stack = _stack()
    if held:
        # reentrant re-entry of an ALREADY-HELD object is not an
        # acquisition in ordering terms at all — it can never block, so it
        # must not paint edges (or rank violations) against the OTHER
        # locks acquired since (kv.lock -> counter lock -> kv.get is the
        # canonical legal shape). Same-object re-entry of a plain lock is
        # a guaranteed deadlock and asserts before blocking.
        for h in held:
            if h.obj_id == obj_id:
                if reentrant:
                    held.append(_Held(name, obj_id, reentrant, stack))
                    return
                with _wmu:
                    _violations.append({
                        "kind": "self_deadlock", "lock": name,
                        "held_stack": h.stack, "acquire_stack": stack,
                    })
                raise LockOrderViolation(
                    f"same-object re-acquisition of non-reentrant "
                    f"lock '{name}' — this thread deadlocks now\n"
                    f"first acquired at:\n{h.stack}\n"
                    f"re-acquired at:\n{stack}"
                )
        with _wmu:
            if _ranks is None:
                _ranks, _tree_locks, _plan_locks = _load_manifest()
            for h in held:
                if h.name == name and name in _tree_locks:
                    continue  # distinct instances, declared tree-ordered
                ent = _edges.get((h.name, name))
                if ent is None:
                    _edges[(h.name, name)] = {
                        "count": 1, "held_stack": h.stack,
                        "acquire_stack": stack,
                    }
                else:
                    ent["count"] += 1
                if h.name in _plan_locks and name in _plan_locks:
                    # plan-tree pair: instances acquire along the (acyclic)
                    # plan tree; class-level rank does not apply
                    continue
                rs = _ranks.get(h.name)
                rd = _ranks.get(name)
                if rs is not None and rd is not None and rs >= rd \
                        and h.name != name:
                    _violations.append({
                        "kind": "order_inversion", "src": h.name,
                        "dst": name, "held_stack": h.stack,
                        "acquire_stack": stack,
                    })
                    raise LockOrderViolation(
                        f"lock-order inversion: acquired '{name}' (rank "
                        f"{rd}) while holding '{h.name}' (rank {rs}); the "
                        f"declared order is the reverse\n"
                        f"'{h.name}' acquired at:\n{h.stack}\n"
                        f"'{name}' acquired at:\n{stack}"
                    )
    held.append(_Held(name, obj_id, reentrant, stack))


def _on_released(name: str, obj_id: int) -> None:
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i].name == name and held[i].obj_id == obj_id:
            del held[i]
            return


class WitnessLock:
    """Proxy over a threading lock; one global-flag check when the witness
    is off. Supports the full with/acquire(blocking=, timeout=)/release/
    locked surface the project uses."""

    __slots__ = ("_lock", "name", "_reentrant")

    def __init__(self, name: str, reentrant: bool = False) -> None:
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self.name = name
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _ENABLED:
            # check/record BEFORE blocking: a would-deadlock acquisition
            # must assert, not hang the suite
            _on_acquired(self.name, id(self), self._reentrant)
            got = self._lock.acquire(blocking, timeout)
            if not got:
                _on_released(self.name, id(self))
            return got
        return self._lock.acquire(blocking, timeout)

    def release(self) -> None:
        self._lock.release()
        if _ENABLED:
            _on_released(self.name, id(self))

    def __enter__(self) -> "WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner = self._lock
        if hasattr(inner, "locked"):
            return inner.locked()
        # RLock pre-3.12 has no locked(); approximate via non-blocking probe
        if inner.acquire(blocking=False):  # pragma: no cover
            inner.release()
            return False
        return True

    def __repr__(self) -> str:  # pragma: no cover
        return f"<WitnessLock {self.name} reentrant={self._reentrant}>"


def make_lock(name: str) -> WitnessLock:
    """A mutual-exclusion lock with a canonical name (module.attr)."""
    return WitnessLock(name, reentrant=False)


def make_rlock(name: str) -> WitnessLock:
    """A reentrant lock with a canonical name (module.attr)."""
    return WitnessLock(name, reentrant=True)


# -- witness mode control -----------------------------------------------------

def witness_enabled() -> bool:
    return _ENABLED


_dump_registered = False  # one atexit dump per process; guarded-by: _wmu


def enable_witness(out: Optional[str] = None) -> None:
    """Arm the witness for this process (sticky; idempotent — every
    SchedulerServer/PollLoop construction calls through here, so the
    atexit dump registers exactly once). `out` registers an atexit JSON
    dump."""
    global _ENABLED, _dump_registered
    _ENABLED = True
    if out:
        with _wmu:
            if _dump_registered:
                return
            _dump_registered = True
        atexit.register(dump, out)


def disable_witness() -> None:
    global _ENABLED
    _ENABLED = False


def reset_witness() -> None:
    """Drop recorded edges/violations (tests)."""
    with _wmu:
        _edges.clear()
        _violations.clear()


def _env_dump_path() -> Optional[str]:
    """Per-process dump path for env-armed runs. Witness CI lanes fork
    worker processes that ALL inherit BALLISTA_LOCK_WITNESS_OUT; with one
    shared path the last atexit os.replace wins and every other process's
    edges vanish. Each process dumps to <OUT>.<pid> instead, and
    `--check-witness` accepts the whole set, merging edges before the
    static diff."""
    out = os.environ.get("BALLISTA_LOCK_WITNESS_OUT")
    return f"{out}.{os.getpid()}" if out else None


def maybe_enable_from_config(config) -> None:
    """Arm the witness when ballista.debug.lock_witness is set — called by
    the scheduler/executor entry points so one config flag covers a whole
    StandaloneCluster. Enabling is sticky and process-global."""
    try:
        if config.debug_lock_witness():
            enable_witness(_env_dump_path())
    except Exception:
        pass


def witness_edges() -> Dict[Tuple[str, str], int]:
    with _wmu:
        return {k: v["count"] for k, v in _edges.items()}


def witness_violations() -> List[dict]:
    with _wmu:
        return list(_violations)


def dump(path: str) -> dict:
    """Write the witness record (observed edges with example stacks, and
    any violations) as JSON; returns the record."""
    with _wmu:
        record = {
            "edges": [
                {"src": s, "dst": d, "count": v["count"],
                 "held_stack": v["held_stack"],
                 "acquire_stack": v["acquire_stack"]}
                for (s, d), v in sorted(_edges.items())
            ],
            "violations": list(_violations),
        }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, path)
    return record


# env arming at import: one variable turns every subsequently created (and
# existing — the flag is checked per acquire) project lock into a witness
if os.environ.get("BALLISTA_LOCK_WITNESS", "").strip() in ("1", "true", "yes"):
    enable_witness(_env_dump_path())

"""GraphViz emitter for query-stage DAGs (ref rust/core/src/utils.rs:190-290
produce_diagram). Render with `dot -Tpng out.dot`."""

from __future__ import annotations

from typing import List

from ballista_tpu.distributed.planner import find_unresolved_shuffles
from ballista_tpu.distributed.stages import ShuffleWriterExec
from ballista_tpu.physical.plan import ExecutionPlan


def _label(node: ExecutionPlan) -> str:
    return node.fmt().replace('"', "'")


def produce_diagram(stages: List[ShuffleWriterExec]) -> str:
    lines = ["digraph G {", "  rankdir=BT;", "  node [shape=box, fontname=monospace];"]
    counter = [0]

    def emit(node: ExecutionPlan, cluster: int) -> str:
        nid = f"s{cluster}_n{counter[0]}"
        counter[0] += 1
        lines.append(f'    {nid} [label="{_label(node)}"];')
        for c in node.children():
            cid = emit(c, cluster)
            lines.append(f"    {cid} -> {nid};")
        return nid

    roots = {}
    for stage in stages:
        lines.append(f"  subgraph cluster_{stage.stage_id} {{")
        lines.append(f'    label="Stage {stage.stage_id}";')
        roots[stage.stage_id] = emit(stage, stage.stage_id)
        lines.append("  }")

    # cross-stage edges: UnresolvedShuffle -> producing stage root
    for stage in stages:
        for u in find_unresolved_shuffles(stage):
            if u.stage_id in roots:
                lines.append(
                    f'  {roots[u.stage_id]} -> {roots[stage.stage_id]} '
                    f'[style=dashed, label="shuffle"];'
                )
    lines.append("}")
    return "\n".join(lines)


def plan_diagram(plan: ExecutionPlan) -> str:
    """Single-plan dot graph (no stages)."""
    lines = ["digraph G {", "  rankdir=BT;", "  node [shape=box, fontname=monospace];"]
    counter = [0]

    def emit(node: ExecutionPlan) -> str:
        nid = f"n{counter[0]}"
        counter[0] += 1
        lines.append(f'  {nid} [label="{_label(node)}"];')
        for c in node.children():
            cid = emit(c)
            lines.append(f"  {cid} -> {nid};")
        return nid

    emit(plan)
    lines.append("}")
    return "\n".join(lines)

"""Table sources (scans).

The reference scans CSV / Parquet / in-memory tables through DataFusion's
TableProvider + the DFTableAdapter bridge (reference rust/core/src/datasource.rs:28-66).
Here a TableSource is a lightweight descriptor: schema + file list; the
physical layer turns it into scan operators, and partition count = file count
(the reference's per-file partitioning for CSV/Parquet directories).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import pyarrow as pa
import pyarrow.csv
import pyarrow.parquet

from ballista_tpu.errors import IoError, PlanError


def _discover_files(path: str, suffix: str) -> List[str]:
    """A path is a single file or a directory of part-files (reference
    behavior of DataFusion's file scan for directories)."""
    if os.path.isfile(path):
        return [path]
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith(suffix) and not f.startswith(".")
        )
        if not files:
            raise IoError(f"no *{suffix} files under {path}")
        return files
    raise IoError(f"no such path: {path}")


class TableSource:
    """Base descriptor for a scannable table."""

    def schema(self) -> pa.Schema:
        raise NotImplementedError

    def num_partitions(self) -> int:
        raise NotImplementedError

    def table_type(self) -> str:
        raise NotImplementedError


class CsvTableSource(TableSource):
    def __init__(
        self,
        path: str,
        schema: Optional[pa.Schema] = None,
        has_header: bool = True,
        delimiter: str = ",",
        file_extension: str = ".csv",
    ) -> None:
        self.path = path
        self.has_header = has_header
        self.delimiter = delimiter
        self.file_extension = file_extension
        self.files = _discover_files(path, file_extension)
        if schema is None:
            schema = self._infer_schema()
        self._schema = schema

    def _infer_schema(self) -> pa.Schema:
        read_opts = pa.csv.ReadOptions(autogenerate_column_names=not self.has_header)
        parse_opts = pa.csv.ParseOptions(delimiter=self.delimiter)
        table = pa.csv.read_csv(
            self.files[0], read_options=read_opts, parse_options=parse_opts
        )
        return table.schema

    def schema(self) -> pa.Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self.files)

    def table_type(self) -> str:
        return "csv"


class ParquetTableSource(TableSource):
    def __init__(self, path: str, file_extension: str = ".parquet") -> None:
        self.path = path
        self.files = _discover_files(path, file_extension)
        self._schema = pa.parquet.read_schema(self.files[0])

    def schema(self) -> pa.Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self.files)

    def table_type(self) -> str:
        return "parquet"


class MemoryTableSource(TableSource):
    """In-memory table: a list of record-batch lists, one list per partition."""

    def __init__(self, schema: pa.Schema, partitions: List[List[pa.RecordBatch]]) -> None:
        self._schema = schema
        self.partitions = partitions

    @classmethod
    def from_table(cls, table: pa.Table, n_partitions: int = 1) -> "MemoryTableSource":
        batches = table.to_batches()
        if 1 < n_partitions and len(batches) < n_partitions and table.num_rows:
            # a single-chunk table would otherwise land whole in partition 0
            # and leave the rest empty — split rows evenly instead
            chunk = -(-table.num_rows // n_partitions)
            batches = table.combine_chunks().to_batches(max_chunksize=chunk)
        parts: List[List[pa.RecordBatch]] = [[] for _ in range(n_partitions)]
        for i, b in enumerate(batches):
            parts[i % n_partitions].append(b)
        return cls(table.schema, parts)

    def schema(self) -> pa.Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self.partitions)

    def table_type(self) -> str:
        return "memory"


def make_source(table_type: str, path: str, options: Dict[str, Any]) -> TableSource:
    """Rebuild a source from serialized descriptor fields (serde path)."""
    if table_type == "csv":
        schema = options.get("schema")
        return CsvTableSource(
            path,
            schema=schema,
            has_header=options.get("has_header", True),
            delimiter=options.get("delimiter", ","),
            file_extension=options.get("file_extension", ".csv"),
        )
    if table_type == "parquet":
        return ParquetTableSource(path, file_extension=options.get("file_extension", ".parquet"))
    raise PlanError(f"unknown table type {table_type!r}")

"""Single-process execution context + DataFrame.

This is the engine's "DataFusion role": table registry, SQL entry point,
logical building, optimization, physical planning, and local execution.
The distributed client (ballista_tpu.client) presents the same surface but
submits plans to a scheduler instead (reference rust/client/src/context.rs).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from ballista_tpu.config import BallistaConfig
from ballista_tpu.datasource import (
    CsvTableSource,
    MemoryTableSource,
    ParquetTableSource,
    TableSource,
)
from ballista_tpu.errors import PlanError
from ballista_tpu.logical import expr as lx
from ballista_tpu.logical import plan as lp
from ballista_tpu.logical.builder import LogicalPlanBuilder
from ballista_tpu.physical.plan import ExecutionPlan, TaskContext, collect_all
from ballista_tpu.physical.planner import PhysicalPlanner


class ExecutionContext:
    def __init__(self, config: Optional[BallistaConfig] = None) -> None:
        self.config = config or BallistaConfig()
        self.tables: Dict[str, TableSource] = {}

    # -- registration ------------------------------------------------------
    def register_table(self, name: str, source: TableSource) -> None:
        self.tables[name.lower()] = source

    def register_csv(self, name: str, path: str, schema: Optional[pa.Schema] = None,
                     has_header: bool = True, delimiter: str = ",",
                     file_extension: str = ".csv") -> None:
        self.register_table(
            name,
            CsvTableSource(path, schema=schema, has_header=has_header,
                           delimiter=delimiter, file_extension=file_extension),
        )

    def register_parquet(self, name: str, path: str) -> None:
        self.register_table(name, ParquetTableSource(path))

    def register_record_batches(self, name: str, table: pa.Table,
                                n_partitions: int = 1) -> None:
        self.register_table(name, MemoryTableSource.from_table(table, n_partitions))

    # -- frames ------------------------------------------------------------
    def table(self, name: str) -> "DataFrame":
        src = self.tables.get(name.lower())
        if src is None:
            raise PlanError(f"no table registered as {name!r}")
        return DataFrame(self, LogicalPlanBuilder.scan(name, src))

    def read_csv(self, path: str, **kwargs) -> "DataFrame":
        src = CsvTableSource(path, **kwargs)
        return DataFrame(self, LogicalPlanBuilder.scan(path, src))

    def read_parquet(self, path: str) -> "DataFrame":
        src = ParquetTableSource(path)
        return DataFrame(self, LogicalPlanBuilder.scan(path, src))

    def sql(self, query: str) -> "DataFrame":
        from ballista_tpu.sql.planner import plan_sql

        plan = plan_sql(query, self)
        if isinstance(plan, lp.CreateExternalTable):
            self._create_external_table(plan)
            return DataFrame(self, LogicalPlanBuilder.empty(False))
        return DataFrame(self, LogicalPlanBuilder(plan))

    def _create_external_table(self, node: lp.CreateExternalTable) -> None:
        ft = node.file_type.lower()
        if ft == "csv":
            self.register_csv(node.name, node.location, schema=node.table_schema,
                              has_header=node.has_header)
        elif ft == "parquet":
            self.register_parquet(node.name, node.location)
        else:
            raise PlanError(f"unsupported external table file type {node.file_type!r}")

    # -- execution ---------------------------------------------------------
    def optimize(self, plan: lp.LogicalPlan) -> lp.LogicalPlan:
        from ballista_tpu.optimizer.rules import optimize_plan

        return optimize_plan(plan)

    def create_physical_plan(self, plan: lp.LogicalPlan) -> ExecutionPlan:
        planner = PhysicalPlanner(
            batch_size=self.config.batch_size(),
            coalesce_aggregates=self.config.tpu_coalesce_aggregates(),
            coalesce_max_bytes=self.config.tpu_coalesce_max_bytes(),
            spmd_joins=self.config.tpu_spmd(),
        )
        return planner.create_physical_plan(self.optimize(plan))

    def collect(self, plan: lp.LogicalPlan) -> pa.Table:
        from ballista_tpu.utils.tracing import span

        with span("plan"):
            physical = self.create_physical_plan(plan)
        ctx = TaskContext(config=self.config)
        with span("execute"):
            return collect_all(physical, ctx)


class DataFrame:
    """Relational-verb DataFrame over a logical plan (reference
    BallistaDataFrame, rust/client/src/context.rs:149-315)."""

    def __init__(self, ctx: ExecutionContext, builder: LogicalPlanBuilder) -> None:
        self._ctx = ctx
        self._builder = builder

    # verbs ---------------------------------------------------------------
    def select_columns(self, *names: str) -> "DataFrame":
        return self.select(*[lx.col(n) for n in names])

    def select(self, *exprs: lx.Expr) -> "DataFrame":
        return DataFrame(self._ctx, self._builder.project(list(exprs)))

    def filter(self, predicate: lx.Expr) -> "DataFrame":
        return DataFrame(self._ctx, self._builder.filter(predicate))

    def aggregate(self, group_by: Sequence[lx.Expr], aggs: Sequence[lx.Expr]) -> "DataFrame":
        return DataFrame(self._ctx, self._builder.aggregate(group_by, aggs))

    def sort(self, *exprs: lx.SortExpr) -> "DataFrame":
        return DataFrame(self._ctx, self._builder.sort(list(exprs)))

    def limit(self, n: int, skip: int = 0) -> "DataFrame":
        return DataFrame(self._ctx, self._builder.limit(n, skip))

    def join(self, right: "DataFrame", left_cols: Sequence[str],
             right_cols: Sequence[str], how: str = "inner") -> "DataFrame":
        on = [
            (lx.col(l), lx.col(r)) for l, r in zip(left_cols, right_cols)
        ]
        jt = lp.JoinType(how)
        return DataFrame(self._ctx, self._builder.join(right._builder, on, jt))

    def repartition(self, n: int, *hash_exprs: lx.Expr) -> "DataFrame":
        if hash_exprs:
            return DataFrame(self._ctx, self._builder.repartition_hash(list(hash_exprs), n))
        return DataFrame(self._ctx, self._builder.repartition_round_robin(n))

    def distinct(self) -> "DataFrame":
        return DataFrame(self._ctx, self._builder.distinct())

    def alias(self, name: str) -> "DataFrame":
        return DataFrame(self._ctx, self._builder.alias(name))

    def union(self, *others: "DataFrame", all: bool = True) -> "DataFrame":
        return DataFrame(
            self._ctx, self._builder.union([o._builder for o in others], all=all)
        )

    # terminal ------------------------------------------------------------
    def logical_plan(self) -> lp.LogicalPlan:
        return self._builder.build()

    def schema(self) -> pa.Schema:
        return self.logical_plan().schema()

    def explain(self) -> str:
        logical = self.logical_plan()
        optimized = self._ctx.optimize(logical)
        physical = self._ctx.create_physical_plan(logical)
        return (
            "== Logical Plan ==\n" + str(logical)
            + "\n== Optimized Logical Plan ==\n" + str(optimized)
            + "\n== Physical Plan ==\n" + str(physical)
        )

    def collect(self) -> pa.Table:
        return self._ctx.collect(self.logical_plan())

    def to_pandas(self):
        return self.collect().to_pandas()

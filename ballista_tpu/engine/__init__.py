from ballista_tpu.engine.context import ExecutionContext, DataFrame  # noqa: F401

// Native shuffle partitioner.
//
// The repartition-exchange host path (the data plane the reference implements
// in Rust: hash partitioning in RepartitionExec + the shuffle writer split,
// ref rust/executor/src/flight_service.rs + execution_plans) implemented in
// C++: splitmix64 row hashing over Arrow column buffers and a counting-sort
// partition split producing contiguous per-partition row-index ranges —
// O(n + P) instead of the O(n*P) per-partition filter loop.
//
// Build: g++ -O3 -shared -fPIC -o libballista_shuffle.so shuffle.cpp
// Bound via ctypes (no pybind11 in the toolchain).

#include <cstdint>
#include <cstring>

static inline uint64_t splitmix64(uint64_t x) {
    uint64_t z = x + 0x9E3779B97F4A7C15ULL;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

extern "C" {

// Mix an int64 key column into the per-row hash accumulator.
void hash_mix_i64(const int64_t* keys, int64_t n, uint64_t* acc) {
    for (int64_t i = 0; i < n; i++) {
        acc[i] = splitmix64(acc[i] ^ splitmix64((uint64_t)keys[i]));
    }
}

// Mix an int32 key column (dates, dictionary codes).
void hash_mix_i32(const int32_t* keys, int64_t n, uint64_t* acc) {
    for (int64_t i = 0; i < n; i++) {
        acc[i] = splitmix64(acc[i] ^ splitmix64((uint64_t)(int64_t)keys[i]));
    }
}

// Mix a float64 key column (bit pattern).
void hash_mix_f64(const double* keys, int64_t n, uint64_t* acc) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t bits;
        std::memcpy(&bits, &keys[i], sizeof(bits));
        acc[i] = splitmix64(acc[i] ^ splitmix64(bits));
    }
}

// Mix a UTF-8 string column (Arrow offsets + data buffers), FNV-1a per row.
void hash_mix_str(const int32_t* offsets, const uint8_t* data, int64_t n,
                  uint64_t* acc) {
    for (int64_t i = 0; i < n; i++) {
        uint64_t h = 0xCBF29CE484222325ULL;
        for (int32_t j = offsets[i]; j < offsets[i + 1]; j++) {
            h = (h ^ data[j]) * 0x100000001B3ULL;
        }
        acc[i] = splitmix64(acc[i] ^ h);
    }
}

// Finalize: map accumulated hashes to partition ids.
void hash_to_partitions(const uint64_t* acc, int64_t n, uint32_t num_parts,
                        int32_t* out_part_ids) {
    for (int64_t i = 0; i < n; i++) {
        out_part_ids[i] = (int32_t)(acc[i] % (uint64_t)num_parts);
    }
}

// Counting sort by partition id: emits row indices grouped by partition
// (out_indices) and partition offsets (out_offsets, length num_parts+1).
void partition_indices(const int32_t* part_ids, int64_t n, uint32_t num_parts,
                       int64_t* out_indices, int64_t* out_offsets) {
    for (uint32_t p = 0; p <= num_parts; p++) out_offsets[p] = 0;
    for (int64_t i = 0; i < n; i++) out_offsets[part_ids[i] + 1]++;
    for (uint32_t p = 0; p < num_parts; p++) out_offsets[p + 1] += out_offsets[p];
    // stable fill
    int64_t* cursor = new int64_t[num_parts];
    for (uint32_t p = 0; p < num_parts; p++) cursor[p] = out_offsets[p];
    for (int64_t i = 0; i < n; i++) {
        out_indices[cursor[part_ids[i]]++] = i;
    }
    delete[] cursor;
}

}  // extern "C"

"""Native (C++) runtime components, bound via ctypes.

Builds lazily with g++ on first import; falls back to the numpy path when no
compiler or build failure (the library is optional, the contract is not).
"""

from __future__ import annotations

import ctypes
import logging
import os
import pathlib
import subprocess
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa

log = logging.getLogger("ballista.native")

_HERE = pathlib.Path(__file__).resolve().parent
_SO = _HERE / "libballista_shuffle.so"
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    src = _HERE / "shuffle.cpp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", str(_SO), str(src)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception as e:
        log.warning("native shuffle build failed, using numpy fallback: %s", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _SO.exists() or _SO.stat().st_mtime < (_HERE / "shuffle.cpp").stat().st_mtime:
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(str(_SO))
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.hash_mix_i64.argtypes = [i64p, ctypes.c_int64, u64p]
        lib.hash_mix_i32.argtypes = [i32p, ctypes.c_int64, u64p]
        lib.hash_mix_f64.argtypes = [f64p, ctypes.c_int64, u64p]
        lib.hash_mix_str.argtypes = [i32p, u8p, ctypes.c_int64, u64p]
        lib.hash_to_partitions.argtypes = [u64p, ctypes.c_int64, ctypes.c_uint32, i32p]
        lib.partition_indices.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_uint32, i64p, i64p
        ]
        _lib = lib
    except OSError as e:
        log.warning("cannot load native shuffle lib: %s", e)
    return _lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def native_hash_rows(arrays: List[pa.Array], num_partitions: int) -> Optional[np.ndarray]:
    """C++ row hashing over Arrow buffers; None -> caller uses numpy path.

    Produces bit-identical results to the numpy implementation in
    physical/repartition.py (same splitmix64/FNV-1a scheme), so executors
    with and without a compiler can cooperate in one shuffle.
    """
    lib = get_lib()
    if lib is None:
        return None
    try:
        n = len(arrays[0])
        acc = np.zeros(n, dtype=np.uint64)
        for arr in arrays:
            a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
            if a.null_count:
                return None
            t = a.type
            if pa.types.is_date32(t):
                a = a.cast(pa.int32())
                t = a.type
            if (
                pa.types.is_integer(t)
                or pa.types.is_boolean(t)
                or pa.types.is_timestamp(t)
            ):
                # everything integer-like routes through int64, matching the
                # numpy path exactly (sub-64-bit values sign/zero-extend the
                # same way; uint32 > 2^31 must not truncate)
                vals = np.ascontiguousarray(
                    a.cast(pa.int64()).to_numpy(zero_copy_only=False).astype(np.int64)
                )
                lib.hash_mix_i64(
                    _ptr(vals, ctypes.c_int64), n, _ptr(acc, ctypes.c_uint64)
                )
            elif pa.types.is_floating(t):
                vals = np.ascontiguousarray(
                    a.cast(pa.float64()).to_numpy(zero_copy_only=False)
                )
                lib.hash_mix_f64(_ptr(vals, ctypes.c_double), n, _ptr(acc, ctypes.c_uint64))
            elif pa.types.is_string(t):
                bufs = a.buffers()  # [validity, offsets, data]
                if a.offset != 0:
                    return None
                offsets = np.frombuffer(bufs[1], dtype=np.int32, count=n + 1)
                data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] else np.zeros(1, np.uint8)
                lib.hash_mix_str(
                    _ptr(np.ascontiguousarray(offsets), ctypes.c_int32),
                    _ptr(np.ascontiguousarray(data), ctypes.c_uint8),
                    n,
                    _ptr(acc, ctypes.c_uint64),
                )
            else:
                return None
        out = np.empty(n, dtype=np.int32)
        lib.hash_to_partitions(
            _ptr(acc, ctypes.c_uint64), n, num_partitions, _ptr(out, ctypes.c_int32)
        )
        return out
    except Exception as e:  # contract: any native-path surprise -> numpy path
        log.warning("native hash failed, numpy fallback: %s", e)
        return None


def native_partition_indices(
    part_ids: np.ndarray, num_partitions: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Counting-sort split: returns (row indices grouped by partition,
    offsets[num_partitions+1]); None -> numpy fallback."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(part_ids)
    ids = np.ascontiguousarray(part_ids, dtype=np.int32)
    indices = np.empty(n, dtype=np.int64)
    offsets = np.empty(num_partitions + 1, dtype=np.int64)
    lib.partition_indices(
        _ptr(ids, ctypes.c_int32), n, num_partitions,
        _ptr(indices, ctypes.c_int64), _ptr(offsets, ctypes.c_int64),
    )
    return indices, offsets

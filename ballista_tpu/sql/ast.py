"""SQL statement AST (expressions reuse the logical Expr tree directly)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from ballista_tpu.logical.expr import Expr


class FromItem:
    pass


@dataclass
class TableRef(FromItem):
    name: str
    alias: Optional[str] = None


@dataclass
class SubqueryRef(FromItem):
    stmt: "SelectStmt"
    alias: str


@dataclass
class JoinItem(FromItem):
    left: FromItem
    right: FromItem
    join_type: str  # inner | left | right | full | cross
    condition: Optional[Expr]


@dataclass
class OrderItem:
    expr: Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = dialect default (asc: last)


@dataclass
class SelectStmt:
    distinct: bool = False
    projections: List[Tuple[Any, Optional[str]]] = field(default_factory=list)
    # each projection: (Expr | "*" | ("qualified_star", rel), alias)
    from_items: List[FromItem] = field(default_factory=list)
    where: Optional[Expr] = None
    group_by: List[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    union_with: List[Tuple["SelectStmt", bool]] = field(default_factory=list)  # (stmt, all)
    # GROUP BY ROLLUP/CUBE/GROUPING SETS: index lists into group_by, one per
    # grouping set; None = plain GROUP BY
    grouping_sets: Optional[List[List[int]]] = None


@dataclass
class CreateExternalTableStmt:
    name: str
    columns: List[Tuple[str, str]]
    file_type: str
    location: str
    has_header: bool = False


@dataclass
class ExplainStmt:
    stmt: SelectStmt
    verbose: bool = False


class IntervalLiteral(Expr):
    """INTERVAL 'n' unit — only valid in date arithmetic, folded at plan time."""

    def __init__(self, months: int, days: int) -> None:
        self.months = months
        self.days = days

    def __str__(self) -> str:
        return f"INTERVAL {self.months}mo {self.days}d"

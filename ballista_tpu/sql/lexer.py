"""SQL lexer.

The reference delegates SQL parsing to DataFusion's sqlparser
(rust/scheduler/src/lib.rs:236-249 parses SQL server-side). Built natively
here: tokens for the SQL subset covering TPC-H q1-q22 plus DDL
(CREATE EXTERNAL TABLE).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

from ballista_tpu.errors import SqlError

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "like", "between", "is",
    "null", "true", "false", "case", "when", "then", "else", "end", "cast",
    "join", "inner", "left", "right", "full", "outer", "cross", "on",
    "union", "all", "distinct", "exists", "any", "some", "asc", "desc",
    "nulls", "first", "last", "date", "interval", "timestamp", "time",
    "extract", "substring", "for", "create", "external", "table", "stored",
    "location", "with", "header", "row", "options", "explain", "analyze",
    "verbose", "escape", "over", "partition",
    "rows", "range", "unbounded", "preceding", "following", "current",
    "rollup", "cube", "grouping", "sets",
}


class Token(NamedTuple):
    kind: str  # keyword | ident | number | string | op | eof
    value: str
    pos: int


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    i = 0
    n = len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i)
            if j < 0:
                raise SqlError("unterminated block comment")
            i = j + 2
            continue
        if c == "'":
            # string literal with '' escape
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise SqlError("unterminated string literal")
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            tokens.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise SqlError("unterminated quoted identifier")
            tokens.append(Token("ident", sql[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i:
                    seen_exp = True
                    j += 1
                    if j < n and sql[j] in "+-":
                        j += 1
                else:
                    break
            tokens.append(Token("number", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            if word.lower() in KEYWORDS:
                tokens.append(Token("keyword", word.lower(), i))
            else:
                tokens.append(Token("ident", word, i))
            i = j
            continue
        # operators
        for op in ("<>", "<=", ">=", "!=", "||"):
            if sql.startswith(op, i):
                tokens.append(Token("op", op, i))
                i += 2
                break
        else:
            if c in "+-*/%(),.;=<>":
                tokens.append(Token("op", c, i))
                i += 1
            else:
                raise SqlError(f"unexpected character {c!r} at {i}")
    tokens.append(Token("eof", "", n))
    return tokens

"""SQL -> LogicalPlan planner.

Covers the full TPC-H q1-q22 surface:
- comma-style FROM lists with join-graph ordering (equi predicates pulled from
  WHERE become join keys; single-relation predicates push to their scan side
  before joining — essential at SF>=1)
- explicit JOIN ... ON, cross joins, derived tables
- aggregate extraction + post-aggregate expression rewriting (SELECT/HAVING/
  ORDER BY over aggregate results)
- subquery decorrelation: uncorrelated IN -> SEMI join, NOT IN -> ANTI,
  correlated EXISTS/NOT EXISTS -> SEMI/ANTI on correlation keys, correlated
  scalar-aggregate subqueries -> grouped aggregate + INNER join (q2/q17-style),
  uncorrelated scalar subqueries -> single-row aggregate + cross join.

The reference gets all of this from DataFusion's SQL frontend; it is built
natively here (SQL entry at reference rust/scheduler/src/lib.rs:236-249,
client side rust/client/src/context.rs:131-143).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import pyarrow as pa

from ballista_tpu.errors import PlanError, SchemaError, SqlError
from ballista_tpu.logical import expr as lx
from ballista_tpu.logical import plan as lp
from ballista_tpu.sql import ast as sa
from ballista_tpu.sql.parser import parse_sql, parse_type


def plan_sql(query: str, ctx) -> lp.LogicalPlan:
    stmt = parse_sql(query)
    if isinstance(stmt, sa.CreateExternalTableStmt):
        schema = None
        if stmt.columns:
            schema = pa.schema(
                [pa.field(n, parse_type(t)) for n, t in stmt.columns]
            )
        return lp.CreateExternalTable(
            stmt.name, stmt.location, stmt.file_type, stmt.has_header, schema
        )
    if isinstance(stmt, sa.ExplainStmt):
        inner = SelectPlanner(ctx).plan(stmt.stmt)
        return lp.Explain(inner, stmt.verbose)
    return SelectPlanner(ctx).plan(stmt)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def split_conjuncts(e: Optional[lx.Expr]) -> List[lx.Expr]:
    if e is None:
        return []
    if isinstance(e, lx.BinaryExpr) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def conjoin(exprs: Sequence[lx.Expr]) -> Optional[lx.Expr]:
    out: Optional[lx.Expr] = None
    for e in exprs:
        out = e if out is None else lx.BinaryExpr(out, "and", e)
    return out


def factor_or_common(e: lx.Expr) -> List[lx.Expr]:
    """(A and X) or (A and Y) -> [A, (X or Y)].

    Lifts conjuncts common to every OR branch to the top level, so equi-join
    keys hidden inside each disjunct become visible to join planning. q19's
    WHERE is the canonical shape: all three OR branches repeat
    `p_partkey = l_partkey` (+ shipmode/shipinstruct filters); without
    factoring the whole predicate lands post-join and the join degrades to a
    cartesian product (8.7 TiB of pairs at SF=1). Same rewrite DataFusion
    applies before join-key extraction. Returns the conjunct list (the input
    unchanged, as a 1-list, when nothing factors).
    """
    if not (isinstance(e, lx.BinaryExpr) and e.op == "or"):
        return [e]

    branches: List[lx.Expr] = []

    def flat_or(x: lx.Expr) -> None:
        if isinstance(x, lx.BinaryExpr) and x.op == "or":
            flat_or(x.left)
            flat_or(x.right)
        else:
            branches.append(x)

    flat_or(e)
    branch_conjs = [split_conjuncts(b) for b in branches]
    # conjuncts present (by structural string) in every branch
    keyed = [{str(c): c for c in bc} for bc in branch_conjs]
    common_keys = set(keyed[0])
    for k in keyed[1:]:
        common_keys &= set(k)
    if not common_keys:
        return [e]
    common = [c for key, c in keyed[0].items() if key in common_keys]
    residuals: List[Optional[lx.Expr]] = []
    for bc in branch_conjs:
        seen: Set[str] = set()
        rest = []
        for c in bc:
            # drop only ONE occurrence per common key (duplicates stay)
            if str(c) in common_keys and str(c) not in seen:
                seen.add(str(c))
                continue
            rest.append(c)
        residuals.append(conjoin(rest))
    if any(r is None for r in residuals):
        # some branch was exactly the common part: A or (A and X) = A
        return common
    disj: lx.Expr = residuals[0]
    for r in residuals[1:]:
        disj = lx.BinaryExpr(disj, "or", r)
    return common + [disj]


def collect_columns(e: lx.Expr, out: List[lx.Column]) -> None:
    if isinstance(e, lx.Column):
        out.append(e)
    for c in e.children():
        collect_columns(c, out)


def contains_subquery(e: lx.Expr) -> bool:
    if isinstance(e, (lx.ScalarSubquery, lx.InSubquery, lx.Exists)):
        return True
    return any(contains_subquery(c) for c in e.children())


def collect_aggregates(e: lx.Expr, out: List[lx.AggregateExpr]) -> None:
    if isinstance(e, lx.WindowExpr):
        return  # window-function internals are not GROUP BY aggregates
    if isinstance(e, lx.AggregateExpr):
        if not any(a.equals(e) for a in out):
            out.append(e)
        return
    for c in e.children():
        collect_aggregates(c, out)


def collect_windows(e: lx.Expr, out: List["lx.WindowExpr"]) -> None:
    if isinstance(e, lx.WindowExpr):
        if not any(str(w) == str(e) for w in out):
            out.append(e)
        return
    for c in e.children():
        collect_windows(c, out)


def _contains_grouping(e: lx.Expr) -> bool:
    if isinstance(e, lx.ScalarFunction) and e.fn == "grouping":
        return True
    return any(
        isinstance(c, lx.Expr) and _contains_grouping(c) for c in e.children()
    )


def dataclasses_replace_projections(stmt, mapping):
    """stmt copy with the mapping applied to projections, having, order by."""
    import dataclasses

    return dataclasses.replace(
        stmt,
        projections=[
            (rewrite_expr(e, mapping) if isinstance(e, lx.Expr) else e, a)
            for e, a in stmt.projections
        ],
        having=None if stmt.having is None else rewrite_expr(stmt.having, mapping),
        order_by=[
            dataclasses.replace(oi, expr=rewrite_expr(oi.expr, mapping))
            for oi in stmt.order_by
        ],
    )


def _null_out(e: lx.Expr, excluded_strs) -> lx.Expr:
    """Replace references to excluded group keys with NULL (grouping-set
    branches); NULL propagates through enclosing expressions. Aggregate
    arguments are protected: super-aggregate rows aggregate the REAL column
    (count(r) in the grand total counts every non-null r, per the standard),
    only the group-key projection of r becomes NULL."""
    if not excluded_strs:
        return e
    aggs: List[lx.AggregateExpr] = []
    collect_aggregates(e, aggs)
    hide = {str(a): lx.Column(f"__gs_protect_{i}") for i, a in enumerate(aggs)}
    unhide = {str(c): a for a, c in zip(aggs, hide.values())}
    e = rewrite_expr(e, hide)
    e = rewrite_expr(e, {s: lx.Literal(None, pa.null()) for s in excluded_strs})
    return rewrite_expr(e, unhide)


def rewrite_expr(e: lx.Expr, mapping: Dict[str, lx.Expr]) -> lx.Expr:
    """Replace any subtree whose str() matches a mapping key."""
    key = str(e)
    if key in mapping:
        return mapping[key]
    if isinstance(e, lx.Alias):
        return lx.Alias(rewrite_expr(e.expr, mapping), e.name)
    if isinstance(e, lx.BinaryExpr):
        return lx.BinaryExpr(
            rewrite_expr(e.left, mapping), e.op, rewrite_expr(e.right, mapping)
        )
    if isinstance(e, lx.Not):
        return lx.Not(rewrite_expr(e.expr, mapping))
    if isinstance(e, lx.Negative):
        return lx.Negative(rewrite_expr(e.expr, mapping))
    if isinstance(e, lx.IsNull):
        return lx.IsNull(rewrite_expr(e.expr, mapping))
    if isinstance(e, lx.IsNotNull):
        return lx.IsNotNull(rewrite_expr(e.expr, mapping))
    if isinstance(e, lx.Between):
        return lx.Between(
            rewrite_expr(e.expr, mapping),
            rewrite_expr(e.low, mapping),
            rewrite_expr(e.high, mapping),
            e.negated,
        )
    if isinstance(e, lx.InList):
        return lx.InList(
            rewrite_expr(e.expr, mapping),
            [rewrite_expr(v, mapping) for v in e.values],
            e.negated,
        )
    if isinstance(e, lx.Like):
        return lx.Like(
            rewrite_expr(e.expr, mapping),
            rewrite_expr(e.pattern, mapping),
            e.negated,
            e.escape,
        )
    if isinstance(e, lx.Case):
        return lx.Case(
            None if e.expr is None else rewrite_expr(e.expr, mapping),
            [
                (rewrite_expr(w, mapping), rewrite_expr(t, mapping))
                for w, t in e.when_then
            ],
            None if e.else_expr is None else rewrite_expr(e.else_expr, mapping),
        )
    if isinstance(e, lx.TryCast):
        return lx.TryCast(rewrite_expr(e.expr, mapping), e.dtype)
    if isinstance(e, lx.Cast):
        return lx.Cast(rewrite_expr(e.expr, mapping), e.dtype)
    if isinstance(e, lx.ScalarFunction):
        return lx.ScalarFunction(e.fn, [rewrite_expr(a, mapping) for a in e.args])
    if isinstance(e, lx.SortExpr):
        return lx.SortExpr(rewrite_expr(e.expr, mapping), e.ascending, e.nulls_first)
    if isinstance(e, lx.AggregateExpr):
        return lx.AggregateExpr(e.fn, rewrite_expr(e.expr, mapping), e.distinct)
    if isinstance(e, lx.WindowExpr):
        return lx.WindowExpr(
            e.fn,
            None if e.arg is None else rewrite_expr(e.arg, mapping),
            [rewrite_expr(p, mapping) for p in e.partition_by],
            [rewrite_expr(o, mapping) for o in e.order_by],
            e.frame,
        )
    return e


def _resolves_in(col: lx.Column, schema: pa.Schema) -> bool:
    try:
        col.index_in(schema)
        return True
    except SchemaError:
        return False


def _expr_resolves(e: lx.Expr, schema: pa.Schema) -> bool:
    """True when every column reference under e resolves against schema.
    Walks the tree explicitly — data_type() can short-circuit (boolean
    BinaryExprs return bool without resolving their children)."""
    if isinstance(e, lx.Column):
        return _resolves_in(e, schema)
    return all(_expr_resolves(c, schema) for c in e.children())


# ---------------------------------------------------------------------------
# SelectPlanner
# ---------------------------------------------------------------------------


class SelectPlanner:
    def __init__(self, ctx, outer_schema: Optional[pa.Schema] = None) -> None:
        self.ctx = ctx
        self.outer_schema = outer_schema

    # -- entry -------------------------------------------------------------
    def _plan_core(self, stmt: sa.SelectStmt) -> lp.LogicalPlan:
        """One statement body, grouping sets included (no union/order)."""
        if stmt.grouping_sets is not None:
            return self._plan_grouping_sets(stmt)
        return self._plan_body(stmt)

    def plan(self, stmt: sa.SelectStmt) -> lp.LogicalPlan:
        plan = self._plan_core(stmt)
        if stmt.union_with:
            branches = [plan]
            all_flags = []
            for sub, all_ in stmt.union_with:
                branches.append(self._plan_core(sub))
                all_flags.append(all_)
            # normalize field names to the first branch's
            base_schema = branches[0].schema()
            for b in branches[1:]:
                if len(b.schema()) != len(base_schema):
                    raise SqlError(
                        f"UNION branches have different column counts: "
                        f"{len(base_schema)} vs {len(b.schema())}"
                    )
            norm = [branches[0]]
            for b in branches[1:]:
                if b.schema().names != base_schema.names:
                    exprs = []
                    for f_out, f_in in zip(base_schema, b.schema()):
                        bare = f_in.name.split(".")[-1]
                        rel = f_in.name.split(".")[0] if "." in f_in.name else None
                        exprs.append(lx.Alias(lx.Column(bare, rel), f_out.name))
                    b = lp.Projection(b, exprs)
                norm.append(b)
            u: lp.LogicalPlan = lp.Union(norm, all=True)
            if not all(all_flags):
                u = lp.Distinct(u)
            plan = u
        plan = self._apply_order_limit(plan, stmt)
        return plan

    # -- grouping sets ------------------------------------------------------
    def _plan_grouping_sets(self, stmt: sa.SelectStmt) -> lp.LogicalPlan:
        """ROLLUP/CUBE/GROUPING SETS lower to a UNION ALL of one aggregation
        per grouping set; group keys excluded from a set project as typed
        NULLs (references to them inside expressions become NULL and
        propagate), and GROUPING(key) markers resolve to 0/1 per set."""
        import dataclasses

        def resolve_grouping_markers(e: lx.Expr, excluded_strs) -> lx.Expr:
            """GROUPING(key) -> 1 when the key is aggregated away in this
            grouping set, else 0 (the standard's super-aggregate marker)."""
            mapping = {}
            for g in stmt.group_by:
                marker = lx.ScalarFunction("grouping", [g])
                mapping[str(marker)] = lx.Literal(
                    1 if str(g) in excluded_strs else 0, pa.int64()
                )
            return rewrite_expr(e, mapping)

        # probe: the full-key variant fixes the output schema (types for the
        # NULL fills and the union contract)
        probe = dataclasses.replace(
            stmt,
            projections=[
                (resolve_grouping_markers(e, set()) if isinstance(e, lx.Expr) else e,
                 a)
                for e, a in stmt.projections
            ],
            having=(
                resolve_grouping_markers(stmt.having, set())
                if stmt.having is not None
                else None
            ),
            grouping_sets=None, order_by=[], limit=None, offset=0,
            union_with=[],
        )
        probe_plan = self._plan_body(probe)
        out_schema = probe_plan.schema()

        if any(not isinstance(e, lx.Expr) for e, _ in stmt.projections):
            raise SqlError("SELECT * is not valid with grouping sets")
        if len(out_schema) != len(stmt.projections):
            raise SqlError("grouping sets cannot resolve the select list")

        branches: List[lp.LogicalPlan] = []
        all_keys = set(range(len(stmt.group_by)))
        for s in stmt.grouping_sets:
            if set(s) == all_keys:
                # the probe IS the full-key branch (ROLLUP/CUBE always have
                # one); don't plan the most expensive branch twice
                branches.append(probe_plan)
                continue
            excluded = {
                str(stmt.group_by[i])
                for i in range(len(stmt.group_by))
                if i not in s
            }
            # cast + alias every entry to the probe's field so all branches
            # share one schema (names AND types) for the union
            projections = []
            for (e, _alias), f_out in zip(stmt.projections, out_schema):
                e2 = _null_out(resolve_grouping_markers(e, excluded), excluded)
                projections.append((lx.Alias(lx.Cast(e2, f_out.type), f_out.name), None))
            having = (
                _null_out(resolve_grouping_markers(stmt.having, excluded), excluded)
                if stmt.having is not None
                else None
            )
            variant = dataclasses.replace(
                stmt,
                projections=projections,
                group_by=[stmt.group_by[i] for i in s],
                having=having,
                grouping_sets=None,
                order_by=[],
                limit=None,
                offset=0,
                union_with=[],
            )
            branches.append(self._plan_body(variant))
        # ORDER BY on the union resolves selected expressions to the shared
        # output columns (per-branch aggregate mappings don't apply)
        self._order_mapping = {
            str(e): lx.Column(f_out.name)
            for (e, _a), f_out in zip(stmt.projections, out_schema)
        }
        return lp.Union(branches, all=True)

    # -- body (no union/order/limit) ---------------------------------------
    def _plan_body(self, stmt: sa.SelectStmt) -> lp.LogicalPlan:
        # GROUPING(key) under plain GROUP BY is constantly 0; anything the
        # grouping-sets rewrite didn't resolve (non-key argument, no GROUP
        # BY) must fail here with a clear message rather than at execution
        if any(
            _contains_grouping(e)
            for e, _ in stmt.projections
            if isinstance(e, lx.Expr)
        ) or (stmt.having is not None and _contains_grouping(stmt.having)):
            zeros = {
                str(lx.ScalarFunction("grouping", [g])): lx.Literal(0, pa.int64())
                for g in stmt.group_by
            }
            stmt = dataclasses_replace_projections(stmt, zeros)
            for e, _ in stmt.projections:
                if isinstance(e, lx.Expr) and _contains_grouping(e):
                    raise SqlError(
                        "GROUPING() takes a grouping key and requires GROUP BY"
                    )
            if stmt.having is not None and _contains_grouping(stmt.having):
                raise SqlError(
                    "GROUPING() takes a grouping key and requires GROUP BY"
                )
        # 1. FROM + WHERE with join-graph ordering
        plan = self._plan_from_where(stmt)

        # 2. aggregate extraction
        aggs: List[lx.AggregateExpr] = []
        select_exprs: List[lx.Expr] = []
        for proj, alias in stmt.projections:
            # note: proj may be an Expr whose __eq__ is overloaded; compare
            # types first
            if isinstance(proj, str) and proj == "*":
                for f in plan.schema():
                    bare = f.name.split(".")[-1]
                    rel = f.name.split(".")[0] if "." in f.name else None
                    select_exprs.append(lx.Column(bare, rel))
                continue
            if isinstance(proj, tuple) and proj[0] == "qualified_star":
                rel = proj[1]
                for f in plan.schema():
                    if f.name.startswith(rel + "."):
                        select_exprs.append(lx.Column(f.name.split(".")[-1], rel))
                continue
            e = proj
            if alias:
                e = lx.Alias(e, alias)
            select_exprs.append(e)

        for e in select_exprs:
            collect_aggregates(e, aggs)
        if stmt.having is not None:
            collect_aggregates(stmt.having, aggs)
        for oi in stmt.order_by:
            collect_aggregates(oi.expr, aggs)

        group_exprs = self._resolve_group_by(stmt.group_by, select_exprs)

        if aggs or group_exprs:
            plan, mapping = self._plan_aggregate(plan, group_exprs, aggs)
            select_exprs = [rewrite_expr(e, mapping) for e in select_exprs]
            if stmt.having is not None:
                having = rewrite_expr(stmt.having, mapping)
                plain_having = []
                for c in split_conjuncts(having):
                    if contains_subquery(c):
                        plan = self._apply_subquery_conjunct(plan, c)
                    else:
                        plain_having.append(c)
                if plain_having:
                    plan = lp.Filter(plan, conjoin(plain_having))
            self._order_mapping = mapping
        else:
            if stmt.having is not None:
                raise SqlError("HAVING requires GROUP BY or aggregates")
            self._order_mapping = {}

        # window functions evaluate over the (post-aggregate) relation
        wexprs: List[lx.Expr] = []
        for e in select_exprs:
            collect_windows(e, wexprs)
        if wexprs:
            plan = lp.Window(plan, wexprs)
            wmap = {str(w): lx.Column(w.output_name()) for w in wexprs}
            select_exprs = [rewrite_expr(e, wmap) for e in select_exprs]
            self._order_mapping.update(wmap)

        plan = lp.Projection(plan, select_exprs)
        if stmt.distinct:
            plan = lp.Distinct(plan)
        return plan

    def _apply_order_limit(self, plan: lp.LogicalPlan, stmt: sa.SelectStmt) -> lp.LogicalPlan:
        if stmt.order_by:
            out_schema = plan.schema()
            sort_exprs = []
            mapping = getattr(self, "_order_mapping", {})
            # ORDER BY may reference input columns/exprs the SELECT list
            # dropped (standard SQL): append them to the projection as
            # hidden sort columns, sort, then strip. DISTINCT keeps the
            # strict rule (hidden columns would change its semantics).
            base_proj = plan if isinstance(plan, lp.Projection) else None
            hidden: List[lx.Expr] = []
            for hi, oi in enumerate(stmt.order_by):
                e = oi.expr
                # ordinal reference: ORDER BY 1
                if isinstance(e, lx.Literal) and isinstance(e.value, int):
                    idx = e.value - 1
                    if not (0 <= idx < len(out_schema)):
                        raise SqlError(f"ORDER BY position {e.value} out of range")
                    f = out_schema.field(idx)
                    e = lx.Column(f.name.split(".")[-1],
                                  f.name.split(".")[0] if "." in f.name else None)
                else:
                    e = rewrite_expr(e, mapping)
                    if not _expr_resolves(e, out_schema):
                        if base_proj is not None and _expr_resolves(
                            e, base_proj.input.schema()
                        ):
                            name = f"__sort_{hi}"
                            hidden.append(lx.Alias(e, name))
                            e = lx.Column(name)
                        else:
                            raise SqlError(
                                f"ORDER BY expression {e!s} not in output"
                            )
                nf = oi.nulls_first if oi.nulls_first is not None else False
                sort_exprs.append(lx.SortExpr(e, oi.ascending, nf))
            if hidden:
                visible = [f.name for f in out_schema]
                plan = lp.Projection(base_proj.input, list(base_proj.exprs) + hidden)
                plan = lp.Sort(plan, sort_exprs)
                plan = lp.Projection(
                    plan, [lx.Alias(lx.Column(n), n) for n in visible]
                )
            else:
                plan = lp.Sort(plan, sort_exprs)
        if stmt.limit is not None:
            plan = lp.Limit(plan, stmt.limit, stmt.offset)
        return plan

    # -- FROM/WHERE --------------------------------------------------------
    def _plan_from_item(self, item: sa.FromItem) -> List[Tuple[str, lp.LogicalPlan]]:
        """Returns [(alias, plan)] — JoinItems collapse into one entry."""
        if isinstance(item, sa.TableRef):
            src = self.ctx.tables.get(item.name.lower())
            if src is None:
                raise SqlError(f"table {item.name!r} not found")
            alias = (item.alias or item.name).lower()
            scan = lp.TableScan(item.name.lower(), src)
            return [(alias, lp.SubqueryAlias(scan, alias))]
        if isinstance(item, sa.SubqueryRef):
            sub = SelectPlanner(self.ctx).plan(item.stmt)
            return [(item.alias.lower(), lp.SubqueryAlias(sub, item.alias.lower()))]
        if isinstance(item, sa.JoinItem):
            left_rels = self._plan_from_item(item.left)
            right_rels = self._plan_from_item(item.right)
            left = left_rels[0][1] if len(left_rels) == 1 else None
            right = right_rels[0][1] if len(right_rels) == 1 else None
            assert left is not None and right is not None, "nested join lists"
            alias = f"{left_rels[0][0]}+{right_rels[0][0]}"
            if item.join_type == "cross" or item.condition is None:
                return [(alias, lp.CrossJoin(left, right))]
            keys, residual = self._split_join_condition(
                item.condition, left.schema(), right.schema()
            )
            jt = {
                "inner": lp.JoinType.INNER,
                "left": lp.JoinType.LEFT,
                "right": lp.JoinType.RIGHT,
                "full": lp.JoinType.FULL,
            }[item.join_type]
            if jt in (lp.JoinType.LEFT, lp.JoinType.RIGHT, lp.JoinType.FULL):
                # ON-residuals of an outer join must filter the nullable side
                # BEFORE joining (filtering after would turn it inner)
                kept: List[lx.Expr] = []
                for c in residual:
                    cols: List[lx.Column] = []
                    collect_columns(c, cols)
                    if jt == lp.JoinType.LEFT and all(
                        _resolves_in(x, right.schema()) for x in cols
                    ):
                        right = lp.Filter(right, c)
                    elif jt == lp.JoinType.RIGHT and all(
                        _resolves_in(x, left.schema()) for x in cols
                    ):
                        left = lp.Filter(left, c)
                    else:
                        kept.append(c)
                if kept:
                    raise SqlError(
                        f"unsupported ON condition for {jt.value} join: {kept[0]}"
                    )
                residual = []
            if keys:
                join = lp.Join(left, right, keys, jt, conjoin(residual))
            else:
                if jt != lp.JoinType.INNER:
                    raise SqlError("non-equi outer joins not supported")
                j: lp.LogicalPlan = lp.CrossJoin(left, right)
                cond = conjoin(residual)
                join = lp.Filter(j, cond) if cond is not None else j
            return [(alias, join)]
        raise SqlError(f"unsupported FROM item {item!r}")

    def _split_join_condition(
        self, cond: lx.Expr, lschema: pa.Schema, rschema: pa.Schema
    ) -> Tuple[List[Tuple[lx.Column, lx.Column]], List[lx.Expr]]:
        keys: List[Tuple[lx.Column, lx.Column]] = []
        residual: List[lx.Expr] = []
        for c in split_conjuncts(cond):
            if (
                isinstance(c, lx.BinaryExpr)
                and c.op == "eq"
                and isinstance(c.left, lx.Column)
                and isinstance(c.right, lx.Column)
            ):
                if _resolves_in(c.left, lschema) and _resolves_in(c.right, rschema):
                    keys.append((c.left, c.right))
                    continue
                if _resolves_in(c.right, lschema) and _resolves_in(c.left, rschema):
                    keys.append((c.right, c.left))
                    continue
            residual.append(c)
        return keys, residual

    def _plan_from_where(self, stmt: sa.SelectStmt) -> lp.LogicalPlan:
        if not stmt.from_items:
            plan: lp.LogicalPlan = lp.EmptyRelation(produce_one_row=True)
            if stmt.where is not None:
                plan = lp.Filter(plan, stmt.where)
            return plan

        rels: List[Tuple[str, lp.LogicalPlan]] = []
        for item in stmt.from_items:
            rels.extend(self._plan_from_item(item))

        conjuncts = [
            f for c in split_conjuncts(stmt.where) for f in factor_or_common(c)
        ]
        subquery_conjuncts = [c for c in conjuncts if contains_subquery(c)]
        plain = [c for c in conjuncts if not contains_subquery(c)]

        # classify plain conjuncts by referenced relations
        rel_schemas = {a: p.schema() for a, p in rels}

        def rels_of(e: lx.Expr) -> Set[str]:
            cols: List[lx.Column] = []
            collect_columns(e, cols)
            out: Set[str] = set()
            for col in cols:
                hits = [a for a, s in rel_schemas.items() if _resolves_in(col, s)]
                if len(hits) == 1:
                    out.add(hits[0])
                elif len(hits) == 0:
                    out.add("?outer")  # may be an outer (correlated) reference
                else:
                    raise SqlError(f"ambiguous column {col.flat_name()!r}")
            return out

        single_rel: Dict[str, List[lx.Expr]] = {a: [] for a, _ in rels}
        equi_edges: List[Tuple[str, str, lx.Column, lx.Column]] = []
        post_join: List[lx.Expr] = []

        for c in plain:
            refs = rels_of(c)
            if "?outer" in refs:
                post_join.append(c)  # resolved later against joined/outer schema
                continue
            if len(refs) == 1:
                single_rel[next(iter(refs))].append(c)
                continue
            if (
                len(refs) == 2
                and isinstance(c, lx.BinaryExpr)
                and c.op == "eq"
                and isinstance(c.left, lx.Column)
                and isinstance(c.right, lx.Column)
            ):
                la = next(a for a, s in rel_schemas.items() if _resolves_in(c.left, s))
                ra = next(a for a, s in rel_schemas.items() if _resolves_in(c.right, s))
                equi_edges.append((la, ra, c.left, c.right))
                continue
            post_join.append(c)

        # push single-relation predicates down
        planned: Dict[str, lp.LogicalPlan] = {}
        for a, p in rels:
            preds = single_rel[a]
            planned[a] = lp.Filter(p, conjoin(preds)) if preds else p

        # greedy join-graph ordering
        order = [a for a, _ in rels]
        joined = {order[0]}
        plan = planned[order[0]]
        remaining = set(order[1:])
        edges = list(equi_edges)
        while remaining:
            # find an edge between joined set and one remaining relation
            pick = None
            for a in order:
                if a not in remaining:
                    continue
                usable = [
                    (la, ra, lc, rc)
                    for (la, ra, lc, rc) in edges
                    if (la in joined and ra == a) or (ra in joined and la == a)
                ]
                if usable:
                    pick = (a, usable)
                    break
            if pick is None:
                # no connecting edge: cross join the next relation
                a = next(x for x in order if x in remaining)
                plan = lp.CrossJoin(plan, planned[a])
                joined.add(a)
                remaining.discard(a)
                continue
            a, usable = pick
            keys = []
            for (la, ra, lc, rc) in usable:
                if la in joined:
                    keys.append((lc, rc))
                else:
                    keys.append((rc, lc))
                edges.remove((la, ra, lc, rc))
            plan = lp.Join(plan, planned[a], keys, lp.JoinType.INNER)
            joined.add(a)
            remaining.discard(a)

        # remaining equi edges between already-joined rels -> post filters
        for (la, ra, lc, rc) in edges:
            post_join.append(lx.BinaryExpr(lc, "eq", rc))

        if post_join:
            plan = lp.Filter(plan, conjoin(post_join))

        # subquery conjuncts (decorrelation)
        for c in subquery_conjuncts:
            plan = self._apply_subquery_conjunct(plan, c)

        return plan

    # -- GROUP BY / aggregates ---------------------------------------------
    def _resolve_group_by(
        self, group_by: List[lx.Expr], select_exprs: List[lx.Expr]
    ) -> List[lx.Expr]:
        out = []
        for g in group_by:
            if isinstance(g, lx.Literal) and isinstance(g.value, int):
                idx = g.value - 1
                if not (0 <= idx < len(select_exprs)):
                    raise SqlError(f"GROUP BY position {g.value} out of range")
                e = select_exprs[idx]
                if isinstance(e, lx.Alias):
                    e = e.expr
                out.append(e)
            else:
                out.append(g)
        return out

    def _plan_aggregate(
        self,
        plan: lp.LogicalPlan,
        group_exprs: List[lx.Expr],
        aggs: List[lx.AggregateExpr],
    ) -> Tuple[lp.LogicalPlan, Dict[str, lx.Expr]]:
        agg_plan = lp.Aggregate(plan, group_exprs, list(aggs))
        mapping: Dict[str, lx.Expr] = {}
        for g in group_exprs:
            mapping[str(g)] = lx.Column(g.output_name())
        for a in aggs:
            mapping[str(a)] = lx.Column(a.output_name())
        return agg_plan, mapping

    # -- subqueries --------------------------------------------------------
    def _plan_subquery(
        self, stmt: sa.SelectStmt, outer_schema: pa.Schema
    ) -> Tuple[
        lp.LogicalPlan,
        List[Tuple[lx.Column, lx.Column]],
        List[lx.Expr],
    ]:
        """Plan a subquery's FROM/WHERE, extracting correlation predicates.

        Returns (inner joined+filtered plan, [(outer_col, inner_col)]
        correlation equi keys, residual correlated predicates referencing
        both scopes)."""
        inner_planner = SelectPlanner(self.ctx)
        # plan FROM items
        rels: List[Tuple[str, lp.LogicalPlan]] = []
        for item in stmt.from_items:
            rels.extend(inner_planner._plan_from_item(item))
        rel_schemas = {a: p.schema() for a, p in rels}

        def inner_resolves(col: lx.Column) -> bool:
            return any(_resolves_in(col, s) for s in rel_schemas.values())

        conjuncts = split_conjuncts(stmt.where)
        corr_keys: List[Tuple[lx.Column, lx.Column]] = []
        residuals: List[lx.Expr] = []
        inner_conjuncts: List[lx.Expr] = []
        nested_subq: List[lx.Expr] = []
        for c in conjuncts:
            if contains_subquery(c):
                nested_subq.append(c)
                continue
            cols: List[lx.Column] = []
            collect_columns(c, cols)
            outer_cols = [
                col for col in cols
                if not inner_resolves(col) and _resolves_in(col, outer_schema)
            ]
            if not outer_cols:
                inner_conjuncts.append(c)
                continue
            # correlated equi predicate inner_col = outer_col -> join key
            if (
                isinstance(c, lx.BinaryExpr)
                and c.op == "eq"
                and isinstance(c.left, lx.Column)
                and isinstance(c.right, lx.Column)
            ):
                if inner_resolves(c.left) and not inner_resolves(c.right):
                    corr_keys.append((c.right, c.left))
                    continue
                if inner_resolves(c.right) and not inner_resolves(c.left):
                    corr_keys.append((c.left, c.right))
                    continue
            # other correlated predicate -> residual join filter
            residuals.append(c)

        # build inner join tree with the non-correlated conjuncts
        inner_stmt = sa.SelectStmt(
            projections=[("*", None)],
            from_items=stmt.from_items,
            where=conjoin(inner_conjuncts),
        )
        inner_plan = inner_planner._plan_from_where(inner_stmt)
        for c in nested_subq:
            inner_plan = inner_planner._apply_subquery_conjunct(inner_plan, c)
        return inner_plan, corr_keys, residuals

    def _subquery_is_correlated(
        self, stmt: sa.SelectStmt, outer_schema: pa.Schema
    ) -> bool:
        """Check whether any WHERE conjunct references an outer column."""
        inner_planner = SelectPlanner(self.ctx)
        rels: List[Tuple[str, lp.LogicalPlan]] = []
        for item in stmt.from_items:
            rels.extend(inner_planner._plan_from_item(item))
        rel_schemas = [p.schema() for _a, p in rels]
        for c in split_conjuncts(stmt.where):
            if contains_subquery(c):
                continue
            cols: List[lx.Column] = []
            collect_columns(c, cols)
            for col in cols:
                if not any(_resolves_in(col, s) for s in rel_schemas) and _resolves_in(
                    col, outer_schema
                ):
                    return True
        return False

    def _apply_subquery_conjunct(
        self, plan: lp.LogicalPlan, conjunct: lx.Expr
    ) -> lp.LogicalPlan:
        outer_schema = plan.schema()

        # EXISTS / NOT EXISTS
        if isinstance(conjunct, lx.Exists) or (
            isinstance(conjunct, lx.Not) and isinstance(conjunct.expr, lx.Exists)
        ):
            node = conjunct if isinstance(conjunct, lx.Exists) else conjunct.expr
            negated = isinstance(conjunct, lx.Not) or node.negated
            if not self._subquery_is_correlated(node.stmt, outer_schema):
                # uncorrelated EXISTS gates every outer row on whether the
                # subquery yields any row at all: cross-join a one-row
                # count aggregate over LIMIT 1 (one row decides the truth),
                # filter on it, project it back away
                try:
                    sub = SelectPlanner(self.ctx).plan(node.stmt)
                except SchemaError:
                    # correlation the WHERE-conjunct scan missed (e.g. via
                    # the SELECT list): fall through to the correlated path
                    sub = None
                if sub is not None:
                    alias = f"__exists_{id(node)}"
                    ncol_name = "__exists_n"
                    probe = lp.Aggregate(
                        lp.Limit(sub, 1),
                        [],
                        [lx.Alias(
                            lx.AggregateExpr("count", lx.Wildcard(), False),
                            ncol_name,
                        )],
                    )
                    probe = lp.SubqueryAlias(probe, alias)
                    joined = lp.CrossJoin(plan, probe)
                    ncol = lx.Column(ncol_name, alias)
                    zero = lx.Literal(0, pa.int64())
                    cond = lx.BinaryExpr(ncol, "eq" if negated else "gt", zero)
                    filtered = lp.Filter(joined, cond)
                    # alias kept columns back to their FLAT names; the bare
                    # Column resolves each flat name EXACTLY (outer schema
                    # names are unique), so a legitimate dot inside an
                    # output name is not misread as qualifier.column
                    keep = [
                        lx.Alias(lx.Column(f.name), f.name)
                        for f in outer_schema
                    ]
                    return lp.Projection(filtered, keep)
            inner_plan, corr_keys, residuals = self._plan_subquery(
                node.stmt, outer_schema
            )
            if not corr_keys:
                raise SqlError(
                    "EXISTS subquery correlation must appear as equality "
                    "conjuncts in the subquery's WHERE clause"
                )
            on = [(o, i) for o, i in corr_keys]
            jt = lp.JoinType.ANTI if negated else lp.JoinType.SEMI
            return lp.Join(plan, inner_plan, on, jt, conjoin(residuals))

        # [NOT] IN (subquery)
        if isinstance(conjunct, lx.InSubquery) or (
            isinstance(conjunct, lx.Not) and isinstance(conjunct.expr, lx.InSubquery)
        ):
            node = conjunct if isinstance(conjunct, lx.InSubquery) else conjunct.expr
            negated = isinstance(conjunct, lx.Not) or node.negated
            if not isinstance(node.expr, lx.Column):
                raise SqlError("IN (subquery) requires a column on the left")
            jt = lp.JoinType.ANTI if negated else lp.JoinType.SEMI
            if not self._subquery_is_correlated(node.stmt, outer_schema):
                # full sub-select planning (aggregates/HAVING/DISTINCT ok);
                # wrap in a unique alias so inner names can't collide with
                # outer scope
                sub = SelectPlanner(self.ctx).plan(node.stmt)
                alias = f"__in_{id(node)}"
                sub = lp.SubqueryAlias(sub, alias)
                in_key = lx.Column(sub.schema().names[0].split(".")[-1], alias)
                on = [(node.expr, in_key)]
                if negated:
                    # SQL three-valued NOT IN: any NULL in the subquery result
                    # means no row qualifies, and a NULL probe value never
                    # qualifies either
                    return self._not_in_null_aware(plan, node.expr, sub, in_key, on)
                return lp.Join(plan, sub, on, jt)
            inner_plan, corr_keys, residuals = self._plan_subquery(
                node.stmt, outer_schema
            )
            # project the IN value under a unique alias (bare select-list names
            # can collide with the kept qualified columns), keeping original
            # columns for correlation keys / residuals
            proj0, _al = node.stmt.projections[0]
            if isinstance(proj0, str):
                raise SqlError("IN (subquery) requires an explicit select column")
            in_alias = f"__in_val_{id(node)}"
            keep = [
                lx.Column(f.name.split(".")[-1], f.name.split(".")[0] if "." in f.name else None)
                for f in inner_plan.schema()
            ]
            inner_full = lp.Projection(
                inner_plan, [lx.Alias(proj0, in_alias)] + keep
            )
            on = [(node.expr, lx.Column(in_alias))]
            for o, i in corr_keys:
                on.append((o, i))
            if negated and residuals:
                raise SqlError("correlated NOT IN with residual predicates not supported")
            return lp.Join(plan, inner_full, on, jt, conjoin(residuals))

        # comparison with scalar subquery
        subqs: List[lx.ScalarSubquery] = []

        def walk(e: lx.Expr) -> None:
            if isinstance(e, lx.ScalarSubquery):
                subqs.append(e)
                return
            for ch in _expr_children_full(e):
                walk(ch)

        walk(conjunct)
        if not subqs:
            raise SqlError(f"unhandled subquery conjunct: {conjunct}")

        mapping: Dict[str, lx.Expr] = {}
        for sq in subqs:
            plan, ref = self._join_scalar_subquery(plan, sq, outer_schema)
            mapping[str(sq)] = ref
        rewritten = rewrite_expr(conjunct, mapping)
        return lp.Filter(plan, rewritten)

    def _not_in_null_aware(
        self,
        plan: lp.LogicalPlan,
        probe_expr: lx.Column,
        sub: lp.LogicalPlan,
        in_key: lx.Column,
        on: List[Tuple[lx.Column, lx.Column]],
    ) -> lp.LogicalPlan:
        """NOT IN with SQL three-valued semantics: anti-join against non-null
        inner values, drop null probe values, and produce no rows at all if
        the subquery result contains any NULL."""
        original_fields = list(plan.schema().names)
        nonnull_sub = lp.Filter(sub, lx.IsNotNull(in_key))
        out: lp.LogicalPlan = lp.Join(plan, nonnull_sub, on, lp.JoinType.ANTI)
        out = lp.Filter(out, lx.IsNotNull(probe_expr))
        # null guard: cross join a 1-row count of NULL inner values, require 0
        nullcnt = f"__in_nullcnt_{id(sub)}"
        nulls_agg = lp.Aggregate(
            lp.Filter(sub, lx.IsNull(in_key)),
            [],
            [lx.Alias(lx.AggregateExpr("count", lx.Wildcard()), nullcnt)],
        )
        out = lp.CrossJoin(out, nulls_agg)
        out = lp.Filter(out, lx.BinaryExpr(lx.Column(nullcnt), "eq", lx.Literal(0)))
        # strip the helper column so downstream SELECT * stays clean
        restore = [
            lx.Alias(
                lx.Column(n.split(".")[-1], n.split(".")[0] if "." in n else None), n
            )
            for n in original_fields
        ]
        return lp.Projection(out, restore)

    def _join_scalar_subquery(
        self, plan: lp.LogicalPlan, sq: lx.ScalarSubquery, outer_schema: pa.Schema
    ) -> Tuple[lp.LogicalPlan, lx.Expr]:
        stmt: sa.SelectStmt = sq.stmt  # type: ignore[attr-defined]
        inner_plan, corr_keys, residuals = self._plan_subquery(stmt, outer_schema)
        if residuals:
            raise SqlError(
                "scalar subquery with non-equi correlated predicates "
                f"not supported: {residuals[0]}"
            )
        # subquery must be a single aggregate projection
        if len(stmt.projections) != 1:
            raise SqlError("scalar subquery must have one projection")
        proj, _alias = stmt.projections[0]
        aggs: List[lx.AggregateExpr] = []
        collect_aggregates(proj, aggs)
        if not aggs:
            raise SqlError("scalar subquery must be an aggregate")
        out_name = f"__sq_{id(sq)}"

        if corr_keys:
            group_cols = [i for (_o, i) in corr_keys]
            # exact_floats: the subquery result is compared against source
            # values (q2: = MIN(ps_supplycost)); f32 device paths decline
            agg = lp.Aggregate(inner_plan, group_cols, list(aggs),
                               exact_floats=True)
            mapping = {str(a): lx.Column(a.output_name()) for a in aggs}
            value = rewrite_expr(proj, mapping)
            # project: correlation keys (renamed uniquely) + value
            key_aliases = []
            proj_exprs: List[lx.Expr] = []
            for k, (o, i) in enumerate(corr_keys):
                kname = f"__sqk_{id(sq)}_{k}"
                proj_exprs.append(lx.Alias(lx.Column(i.name, i.relation), kname))
                key_aliases.append(kname)
            proj_exprs.append(lx.Alias(value, out_name))
            agg_proj = lp.Projection(agg, proj_exprs)
            on = [
                (o, lx.Column(kname)) for (o, _i), kname in zip(corr_keys, key_aliases)
            ]
            # LEFT join: outer rows with an empty group must survive — their
            # aggregate value is NULL (comparisons then drop them, matching
            # SQL), except COUNT whose value over an empty group is 0
            joined = lp.Join(plan, agg_proj, on, lp.JoinType.LEFT)
            ref: lx.Expr = lx.Column(out_name)
            if all(a.fn == "count" for a in aggs):
                ref = lx.ScalarFunction(
                    "coalesce", [lx.Cast(ref, pa.int64()), lx.Literal(0)]
                )
            return joined, ref

        # uncorrelated: single-row aggregate, cross join
        agg = lp.Aggregate(inner_plan, [], list(aggs), exact_floats=True)
        mapping = {str(a): lx.Column(a.output_name()) for a in aggs}
        value = rewrite_expr(proj, mapping)
        agg_proj = lp.Projection(agg, [lx.Alias(value, out_name)])
        joined = lp.CrossJoin(plan, agg_proj)
        return joined, lx.Column(out_name)


def _expr_children_full(e: lx.Expr) -> List[lx.Expr]:
    """children() plus subquery-bearing nodes' wrapped exprs."""
    if isinstance(e, lx.InSubquery):
        return [e.expr]
    return e.children()

"""SQL -> LogicalPlan entry point (frontend lands in the next milestone)."""

from __future__ import annotations

from ballista_tpu.errors import SqlError


def plan_sql(query: str, ctx) -> "LogicalPlan":  # noqa: F821
    raise SqlError("SQL frontend not yet available; use the DataFrame API")

"""Recursive-descent SQL parser covering the TPC-H q1-q22 surface.

Statements: SELECT (joins, subqueries, CASE, EXTRACT, date/interval
arithmetic, EXISTS/IN, UNION), CREATE EXTERNAL TABLE, EXPLAIN.
The reference gets this from DataFusion's sqlparser crate; built natively here.
"""

from __future__ import annotations

import datetime
from typing import List, Optional, Tuple

import pyarrow as pa

from ballista_tpu.errors import SqlError
from ballista_tpu.logical import expr as lx
from ballista_tpu.sql.ast import (
    CreateExternalTableStmt,
    ExplainStmt,
    FromItem,
    IntervalLiteral,
    JoinItem,
    OrderItem,
    SelectStmt,
    SubqueryRef,
    TableRef,
)
from ballista_tpu.sql.lexer import Token, tokenize

# keywords that stay legal as identifiers (clause-introducers only; the
# primary-expression and identifier parsers fall back to treating them as
# names). Frame/grouping words are positional: `rows`/`rollup` only act as
# syntax right after ORDER BY exprs / GROUP BY.
NON_RESERVED = {
    "rollup", "cube", "grouping", "sets",
    "rows", "range", "unbounded", "preceding", "following", "current",
}

_CMP_OPS = {"=": "eq", "<>": "neq", "!=": "neq", "<": "lt", "<=": "lteq",
            ">": "gt", ">=": "gteq"}

_TYPE_NAMES = {
    "int": pa.int32(), "integer": pa.int32(), "smallint": pa.int16(),
    "tinyint": pa.int8(), "bigint": pa.int64(),
    "float": pa.float32(), "real": pa.float32(),
    "double": pa.float64(), "decimal": pa.float64(), "numeric": pa.float64(),
    "varchar": pa.string(), "char": pa.string(), "text": pa.string(),
    "string": pa.string(), "boolean": pa.bool_(), "bool": pa.bool_(),
    "date": pa.date32(), "timestamp": pa.timestamp("us"),
}


def parse_type(name: str) -> pa.DataType:
    t = _TYPE_NAMES.get(name.lower())
    if t is None:
        raise SqlError(f"unknown SQL type {name!r}")
    return t


class Parser:
    def __init__(self, sql: str) -> None:
        self.tokens = tokenize(sql)
        self.pos = 0

    # -- token helpers -----------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.pos + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.pos]
        self.pos += 1
        return t

    def at_keyword(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "keyword" and t.value in words

    def eat_keyword(self, *words: str) -> bool:
        if self.at_keyword(*words):
            self.next()
            return True
        return False

    def expect_keyword(self, word: str) -> None:
        if not self.eat_keyword(word):
            t = self.peek()
            raise SqlError(f"expected {word.upper()}, found {t.value!r} at {t.pos}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            t = self.peek()
            raise SqlError(f"expected {op!r}, found {t.value!r} at {t.pos}")

    def expect_ident(self) -> str:
        t = self.peek()
        # allow non-reserved keywords as identifiers where unambiguous
        if self._identish(t):
            self.next()
            return t.value
        raise SqlError(f"expected identifier, found {t.value!r} at {t.pos}")

    @staticmethod
    def _identish(t) -> bool:
        """Identifiers plus non-reserved keywords (words the lexer tokenizes
        as keywords for clause parsing but that remain legal column/table
        names, e.g. a column literally named `cube`)."""
        return t.kind == "ident" or (
            t.kind == "keyword" and t.value in NON_RESERVED
        )

    # -- entry -------------------------------------------------------------
    def parse_statement(self):
        if self.at_keyword("explain"):
            self.next()
            verbose = self.eat_keyword("verbose")
            return ExplainStmt(self.parse_select(), verbose)
        if self.at_keyword("create"):
            return self.parse_create_external_table()
        stmt = self.parse_select()
        self.eat_op(";")
        t = self.peek()
        if t.kind != "eof":
            raise SqlError(f"unexpected trailing input at {t.pos}: {t.value!r}")
        return stmt

    # -- DDL ---------------------------------------------------------------
    def parse_create_external_table(self) -> CreateExternalTableStmt:
        self.expect_keyword("create")
        self.expect_keyword("external")
        self.expect_keyword("table")
        name = self.expect_ident()
        columns: List[Tuple[str, str]] = []
        if self.eat_op("("):
            while True:
                cname = self.expect_ident()
                t = self.peek()
                if t.kind not in ("ident", "keyword"):
                    raise SqlError(f"expected type name at {t.pos}")
                self.next()
                columns.append((cname, t.value))
                # swallow precision args e.g. DECIMAL(12, 2)
                if self.eat_op("("):
                    depth = 1
                    while depth:
                        tt = self.next()
                        if tt.kind == "op" and tt.value == "(":
                            depth += 1
                        elif tt.kind == "op" and tt.value == ")":
                            depth -= 1
                if not self.eat_op(","):
                    break
            self.expect_op(")")
        self.expect_keyword("stored")
        self.expect_keyword("as")
        ft = self.peek()
        self.next()
        file_type = ft.value
        has_header = False
        if self.eat_keyword("with"):
            self.expect_keyword("header")
            self.expect_keyword("row")
            has_header = True
        self.expect_keyword("location")
        loc = self.peek()
        if loc.kind != "string":
            raise SqlError("LOCATION requires a string literal")
        self.next()
        self.eat_op(";")
        return CreateExternalTableStmt(name, columns, file_type, loc.value, has_header)

    # -- SELECT ------------------------------------------------------------
    def parse_select(self) -> SelectStmt:
        stmt = self._parse_select_body()
        while self.at_keyword("union"):
            self.next()
            all_ = self.eat_keyword("all")
            other = self._parse_select_body()
            stmt.union_with.append((other, all_))
        # ORDER BY / LIMIT after unions apply to the whole statement
        self._parse_order_limit(stmt)
        return stmt

    def _parse_select_body(self) -> SelectStmt:
        if self.eat_op("("):
            inner = self.parse_select()
            self.expect_op(")")
            return inner
        self.expect_keyword("select")
        stmt = SelectStmt()
        stmt.distinct = self.eat_keyword("distinct")
        self.eat_keyword("all")
        # projections
        while True:
            if self.at_op("*"):
                self.next()
                stmt.projections.append(("*", None))
            elif (
                self.peek().kind == "ident"
                and self.peek(1).kind == "op" and self.peek(1).value == "."
                and self.peek(2).kind == "op" and self.peek(2).value == "*"
            ):
                rel = self.expect_ident()
                self.next()  # .
                self.next()  # *
                stmt.projections.append((("qualified_star", rel), None))
            else:
                e = self.parse_expr()
                alias = None
                if self.eat_keyword("as"):
                    alias = self._alias_ident()
                elif self.peek().kind == "ident":
                    alias = self.expect_ident()
                stmt.projections.append((e, alias))
            if not self.eat_op(","):
                break
        # FROM
        if self.eat_keyword("from"):
            stmt.from_items.append(self.parse_from_item())
            while self.eat_op(","):
                stmt.from_items.append(self.parse_from_item())
        if self.eat_keyword("where"):
            stmt.where = self.parse_expr()
        if self.eat_keyword("group"):
            self.expect_keyword("by")
            if self.at_keyword("rollup", "cube", "grouping"):
                self._parse_grouping_sets(stmt)
            else:
                while True:
                    stmt.group_by.append(self.parse_expr())
                    if not self.eat_op(","):
                        break
        if self.eat_keyword("having"):
            stmt.having = self.parse_expr()
        self._parse_order_limit(stmt)
        return stmt

    def _alias_ident(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            self.next()
            return t.value
        raise SqlError(f"expected alias identifier at {t.pos}")

    def _parse_order_limit(self, stmt: SelectStmt) -> None:
        if self.eat_keyword("order"):
            self.expect_keyword("by")
            while True:
                e = self.parse_expr()
                asc = True
                if self.eat_keyword("desc"):
                    asc = False
                else:
                    self.eat_keyword("asc")
                nulls_first: Optional[bool] = None
                if self.eat_keyword("nulls"):
                    if self.eat_keyword("first"):
                        nulls_first = True
                    else:
                        self.expect_keyword("last")
                        nulls_first = False
                stmt.order_by.append(OrderItem(e, asc, nulls_first))
                if not self.eat_op(","):
                    break
        if self.eat_keyword("limit"):
            t = self.next()
            if t.kind != "number":
                raise SqlError("LIMIT requires a number")
            stmt.limit = int(t.value)
        if self.eat_keyword("offset"):
            t = self.next()
            if t.kind != "number":
                raise SqlError("OFFSET requires a number")
            stmt.offset = int(t.value)

    # -- FROM --------------------------------------------------------------
    def parse_from_item(self) -> FromItem:
        item = self._parse_table_factor()
        while True:
            if self.at_keyword("join", "inner", "left", "right", "full", "cross"):
                jtype = "inner"
                if self.eat_keyword("cross"):
                    jtype = "cross"
                elif self.eat_keyword("inner"):
                    pass
                elif self.eat_keyword("left"):
                    jtype = "left"
                    self.eat_keyword("outer")
                elif self.eat_keyword("right"):
                    jtype = "right"
                    self.eat_keyword("outer")
                elif self.eat_keyword("full"):
                    jtype = "full"
                    self.eat_keyword("outer")
                self.expect_keyword("join")
                right = self._parse_table_factor()
                cond = None
                if jtype != "cross":
                    self.expect_keyword("on")
                    cond = self.parse_expr()
                item = JoinItem(item, right, jtype, cond)
            else:
                return item

    def _parse_table_factor(self) -> FromItem:
        if self.eat_op("("):
            if self.at_keyword("select"):
                sub = self.parse_select()
                self.expect_op(")")
                self.eat_keyword("as")
                alias = self._alias_ident()
                return SubqueryRef(sub, alias)
            inner = self.parse_from_item()
            self.expect_op(")")
            return inner
        name = self.expect_ident()
        alias = None
        if self.eat_keyword("as"):
            alias = self._alias_ident()
        elif self.peek().kind == "ident":
            alias = self.expect_ident()
        return TableRef(name, alias)

    # -- expressions -------------------------------------------------------
    def parse_expr(self) -> lx.Expr:
        return self._parse_or()

    def _parse_or(self) -> lx.Expr:
        left = self._parse_and()
        while self.eat_keyword("or"):
            left = lx.BinaryExpr(left, "or", self._parse_and())
        return left

    def _parse_and(self) -> lx.Expr:
        left = self._parse_not()
        while self.eat_keyword("and"):
            left = lx.BinaryExpr(left, "and", self._parse_not())
        return left

    def _parse_not(self) -> lx.Expr:
        if self.eat_keyword("not"):
            return lx.Not(self._parse_not())
        return self._parse_comparison()

    def _parse_comparison(self) -> lx.Expr:
        left = self._parse_additive()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in _CMP_OPS:
                self.next()
                # comparison vs subquery: = (select ...) treated as scalar
                right = self._parse_additive()
                left = lx.BinaryExpr(left, _CMP_OPS[t.value], right)
                continue
            negated = False
            save = self.pos
            if self.eat_keyword("not"):
                negated = True
            if self.eat_keyword("between"):
                low = self._parse_additive()
                self.expect_keyword("and")
                high = self._parse_additive()
                left = lx.Between(left, low, high, negated)
                continue
            if self.eat_keyword("in"):
                self.expect_op("(")
                if self.at_keyword("select"):
                    sub = self.parse_select()
                    self.expect_op(")")
                    node = lx.InSubquery(left, None, negated)  # type: ignore[arg-type]
                    node.stmt = sub  # planned later
                    left = node
                else:
                    values = [self.parse_expr()]
                    while self.eat_op(","):
                        values.append(self.parse_expr())
                    self.expect_op(")")
                    left = lx.InList(left, values, negated)
                continue
            if self.eat_keyword("like"):
                pattern = self._parse_additive()
                escape = None
                if self.eat_keyword("escape"):
                    esc = self.next()
                    escape = esc.value
                if escape is not None:
                    left = lx.Like(left, pattern, negated, escape)
                else:
                    left = lx.BinaryExpr(left, "not_like" if negated else "like", pattern)
                continue
            if negated:
                self.pos = save
                break
            if self.eat_keyword("is"):
                neg = self.eat_keyword("not")
                self.expect_keyword("null")
                left = lx.IsNotNull(left) if neg else lx.IsNull(left)
                continue
            break
        return left

    def _parse_additive(self) -> lx.Expr:
        left = self._parse_multiplicative()
        while True:
            if self.at_op("+", "-", "||"):
                op = self.next().value
                right = self._parse_multiplicative()
                if op == "||":
                    left = lx.ScalarFunction("concat", [left, right])
                else:
                    left = self._fold_date_arith(left, "plus" if op == "+" else "minus", right)
            else:
                return left

    def _fold_date_arith(self, left: lx.Expr, op: str, right: lx.Expr) -> lx.Expr:
        """Fold  date 'lit' +/- interval  at parse time (TPC-H pattern)."""
        if isinstance(right, IntervalLiteral):
            if isinstance(left, lx.Literal) and isinstance(left.value, datetime.date):
                sign = 1 if op == "plus" else -1
                d = _add_interval(left.value, sign * right.months, sign * right.days)
                return lx.Literal(d, pa.date32())
            raise SqlError("interval arithmetic requires a date literal operand")
        if isinstance(left, IntervalLiteral):
            raise SqlError("interval must be the right operand")
        return lx.BinaryExpr(left, op, right)

    def _parse_multiplicative(self) -> lx.Expr:
        left = self._parse_unary()
        while True:
            if self.at_op("*", "/", "%"):
                op = self.next().value
                right = self._parse_unary()
                left = lx.BinaryExpr(
                    left, {"*": "multiply", "/": "divide", "%": "modulo"}[op], right
                )
            else:
                return left

    def _parse_unary(self) -> lx.Expr:
        if self.eat_op("-"):
            e = self._parse_unary()
            if isinstance(e, lx.Literal) and isinstance(e.value, (int, float)):
                return lx.Literal(-e.value, e.dtype)
            return lx.Negative(e)
        if self.eat_op("+"):
            return self._parse_unary()
        return self._parse_primary()

    def _parse_primary(self) -> lx.Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            text = t.value
            if "." in text or "e" in text.lower():
                return lx.Literal(float(text), pa.float64())
            return lx.Literal(int(text), pa.int64())
        if t.kind == "string":
            self.next()
            return lx.Literal(t.value, pa.string())
        if self.at_keyword("null"):
            self.next()
            return lx.Literal(None, pa.null())
        if self.at_keyword("true"):
            self.next()
            return lx.Literal(True, pa.bool_())
        if self.at_keyword("false"):
            self.next()
            return lx.Literal(False, pa.bool_())
        if self.at_keyword("date"):
            self.next()
            s = self.next()
            if s.kind != "string":
                raise SqlError("DATE requires a string literal")
            return lx.Literal(datetime.date.fromisoformat(s.value), pa.date32())
        if self.at_keyword("timestamp"):
            self.next()
            s = self.next()
            if s.kind != "string":
                raise SqlError("TIMESTAMP requires a string literal")
            return lx.Literal(
                datetime.datetime.fromisoformat(s.value), pa.timestamp("us")
            )
        if self.at_keyword("interval"):
            self.next()
            s = self.next()
            if s.kind != "string":
                raise SqlError("INTERVAL requires a string literal")
            unit_t = self.peek()
            if unit_t.kind not in ("ident", "keyword"):
                raise SqlError("INTERVAL requires a unit")
            self.next()
            unit = unit_t.value.lower().rstrip("s")
            qty = int(s.value.strip().split()[0])
            if unit == "year":
                return IntervalLiteral(12 * qty, 0)
            if unit == "month":
                return IntervalLiteral(qty, 0)
            if unit == "day":
                return IntervalLiteral(0, qty)
            if unit == "week":
                return IntervalLiteral(0, 7 * qty)
            raise SqlError(f"unsupported interval unit {unit!r}")
        if self.at_keyword("case"):
            return self._parse_case()
        if self.at_keyword("cast"):
            self.next()
            self.expect_op("(")
            inner = self.parse_expr()
            self.expect_keyword("as")
            tt = self.peek()
            if tt.kind not in ("ident", "keyword"):
                raise SqlError(f"expected type name at {tt.pos}")
            self.next()
            if self.eat_op("("):
                depth = 1
                while depth:
                    x = self.next()
                    if x.kind == "op" and x.value == "(":
                        depth += 1
                    elif x.kind == "op" and x.value == ")":
                        depth -= 1
            self.expect_op(")")
            return lx.Cast(inner, parse_type(tt.value))
        if self.at_keyword("extract"):
            self.next()
            self.expect_op("(")
            part = self.next()
            self.expect_keyword("from")
            inner = self.parse_expr()
            self.expect_op(")")
            return lx.ScalarFunction("extract", [lx.Literal(part.value), inner])
        if self.at_keyword("substring"):
            self.next()
            self.expect_op("(")
            inner = self.parse_expr()
            if self.eat_keyword("from"):
                start = self.parse_expr()
                length = None
                if self.eat_keyword("for"):
                    length = self.parse_expr()
            else:
                self.expect_op(",")
                start = self.parse_expr()
                length = None
                if self.eat_op(","):
                    length = self.parse_expr()
            self.expect_op(")")
            args = [inner, start] + ([length] if length is not None else [])
            return lx.ScalarFunction("substring", args)
        if self.at_keyword("exists"):
            self.next()
            self.expect_op("(")
            sub = self.parse_select()
            self.expect_op(")")
            node = lx.Exists(None, False)  # type: ignore[arg-type]
            node.stmt = sub
            return node
        if self.eat_op("("):
            if self.at_keyword("select"):
                sub = self.parse_select()
                self.expect_op(")")
                node = lx.ScalarSubquery(None)  # type: ignore[arg-type]
                node.stmt = sub
                return node
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if self._identish(t):
            name = self.expect_ident()
            # function call?
            if self.at_op("("):
                return self._parse_function(name)
            # qualified column a.b
            if self.at_op(".") and self._identish(self.peek(1)):
                self.next()
                col2 = self.expect_ident()
                return lx.Column(col2.lower(), name.lower())
            return lx.Column(name.lower())
        raise SqlError(f"unexpected token {t.value!r} at {t.pos}")

    def _parse_function(self, name: str) -> lx.Expr:
        self.expect_op("(")
        fname = name.lower()
        distinct = False
        args: List[lx.Expr] = []
        if self.at_op("*"):
            self.next()
            self.expect_op(")")
            if fname == "count":
                return lx.AggregateExpr("count", lx.Wildcard())
            raise SqlError(f"{name}(*) not supported")
        if not self.at_op(")"):
            if self.eat_keyword("distinct"):
                distinct = True
            args.append(self.parse_expr())
            while self.eat_op(","):
                args.append(self.parse_expr())
        self.expect_op(")")
        if self.at_keyword("over"):
            return self._parse_over(fname, args, distinct)
        if fname in lx.AGGREGATE_FUNCTIONS:
            if len(args) != 1:
                raise SqlError(f"{name} takes one argument")
            return lx.AggregateExpr(fname, args[0], distinct)
        if fname in ("row_number", "rank", "dense_rank"):
            raise SqlError(f"{name} requires an OVER clause")
        if distinct:
            raise SqlError("DISTINCT only valid in aggregates")
        return lx.ScalarFunction(fname, args)

    def _parse_over(self, fname, args, distinct):
        if distinct:
            raise SqlError("DISTINCT not supported in window functions")
        self.expect_keyword("over")
        self.expect_op("(")
        partition_by = []
        order_by = []
        if self.eat_keyword("partition"):
            self.expect_keyword("by")
            partition_by.append(self.parse_expr())
            while self.eat_op(","):
                partition_by.append(self.parse_expr())
        if self.eat_keyword("order"):
            self.expect_keyword("by")
            while True:
                e = self.parse_expr()
                asc = True
                if self.eat_keyword("desc"):
                    asc = False
                else:
                    self.eat_keyword("asc")
                order_by.append(lx.SortExpr(e, asc, False))
                if not self.eat_op(","):
                    break
        frame = None
        if self.eat_keyword("rows"):
            frame = self._parse_frame("rows")
        elif self.eat_keyword("range"):
            frame = self._parse_frame("range")
        self.expect_op(")")
        arg = args[0] if args else None
        return lx.WindowExpr(fname, arg, partition_by, order_by, frame)

    def _parse_grouping_sets(self, stmt) -> None:
        """GROUP BY ROLLUP(a, b) | CUBE(a, b) | GROUPING SETS ((a, b), (a), ())
        — lowered to explicit index sets over a shared key list."""

        def key_index(e) -> int:
            s = str(e)
            for i, g in enumerate(stmt.group_by):
                if str(g) == s:
                    return i
            stmt.group_by.append(e)
            return len(stmt.group_by) - 1

        if self.eat_keyword("rollup"):
            self.expect_op("(")
            idxs = [key_index(self.parse_expr())]
            while self.eat_op(","):
                idxs.append(key_index(self.parse_expr()))
            self.expect_op(")")
            stmt.grouping_sets = [idxs[:k] for k in range(len(idxs), -1, -1)]
        elif self.eat_keyword("cube"):
            self.expect_op("(")
            idxs = [key_index(self.parse_expr())]
            while self.eat_op(","):
                idxs.append(key_index(self.parse_expr()))
            self.expect_op(")")
            if len(idxs) > 6:
                raise SqlError("CUBE supports at most 6 keys (2^k grouping sets)")
            sets = []
            for mask in range(1 << len(idxs)):
                sets.append([idxs[i] for i in range(len(idxs)) if mask & (1 << i)])
            # conventional order: most-detailed first
            stmt.grouping_sets = sorted(sets, key=len, reverse=True)
        else:
            self.expect_keyword("grouping")
            self.expect_keyword("sets")
            self.expect_op("(")
            stmt.grouping_sets = []
            while True:
                self.expect_op("(")
                one: list = []
                if not self.eat_op(")"):
                    one.append(key_index(self.parse_expr()))
                    while self.eat_op(","):
                        one.append(key_index(self.parse_expr()))
                    self.expect_op(")")
                stmt.grouping_sets.append(one)
                if not self.eat_op(","):
                    break
            self.expect_op(")")

    def _parse_frame(self, mode: str):
        """ROWS|RANGE BETWEEN <bound> AND <bound> | ROWS|RANGE <bound>.
        ROWS offsets are integer row counts; RANGE offsets are numeric
        order-key value deltas."""

        def bound(is_start: bool):
            if self.eat_keyword("unbounded"):
                if self.eat_keyword("preceding"):
                    return None if is_start else ("lo",)
                self.expect_keyword("following")
                return ("hi",) if is_start else None
            if self.eat_keyword("current"):
                self.expect_keyword("row")
                return 0
            k = self.parse_expr()
            ok = isinstance(k, lx.Literal) and (
                isinstance(k.value, int)
                if mode == "rows"
                else isinstance(k.value, (int, float))
            )
            if not ok:
                kind = "an integer" if mode == "rows" else "a numeric"
                raise SqlError(f"{mode.upper()} frame offset must be {kind} literal")
            if self.eat_keyword("preceding"):
                return -k.value
            self.expect_keyword("following")
            return k.value

        if self.eat_keyword("between"):
            start = bound(True)
            self.expect_keyword("and")
            end = bound(False)
        else:
            start = bound(True)
            end = 0  # shorthand: <x> PRECEDING == .. AND CURRENT ROW
        if start == ("hi",) or end == ("lo",):
            raise SqlError("invalid window frame bounds")
        return (mode, start, end)

    def _parse_case(self) -> lx.Expr:
        self.expect_keyword("case")
        base = None
        if not self.at_keyword("when"):
            base = self.parse_expr()
        when_then = []
        while self.eat_keyword("when"):
            w = self.parse_expr()
            self.expect_keyword("then")
            t = self.parse_expr()
            when_then.append((w, t))
        else_expr = None
        if self.eat_keyword("else"):
            else_expr = self.parse_expr()
        self.expect_keyword("end")
        return lx.Case(base, when_then, else_expr)


def _add_interval(d: datetime.date, months: int, days: int) -> datetime.date:
    y = d.year
    m = d.month + months
    y += (m - 1) // 12
    m = (m - 1) % 12 + 1
    day = d.day
    while True:  # clamp day to month length (e.g. Jan 31 + 1 month -> Feb 28)
        try:
            base = datetime.date(y, m, day)
            break
        except ValueError:
            day -= 1
    return base + datetime.timedelta(days=days)


def parse_sql(sql: str):
    return Parser(sql).parse_statement()

"""Logical plan / expression <-> protobuf.

The bidirectional converter pair the reference keeps in
rust/core/src/serde/logical_plan/{to,from}_proto.rs; roundtrip tests mirror
its largest test asset (serde/logical_plan/mod.rs:36-920).
"""

from __future__ import annotations

import datetime
from typing import Any, List

import pyarrow as pa

from ballista_tpu.datasource import (
    CsvTableSource,
    MemoryTableSource,
    ParquetTableSource,
    TableSource,
)
from ballista_tpu.errors import SerdeError
from ballista_tpu.logical import expr as lx
from ballista_tpu.logical import plan as lp
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.serde.arrow import (
    batches_from_ipc,
    batches_to_ipc,
    dtype_from_ipc,
    dtype_to_ipc,
    schema_from_ipc,
    schema_to_ipc,
)

# ---------------------------------------------------------------------------
# scalar values
# ---------------------------------------------------------------------------


def scalar_to_proto(value: Any, dtype: pa.DataType) -> pb.ScalarValue:
    out = pb.ScalarValue(type_ipc=dtype_to_ipc(dtype))
    if value is None:
        out.null_value = True
    elif isinstance(value, bool):
        out.bool_value = value
    elif isinstance(value, int):
        out.int64_value = value
    elif isinstance(value, float):
        out.float64_value = value
    elif isinstance(value, str):
        out.utf8_value = value
    elif isinstance(value, bytes):
        out.binary_value = value
    elif isinstance(value, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1)
        out.ts_micros_value = int((value - epoch).total_seconds() * 1_000_000)
    elif isinstance(value, datetime.date):
        out.date32_value = (value - datetime.date(1970, 1, 1)).days
    else:
        raise SerdeError(f"unsupported scalar {value!r}")
    return out


def scalar_from_proto(s: pb.ScalarValue):
    dtype = dtype_from_ipc(s.type_ipc)
    which = s.WhichOneof("value")
    if which == "null_value":
        return None, dtype
    if which == "bool_value":
        return s.bool_value, dtype
    if which == "int64_value":
        return s.int64_value, dtype
    if which == "float64_value":
        return s.float64_value, dtype
    if which == "utf8_value":
        return s.utf8_value, dtype
    if which == "binary_value":
        return s.binary_value, dtype
    if which == "date32_value":
        return datetime.date(1970, 1, 1) + datetime.timedelta(days=s.date32_value), dtype
    if which == "ts_micros_value":
        return (
            datetime.datetime(1970, 1, 1)
            + datetime.timedelta(microseconds=s.ts_micros_value)
        ), dtype
    raise SerdeError(f"empty scalar value {s}")


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------


def frame_to_proto(msg: "pb.WindowFrameNode", frame) -> None:
    """One encode/decode pair for WindowFrameNode, shared by the logical and
    physical serde (the frame tuple semantics live in lx.WindowExpr)."""
    mode, start, end = frame
    msg.SetInParent()
    msg.range_mode = mode == "range"
    if start is None:
        msg.start_unbounded = True
    else:
        msg.start_value = start
    if end is None:
        msg.end_unbounded = True
    else:
        msg.end_value = end


def frame_from_proto(msg: "pb.WindowFrameNode"):
    mode = "range" if msg.range_mode else "rows"

    def bound(unbounded: bool, v: float):
        if unbounded:
            return None
        # ROWS offsets are row counts: restore int (exact in double)
        return v if mode == "range" else int(v)

    return (
        mode,
        bound(msg.start_unbounded, msg.start_value),
        bound(msg.end_unbounded, msg.end_value),
    )


def expr_to_proto(e: lx.Expr) -> pb.LogicalExprNode:
    n = pb.LogicalExprNode()
    if isinstance(e, lx.Column):
        n.column.name = e.name
        if e.relation:
            n.column.relation = e.relation
    elif isinstance(e, lx.Literal):
        n.literal.CopyFrom(scalar_to_proto(e.value, e.dtype))
    elif isinstance(e, lx.Alias):
        n.alias.expr.CopyFrom(expr_to_proto(e.expr))
        n.alias.name = e.name
    elif isinstance(e, lx.BinaryExpr):
        n.binary_expr.l.CopyFrom(expr_to_proto(e.left))
        n.binary_expr.op = e.op
        n.binary_expr.r.CopyFrom(expr_to_proto(e.right))
    elif isinstance(e, lx.Not):
        n.not_expr.expr.CopyFrom(expr_to_proto(e.expr))
    elif isinstance(e, lx.Negative):
        n.negative.expr.CopyFrom(expr_to_proto(e.expr))
    elif isinstance(e, lx.IsNull):
        n.is_null.expr.CopyFrom(expr_to_proto(e.expr))
        n.is_null.negated = False
    elif isinstance(e, lx.IsNotNull):
        n.is_null.expr.CopyFrom(expr_to_proto(e.expr))
        n.is_null.negated = True
    elif isinstance(e, lx.Between):
        n.between.expr.CopyFrom(expr_to_proto(e.expr))
        n.between.low.CopyFrom(expr_to_proto(e.low))
        n.between.high.CopyFrom(expr_to_proto(e.high))
        n.between.negated = e.negated
    elif isinstance(e, lx.InList):
        n.in_list.expr.CopyFrom(expr_to_proto(e.expr))
        for v in e.values:
            n.in_list.values.append(expr_to_proto(v))
        n.in_list.negated = e.negated
    elif isinstance(e, lx.Like):
        n.like.expr.CopyFrom(expr_to_proto(e.expr))
        n.like.pattern.CopyFrom(expr_to_proto(e.pattern))
        n.like.negated = e.negated
        if e.escape:
            n.like.escape = e.escape
    elif isinstance(e, lx.Case):
        if e.expr is not None:
            n.case_expr.base.CopyFrom(expr_to_proto(e.expr))
        for w, t in e.when_then:
            wt = n.case_expr.when_then.add()
            wt.when.CopyFrom(expr_to_proto(w))
            wt.then.CopyFrom(expr_to_proto(t))
        if e.else_expr is not None:
            n.case_expr.else_expr.CopyFrom(expr_to_proto(e.else_expr))
    elif isinstance(e, lx.TryCast):
        n.try_cast.expr.CopyFrom(expr_to_proto(e.expr))
        n.try_cast.dtype_ipc = dtype_to_ipc(e.dtype)
        n.try_cast.safe = True
    elif isinstance(e, lx.Cast):
        n.cast.expr.CopyFrom(expr_to_proto(e.expr))
        n.cast.dtype_ipc = dtype_to_ipc(e.dtype)
    elif isinstance(e, lx.ScalarFunction):
        n.scalar_function.fn = e.fn
        for a in e.args:
            n.scalar_function.args.append(expr_to_proto(a))
    elif isinstance(e, lx.AggregateExpr):
        n.aggregate_expr.fn = e.fn
        n.aggregate_expr.expr.CopyFrom(expr_to_proto(e.expr))
        n.aggregate_expr.distinct = e.distinct
    elif isinstance(e, lx.WindowExpr):
        n.window_expr.fn = e.fn
        if e.arg is not None:
            n.window_expr.arg.CopyFrom(expr_to_proto(e.arg))
        for pe in e.partition_by:
            n.window_expr.partition_by.append(expr_to_proto(pe))
        for oe in e.order_by:
            n.window_expr.order_by.append(expr_to_proto(oe))
        if e.frame is not None:
            frame_to_proto(n.window_expr.frame, e.frame)
    elif isinstance(e, lx.SortExpr):
        n.sort_expr.expr.CopyFrom(expr_to_proto(e.expr))
        n.sort_expr.ascending = e.ascending
        n.sort_expr.nulls_first = e.nulls_first
    elif isinstance(e, lx.Wildcard):
        n.wildcard.SetInParent()
    else:
        raise SerdeError(f"cannot serialize expr {type(e).__name__}")
    return n


def expr_from_proto(n: pb.LogicalExprNode) -> lx.Expr:
    which = n.WhichOneof("expr_type")
    if which == "column":
        return lx.Column(n.column.name, n.column.relation or None)
    if which == "literal":
        value, dtype = scalar_from_proto(n.literal)
        return lx.Literal(value, dtype)
    if which == "alias":
        return lx.Alias(expr_from_proto(n.alias.expr), n.alias.name)
    if which == "binary_expr":
        return lx.BinaryExpr(
            expr_from_proto(n.binary_expr.l),
            n.binary_expr.op,
            expr_from_proto(n.binary_expr.r),
        )
    if which == "not_expr":
        return lx.Not(expr_from_proto(n.not_expr.expr))
    if which == "negative":
        return lx.Negative(expr_from_proto(n.negative.expr))
    if which == "is_null":
        inner = expr_from_proto(n.is_null.expr)
        return lx.IsNotNull(inner) if n.is_null.negated else lx.IsNull(inner)
    if which == "between":
        return lx.Between(
            expr_from_proto(n.between.expr),
            expr_from_proto(n.between.low),
            expr_from_proto(n.between.high),
            n.between.negated,
        )
    if which == "in_list":
        return lx.InList(
            expr_from_proto(n.in_list.expr),
            [expr_from_proto(v) for v in n.in_list.values],
            n.in_list.negated,
        )
    if which == "like":
        return lx.Like(
            expr_from_proto(n.like.expr),
            expr_from_proto(n.like.pattern),
            n.like.negated,
            n.like.escape or None,
        )
    if which == "case_expr":
        base = (
            expr_from_proto(n.case_expr.base)
            if n.case_expr.HasField("base")
            else None
        )
        else_e = (
            expr_from_proto(n.case_expr.else_expr)
            if n.case_expr.HasField("else_expr")
            else None
        )
        return lx.Case(
            base,
            [
                (expr_from_proto(wt.when), expr_from_proto(wt.then))
                for wt in n.case_expr.when_then
            ],
            else_e,
        )
    if which == "cast":
        return lx.Cast(expr_from_proto(n.cast.expr), dtype_from_ipc(n.cast.dtype_ipc))
    if which == "try_cast":
        return lx.TryCast(
            expr_from_proto(n.try_cast.expr), dtype_from_ipc(n.try_cast.dtype_ipc)
        )
    if which == "scalar_function":
        return lx.ScalarFunction(
            n.scalar_function.fn,
            [expr_from_proto(a) for a in n.scalar_function.args],
        )
    if which == "aggregate_expr":
        return lx.AggregateExpr(
            n.aggregate_expr.fn,
            expr_from_proto(n.aggregate_expr.expr),
            n.aggregate_expr.distinct,
        )
    if which == "sort_expr":
        return lx.SortExpr(
            expr_from_proto(n.sort_expr.expr),
            n.sort_expr.ascending,
            n.sort_expr.nulls_first,
        )
    if which == "wildcard":
        return lx.Wildcard()
    if which == "window_expr":
        w = n.window_expr
        arg = expr_from_proto(w.arg) if w.HasField("arg") else None
        order = []
        for oe in w.order_by:
            se = expr_from_proto(oe)
            assert isinstance(se, lx.SortExpr)
            order.append(se)
        frame = frame_from_proto(w.frame) if w.HasField("frame") else None
        return lx.WindowExpr(
            w.fn, arg, [expr_from_proto(pe) for pe in w.partition_by], order,
            frame,
        )
    raise SerdeError(f"empty expr node {n}")


# ---------------------------------------------------------------------------
# table sources
# ---------------------------------------------------------------------------


def source_to_proto(src: TableSource) -> pb.TableSourceDesc:
    d = pb.TableSourceDesc(table_type=src.table_type())
    d.schema_ipc = schema_to_ipc(src.schema())
    if isinstance(src, CsvTableSource):
        d.path = src.path
        d.has_header = src.has_header
        d.delimiter = src.delimiter
        d.file_extension = src.file_extension
    elif isinstance(src, ParquetTableSource):
        d.path = src.path
    elif isinstance(src, MemoryTableSource):
        for part in src.partitions:
            d.partitions_ipc.append(batches_to_ipc(part, src.schema()))
    else:
        raise SerdeError(f"cannot serialize source {type(src).__name__}")
    return d


def source_from_proto(d: pb.TableSourceDesc) -> TableSource:
    if d.table_type == "csv":
        return CsvTableSource(
            d.path,
            schema=schema_from_ipc(d.schema_ipc),
            has_header=d.has_header,
            delimiter=d.delimiter or ",",
            file_extension=d.file_extension or ".csv",
        )
    if d.table_type == "parquet":
        return ParquetTableSource(d.path)
    if d.table_type == "memory":
        schema = schema_from_ipc(d.schema_ipc)
        parts = [batches_from_ipc(p) for p in d.partitions_ipc]
        return MemoryTableSource(schema, parts)
    raise SerdeError(f"unknown table type {d.table_type!r}")


# ---------------------------------------------------------------------------
# plans
# ---------------------------------------------------------------------------


def plan_to_proto(plan: lp.LogicalPlan) -> pb.LogicalPlanNode:
    n = pb.LogicalPlanNode()
    if isinstance(plan, lp.TableScan):
        n.scan.table_name = plan.table_name
        n.scan.source.CopyFrom(source_to_proto(plan.source))
        if plan.projection is not None:
            n.scan.has_projection = True
            n.scan.projection.extend(plan.projection)
    elif isinstance(plan, lp.Projection):
        n.projection.input.CopyFrom(plan_to_proto(plan.input))
        for e in plan.exprs:
            n.projection.exprs.append(expr_to_proto(e))
    elif isinstance(plan, lp.Filter):
        n.filter.input.CopyFrom(plan_to_proto(plan.input))
        n.filter.predicate.CopyFrom(expr_to_proto(plan.predicate))
    elif isinstance(plan, lp.Aggregate):
        n.aggregate.input.CopyFrom(plan_to_proto(plan.input))
        for e in plan.group_exprs:
            n.aggregate.group_exprs.append(expr_to_proto(e))
        for e in plan.aggr_exprs:
            n.aggregate.aggr_exprs.append(expr_to_proto(e))
        n.aggregate.exact_floats = getattr(plan, "exact_floats", False)
    elif isinstance(plan, lp.Sort):
        n.sort.input.CopyFrom(plan_to_proto(plan.input))
        for e in plan.sort_exprs:
            n.sort.sort_exprs.append(expr_to_proto(e))
    elif isinstance(plan, lp.Limit):
        n.limit.input.CopyFrom(plan_to_proto(plan.input))
        n.limit.n = plan.n
        n.limit.skip = plan.skip
    elif isinstance(plan, lp.Join):
        n.join.left.CopyFrom(plan_to_proto(plan.left))
        n.join.right.CopyFrom(plan_to_proto(plan.right))
        for l, r in plan.on:
            n.join.left_keys.append(expr_to_proto(l))
            n.join.right_keys.append(expr_to_proto(r))
        n.join.join_type = plan.join_type.value
        if plan.filter is not None:
            n.join.filter.CopyFrom(expr_to_proto(plan.filter))
    elif isinstance(plan, lp.CrossJoin):
        n.cross_join.left.CopyFrom(plan_to_proto(plan.left))
        n.cross_join.right.CopyFrom(plan_to_proto(plan.right))
    elif isinstance(plan, lp.Repartition):
        n.repartition.input.CopyFrom(plan_to_proto(plan.input))
        n.repartition.scheme = plan.scheme.value
        n.repartition.n = plan.n
        for e in plan.hash_exprs:
            n.repartition.hash_exprs.append(expr_to_proto(e))
    elif isinstance(plan, lp.EmptyRelation):
        n.empty.produce_one_row = plan.produce_one_row
        n.empty.schema_ipc = schema_to_ipc(plan.schema())
    elif isinstance(plan, lp.SubqueryAlias):
        n.subquery_alias.input.CopyFrom(plan_to_proto(plan.input))
        n.subquery_alias.alias = plan.alias
    elif isinstance(plan, lp.Distinct):
        n.distinct.input.CopyFrom(plan_to_proto(plan.input))
    elif isinstance(plan, lp.Union):
        for i in plan.inputs:
            n.union.inputs.append(plan_to_proto(i))
        n.union.all = plan.all
    elif isinstance(plan, lp.Explain):
        n.explain.input.CopyFrom(plan_to_proto(plan.input))
        n.explain.verbose = plan.verbose
    elif isinstance(plan, lp.CreateExternalTable):
        n.create_external_table.name = plan.name
        n.create_external_table.location = plan.location
        n.create_external_table.file_type = plan.file_type
        n.create_external_table.has_header = plan.has_header
        if plan.table_schema is not None:
            n.create_external_table.schema_ipc = schema_to_ipc(plan.table_schema)
    elif isinstance(plan, lp.Window):
        n.window.input.CopyFrom(plan_to_proto(plan.input))
        for e in plan.window_exprs:
            n.window.window_exprs.append(expr_to_proto(e))
    else:
        raise SerdeError(f"cannot serialize plan {type(plan).__name__}")
    return n


def plan_from_proto(n: pb.LogicalPlanNode) -> lp.LogicalPlan:
    which = n.WhichOneof("plan_type")
    if which == "scan":
        src = source_from_proto(n.scan.source)
        projection = list(n.scan.projection) if n.scan.has_projection else None
        return lp.TableScan(n.scan.table_name, src, projection)
    if which == "projection":
        return lp.Projection(
            plan_from_proto(n.projection.input),
            [expr_from_proto(e) for e in n.projection.exprs],
        )
    if which == "filter":
        return lp.Filter(
            plan_from_proto(n.filter.input), expr_from_proto(n.filter.predicate)
        )
    if which == "aggregate":
        return lp.Aggregate(
            plan_from_proto(n.aggregate.input),
            [expr_from_proto(e) for e in n.aggregate.group_exprs],
            [expr_from_proto(e) for e in n.aggregate.aggr_exprs],
            exact_floats=n.aggregate.exact_floats,
        )
    if which == "sort":
        return lp.Sort(
            plan_from_proto(n.sort.input),
            [expr_from_proto(e) for e in n.sort.sort_exprs],
        )
    if which == "limit":
        return lp.Limit(plan_from_proto(n.limit.input), n.limit.n, n.limit.skip)
    if which == "join":
        on = [
            (expr_from_proto(l), expr_from_proto(r))
            for l, r in zip(n.join.left_keys, n.join.right_keys)
        ]
        filt = expr_from_proto(n.join.filter) if n.join.HasField("filter") else None
        return lp.Join(
            plan_from_proto(n.join.left),
            plan_from_proto(n.join.right),
            on,
            lp.JoinType(n.join.join_type),
            filt,
        )
    if which == "cross_join":
        return lp.CrossJoin(
            plan_from_proto(n.cross_join.left), plan_from_proto(n.cross_join.right)
        )
    if which == "repartition":
        return lp.Repartition(
            plan_from_proto(n.repartition.input),
            lp.PartitionScheme(n.repartition.scheme),
            n.repartition.n,
            [expr_from_proto(e) for e in n.repartition.hash_exprs],
        )
    if which == "empty":
        return lp.EmptyRelation(
            n.empty.produce_one_row, schema_from_ipc(n.empty.schema_ipc)
        )
    if which == "subquery_alias":
        return lp.SubqueryAlias(
            plan_from_proto(n.subquery_alias.input), n.subquery_alias.alias
        )
    if which == "distinct":
        return lp.Distinct(plan_from_proto(n.distinct.input))
    if which == "union":
        return lp.Union([plan_from_proto(i) for i in n.union.inputs], n.union.all)
    if which == "explain":
        return lp.Explain(plan_from_proto(n.explain.input), n.explain.verbose)
    if which == "create_external_table":
        c = n.create_external_table
        schema = schema_from_ipc(c.schema_ipc) if c.schema_ipc else None
        return lp.CreateExternalTable(c.name, c.location, c.file_type, c.has_header, schema)
    if which == "window":
        return lp.Window(
            plan_from_proto(n.window.input),
            [expr_from_proto(e) for e in n.window.window_exprs],
        )
    raise SerdeError(f"empty plan node: {n}")

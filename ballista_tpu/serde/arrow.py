"""Arrow <-> bytes helpers for the wire contract.

Schemas and record batches travel as Arrow IPC — the Arrow wire format
itself — instead of the reference's hand-rolled type enum
(reference rust/core/proto/ballista.proto:611-800).
"""

from __future__ import annotations

import io
from typing import List

import pyarrow as pa


def schema_to_ipc(schema: pa.Schema) -> bytes:
    return schema.serialize().to_pybytes()


def schema_from_ipc(data: bytes) -> pa.Schema:
    return pa.ipc.read_schema(pa.BufferReader(data))


def dtype_to_ipc(dtype: pa.DataType) -> bytes:
    return schema_to_ipc(pa.schema([pa.field("f", dtype)]))


def dtype_from_ipc(data: bytes) -> pa.DataType:
    return schema_from_ipc(data).field(0).type


def batches_to_ipc(batches: List[pa.RecordBatch], schema: pa.Schema) -> bytes:
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, schema) as w:
        for b in batches:
            w.write_batch(b)
    return sink.getvalue()


def batches_from_ipc(data: bytes) -> List[pa.RecordBatch]:
    with pa.ipc.open_stream(pa.BufferReader(data)) as r:
        return list(r)

"""Physical plan <-> protobuf.

Like the reference (rust/core/src/serde/physical_plan/), physical expressions
travel as *logical* expression nodes and are re-compiled against the child's
schema on deserialization (ref from_proto.rs:348-365 uses DataFusion's
planner the same way). uncompile_expr is the inverse: physical -> logical.
"""

from __future__ import annotations

from typing import List

import pyarrow as pa

from ballista_tpu.datasource import CsvTableSource, MemoryTableSource, ParquetTableSource
from ballista_tpu.distributed.stages import (
    ShuffleLocation,
    ShuffleReaderExec,
    ShuffleWriterExec,
    UnresolvedShuffleExec,
)
from ballista_tpu.errors import SerdeError
from ballista_tpu.logical import expr as lx
from ballista_tpu.logical.plan import JoinType
from ballista_tpu.physical import expr as px
from ballista_tpu.physical.aggregate import AggregateFunc, AggregateMode, HashAggregateExec
from ballista_tpu.physical.basic import (
    CoalesceBatchesExec,
    EmptyExec,
    FilterExec,
    GlobalLimitExec,
    LocalLimitExec,
    MergeExec,
    ProjectionExec,
    SortExec,
)
from ballista_tpu.parallel.spmd_join import SpmdJoinExec
from ballista_tpu.parallel.spmd_stage import SpmdAggregateExec
from ballista_tpu.physical.expr import create_physical_expr
from ballista_tpu.physical.join import CrossJoinExec, HashJoinExec
from ballista_tpu.physical.plan import ExecutionPlan, Partitioning
from ballista_tpu.physical.repartition import RepartitionExec
from ballista_tpu.physical.scan import CsvScanExec, MemoryScanExec, ParquetScanExec
from ballista_tpu.physical.union import UnionExec
from ballista_tpu.physical.window import WindowExec
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.serde.logical import (
    expr_from_proto,
    expr_to_proto,
    frame_from_proto,
    frame_to_proto,
    scalar_from_proto,
    scalar_to_proto,
    source_from_proto,
    source_to_proto,
)
from ballista_tpu.serde.arrow import dtype_from_ipc, dtype_to_ipc, schema_from_ipc, schema_to_ipc


# ---------------------------------------------------------------------------
# physical expr -> logical expr (for the wire)
# ---------------------------------------------------------------------------


def uncompile_expr(e: px.PhysicalExpr) -> lx.Expr:
    if isinstance(e, px.ColumnExpr):
        if "." in e.name:
            rel, _, bare = e.name.partition(".")
            return lx.Column(bare, rel)
        return lx.Column(e.name)
    if isinstance(e, px.LiteralExpr):
        return lx.Literal(e.value, e.dtype)
    if isinstance(e, px.BinaryPhysicalExpr):
        return lx.BinaryExpr(uncompile_expr(e.left), e.op, uncompile_expr(e.right))
    if isinstance(e, px.NotExpr):
        return lx.Not(uncompile_expr(e.expr))
    if isinstance(e, px.NegativeExpr):
        return lx.Negative(uncompile_expr(e.expr))
    if isinstance(e, px.IsNullExpr):
        inner = uncompile_expr(e.expr)
        return lx.IsNotNull(inner) if e.negated else lx.IsNull(inner)
    if isinstance(e, px.BetweenExpr):
        return lx.Between(
            uncompile_expr(e.expr),
            uncompile_expr(e.low),
            uncompile_expr(e.high),
            e.negated,
        )
    if isinstance(e, px.InListExpr):
        members = (
            [uncompile_expr(v) for v in e.value_exprs]
            if e.value_exprs is not None
            else [lx.Literal(v) for v in e.values]
        )
        return lx.InList(uncompile_expr(e.expr), members, e.negated)
    if isinstance(e, px.CaseExpr):
        return lx.Case(
            None if e.base is None else uncompile_expr(e.base),
            [(uncompile_expr(w), uncompile_expr(t)) for w, t in e.when_then],
            None if e.else_expr is None else uncompile_expr(e.else_expr),
        )
    if isinstance(e, px.CastExpr):
        if e.safe:
            return lx.TryCast(uncompile_expr(e.expr), e.dtype)
        return lx.Cast(uncompile_expr(e.expr), e.dtype)
    if isinstance(e, px.ScalarFunctionExpr):
        return lx.ScalarFunction(e.fn, [uncompile_expr(a) for a in e.args])
    raise SerdeError(f"cannot uncompile {type(e).__name__}")


# ---------------------------------------------------------------------------
# to proto
# ---------------------------------------------------------------------------


def phys_plan_to_proto(plan: ExecutionPlan) -> pb.PhysicalPlanNode:
    n = pb.PhysicalPlanNode()
    if isinstance(plan, (CsvScanExec, ParquetScanExec, MemoryScanExec)):
        n.scan.scan.table_name = ""
        n.scan.scan.source.CopyFrom(source_to_proto(plan.source))
        if plan.projection is not None:
            n.scan.scan.has_projection = True
            n.scan.scan.projection.extend(plan.projection)
        prune = getattr(plan, "prune_predicate", None)
        if prune is not None:
            n.scan.prune_predicate.CopyFrom(expr_to_proto(uncompile_expr(prune)))
    elif isinstance(plan, ProjectionExec):
        n.projection.input.CopyFrom(phys_plan_to_proto(plan.input))
        for e, name in plan.exprs:
            n.projection.exprs.append(expr_to_proto(uncompile_expr(e)))
            n.projection.names.append(name)
    elif isinstance(plan, FilterExec):
        n.filter.input.CopyFrom(phys_plan_to_proto(plan.input))
        n.filter.predicate.CopyFrom(expr_to_proto(uncompile_expr(plan.predicate)))
    elif isinstance(plan, HashAggregateExec):
        n.aggregate.input.CopyFrom(phys_plan_to_proto(plan.input))
        n.aggregate.mode = plan.mode.value
        for e, name in plan.group_exprs:
            n.aggregate.group_exprs.append(expr_to_proto(uncompile_expr(e)))
            n.aggregate.group_names.append(name)
        for a in plan.aggr_funcs:
            fn = a.fn
            distinct = False
            if fn.endswith("_distinct"):
                fn, distinct = fn[: -len("_distinct")], True
            an = pb.AggregateExprNode(fn=fn, distinct=distinct)
            an.expr.CopyFrom(expr_to_proto(uncompile_expr(a.expr)))
            n.aggregate.aggr_funcs.append(an)
            n.aggregate.aggr_names.append(a.name)
            n.aggregate.aggr_dtype_ipc.append(dtype_to_ipc(a.dtype))
            n.aggregate.aggr_input_type_ipc.append(dtype_to_ipc(a.input_type))
        n.aggregate.exact_floats = getattr(plan, "exact_floats", False)
    elif isinstance(plan, HashJoinExec):
        n.join.left.CopyFrom(phys_plan_to_proto(plan.left))
        n.join.right.CopyFrom(phys_plan_to_proto(plan.right))
        for l, r in plan.on:
            n.join.left_keys.append(l)
            n.join.right_keys.append(r)
        n.join.join_type = plan.join_type.value
        n.join.partitioned = plan.partitioned
        if plan.filter is not None:
            n.join.filter.CopyFrom(expr_to_proto(uncompile_expr(plan.filter)))
    elif isinstance(plan, CrossJoinExec):
        n.cross_join.left.CopyFrom(phys_plan_to_proto(plan.left))
        n.cross_join.right.CopyFrom(phys_plan_to_proto(plan.right))
    elif isinstance(plan, SortExec):
        n.sort.input.CopyFrom(phys_plan_to_proto(plan.input))
        for e, asc, nf in plan.sort_keys:
            se = lx.SortExpr(uncompile_expr(e), asc, nf)
            n.sort.sort_exprs.append(expr_to_proto(se))
        if plan.fetch is not None:
            n.sort.has_fetch = True
            n.sort.fetch = plan.fetch
    elif isinstance(plan, GlobalLimitExec):
        n.limit.input.CopyFrom(phys_plan_to_proto(plan.input))
        n.limit.limit = plan.limit
        n.limit.skip = plan.skip
        setattr(n.limit, "global", True)  # `global` is a Python keyword
    elif isinstance(plan, LocalLimitExec):
        n.limit.input.CopyFrom(phys_plan_to_proto(plan.input))
        n.limit.limit = plan.limit
        setattr(n.limit, "global", False)
    elif isinstance(plan, CoalesceBatchesExec):
        n.coalesce_batches.input.CopyFrom(phys_plan_to_proto(plan.input))
        n.coalesce_batches.target_batch_size = plan.target_batch_size
    elif isinstance(plan, MergeExec):
        n.merge.input.CopyFrom(phys_plan_to_proto(plan.input))
    elif isinstance(plan, EmptyExec):
        n.empty.produce_one_row = plan.produce_one_row
        n.empty.schema_ipc = schema_to_ipc(plan.schema())
    elif isinstance(plan, UnionExec):
        for i in plan.inputs:
            n.union.inputs.append(phys_plan_to_proto(i))
    elif isinstance(plan, RepartitionExec):
        n.repartition.input.CopyFrom(phys_plan_to_proto(plan.input))
        n.repartition.scheme = plan.partitioning.scheme
        n.repartition.n = plan.partitioning.partition_count()
        for e in plan.partitioning.exprs:
            n.repartition.hash_exprs.append(expr_to_proto(uncompile_expr(e)))
    elif isinstance(plan, ShuffleWriterExec):
        n.shuffle_writer.input.CopyFrom(phys_plan_to_proto(plan.input))
        n.shuffle_writer.job_id = plan.job_id
        n.shuffle_writer.stage_id = plan.stage_id
        p = plan.shuffle_output_partitioning
        if p is None:
            n.shuffle_writer.scheme = "none"
        else:
            n.shuffle_writer.scheme = p.scheme
            n.shuffle_writer.n = p.partition_count()
            for e in p.exprs:
                n.shuffle_writer.hash_exprs.append(expr_to_proto(uncompile_expr(e)))
    elif isinstance(plan, ShuffleReaderExec):
        for loc in plan.locations:
            pl = n.shuffle_reader.partition_locations.add()
            pl.executor_meta.id = loc.executor_id
            pl.executor_meta.host = loc.host
            pl.executor_meta.port = loc.port
            pl.path = loc.path
            # lineage of the producing map task, so a failed fetch can name
            # exactly what the scheduler must recompute
            pl.partition_id.stage_id = loc.stage_id
            pl.partition_id.partition_id = loc.map_partition
            # disaggregated tier (ISSUE 15): the path-home rides the wire so
            # the executing reader resolves storage-first
            pl.storage_uri = loc.storage_uri
            # HBM-resident exchange hint + piece size (ISSUE 16): the size
            # lets the consumer-side cost model price the transfer the
            # resident hit would skip
            pl.resident = loc.resident
            pl.partition_stats.num_bytes = loc.nbytes
        n.shuffle_reader.schema_ipc = schema_to_ipc(plan.schema())
        n.shuffle_reader.num_partitions = plan.num_partitions
        n.shuffle_reader.identity = plan.identity
    elif isinstance(plan, WindowExec):
        n.window.input.CopyFrom(phys_plan_to_proto(plan.input))
        for f in plan.funcs:
            wf = n.window.funcs.add()
            wf.fn = f.fn
            if f.arg is not None:
                wf.arg.CopyFrom(expr_to_proto(uncompile_expr(f.arg)))
            for p_ in f.partition_by:
                wf.partition_by.append(expr_to_proto(uncompile_expr(p_)))
            for oe, asc in f.order_by:
                wf.order_by.append(
                    expr_to_proto(lx.SortExpr(uncompile_expr(oe), asc, False))
                )
            wf.name = f.name
            wf.dtype_ipc = dtype_to_ipc(f.dtype)
            if f.frame is not None:
                frame_to_proto(wf.frame, f.frame)
    elif isinstance(plan, UnresolvedShuffleExec):
        n.unresolved_shuffle.stage_id = plan.stage_id
        n.unresolved_shuffle.schema_ipc = schema_to_ipc(plan.schema())
        n.unresolved_shuffle.partition_count = plan.partition_count
        n.unresolved_shuffle.identity = plan.identity
    elif isinstance(plan, SpmdAggregateExec):
        n.spmd_aggregate.subplan.CopyFrom(phys_plan_to_proto(plan.subplan))
    elif isinstance(plan, SpmdJoinExec):
        n.spmd_join.subplan.CopyFrom(phys_plan_to_proto(plan.subplan))
    else:
        raise SerdeError(f"cannot serialize physical plan {type(plan).__name__}")
    return n


# ---------------------------------------------------------------------------
# from proto
# ---------------------------------------------------------------------------


def phys_plan_from_proto(n: pb.PhysicalPlanNode) -> ExecutionPlan:
    which = n.WhichOneof("plan_type")
    if which == "scan":
        src = source_from_proto(n.scan.scan.source)
        projection = list(n.scan.scan.projection) if n.scan.scan.has_projection else None
        if isinstance(src, CsvTableSource):
            return CsvScanExec(src, projection)
        if isinstance(src, ParquetTableSource):
            scan = ParquetScanExec(src, projection)
            if n.scan.HasField("prune_predicate"):
                scan.prune_predicate = create_physical_expr(
                    expr_from_proto(n.scan.prune_predicate), scan.schema()
                )
            return scan
        return MemoryScanExec(src, projection)
    if which == "spmd_aggregate":
        return SpmdAggregateExec(phys_plan_from_proto(n.spmd_aggregate.subplan))
    if which == "spmd_join":
        return SpmdJoinExec(phys_plan_from_proto(n.spmd_join.subplan))
    if which == "projection":
        input = phys_plan_from_proto(n.projection.input)
        schema = input.schema()
        exprs = [
            (create_physical_expr(expr_from_proto(e), schema), name)
            for e, name in zip(n.projection.exprs, n.projection.names)
        ]
        return ProjectionExec(input, exprs)
    if which == "filter":
        input = phys_plan_from_proto(n.filter.input)
        return FilterExec(
            input, create_physical_expr(expr_from_proto(n.filter.predicate), input.schema())
        )
    if which == "aggregate":
        input = phys_plan_from_proto(n.aggregate.input)
        mode = AggregateMode(n.aggregate.mode)
        # FINAL consumes partial state positionally: expressions are never
        # re-evaluated, so compile placeholders and use the shipped types
        is_final = mode == AggregateMode.FINAL
        in_schema = input.schema()
        group_exprs = []
        for i, (e, name) in enumerate(
            zip(n.aggregate.group_exprs, n.aggregate.group_names)
        ):
            if is_final:
                group_exprs.append((px.ColumnExpr(name, i), name))
            else:
                group_exprs.append(
                    (create_physical_expr(expr_from_proto(e), in_schema), name)
                )
        funcs = []
        for j, (an, name) in enumerate(
            zip(n.aggregate.aggr_funcs, n.aggregate.aggr_names)
        ):
            dtype = dtype_from_ipc(n.aggregate.aggr_dtype_ipc[j])
            input_type = dtype_from_ipc(n.aggregate.aggr_input_type_ipc[j])
            if is_final:
                pe: px.PhysicalExpr = px.ColumnExpr(name, j)
            else:
                pe = create_physical_expr(expr_from_proto(an.expr), in_schema)
            fn = an.fn if not an.distinct else f"{an.fn}_distinct"
            funcs.append(AggregateFunc(fn, pe, name, dtype, input_type))
        return HashAggregateExec(mode, input, group_exprs, funcs,
                                 exact_floats=n.aggregate.exact_floats)
    if which == "join":
        left = phys_plan_from_proto(n.join.left)
        right = phys_plan_from_proto(n.join.right)
        on = list(zip(n.join.left_keys, n.join.right_keys))
        jt = JoinType(n.join.join_type)
        filt = None
        if n.join.HasField("filter"):
            concat = pa.schema(list(left.schema()) + list(right.schema()))
            filt = create_physical_expr(expr_from_proto(n.join.filter), concat)
        return HashJoinExec(
            left, right, on, jt, filter=filt, partitioned=n.join.partitioned
        )
    if which == "cross_join":
        return CrossJoinExec(
            phys_plan_from_proto(n.cross_join.left),
            phys_plan_from_proto(n.cross_join.right),
        )
    if which == "sort":
        input = phys_plan_from_proto(n.sort.input)
        keys = []
        for se in n.sort.sort_exprs:
            e = expr_from_proto(se)
            assert isinstance(e, lx.SortExpr)
            keys.append(
                (
                    create_physical_expr(e.expr, input.schema()),
                    e.ascending,
                    e.nulls_first,
                )
            )
        fetch = n.sort.fetch if n.sort.has_fetch else None
        return SortExec(input, keys, fetch)
    if which == "limit":
        input = phys_plan_from_proto(n.limit.input)
        if getattr(n.limit, "global"):
            return GlobalLimitExec(input, n.limit.limit, n.limit.skip)
        return LocalLimitExec(input, n.limit.limit)
    if which == "coalesce_batches":
        return CoalesceBatchesExec(
            phys_plan_from_proto(n.coalesce_batches.input),
            n.coalesce_batches.target_batch_size,
        )
    if which == "merge":
        return MergeExec(phys_plan_from_proto(n.merge.input))
    if which == "empty":
        return EmptyExec(n.empty.produce_one_row, schema_from_ipc(n.empty.schema_ipc))
    if which == "union":
        return UnionExec([phys_plan_from_proto(i) for i in n.union.inputs])
    if which == "repartition":
        input = phys_plan_from_proto(n.repartition.input)
        if n.repartition.scheme == "hash":
            exprs = [
                create_physical_expr(expr_from_proto(e), input.schema())
                for e in n.repartition.hash_exprs
            ]
            part = Partitioning.hash(exprs, n.repartition.n)
        elif n.repartition.scheme == "round_robin":
            part = Partitioning.round_robin(n.repartition.n)
        else:
            part = Partitioning.unknown(n.repartition.n)
        return RepartitionExec(input, part)
    if which == "shuffle_writer":
        input = phys_plan_from_proto(n.shuffle_writer.input)
        sw = n.shuffle_writer
        if sw.scheme == "none":
            part = None
        elif sw.scheme == "hash":
            exprs = [
                create_physical_expr(expr_from_proto(e), input.schema())
                for e in sw.hash_exprs
            ]
            part = Partitioning.hash(exprs, sw.n)
        else:
            part = Partitioning.round_robin(sw.n)
        return ShuffleWriterExec(sw.job_id, sw.stage_id, input, part)
    if which == "shuffle_reader":
        locs = [
            ShuffleLocation(
                pl.executor_meta.id,
                pl.executor_meta.host,
                pl.executor_meta.port,
                pl.path,
                stage_id=pl.partition_id.stage_id,
                map_partition=pl.partition_id.partition_id,
                storage_uri=pl.storage_uri,
                resident=pl.resident,
                nbytes=pl.partition_stats.num_bytes,
            )
            for pl in n.shuffle_reader.partition_locations
        ]
        return ShuffleReaderExec(
            locs,
            schema_from_ipc(n.shuffle_reader.schema_ipc),
            n.shuffle_reader.num_partitions,
            identity=n.shuffle_reader.identity,
        )
    if which == "window":
        from ballista_tpu.physical.window import WindowExec, WindowFuncDesc

        input = phys_plan_from_proto(n.window.input)
        schema = input.schema()
        funcs = []
        for wf in n.window.funcs:
            arg = (
                create_physical_expr(expr_from_proto(wf.arg), schema)
                if wf.HasField("arg")
                else None
            )
            order = []
            for oe in wf.order_by:
                se = expr_from_proto(oe)
                order.append((create_physical_expr(se.expr, schema), se.ascending))
            funcs.append(
                WindowFuncDesc(
                    wf.fn,
                    arg,
                    [
                        create_physical_expr(expr_from_proto(pe), schema)
                        for pe in wf.partition_by
                    ],
                    order,
                    wf.name,
                    dtype_from_ipc(wf.dtype_ipc),
                    frame_from_proto(wf.frame) if wf.HasField("frame") else None,
                )
            )
        return WindowExec(input, funcs)
    if which == "unresolved_shuffle":
        return UnresolvedShuffleExec(
            n.unresolved_shuffle.stage_id,
            schema_from_ipc(n.unresolved_shuffle.schema_ipc),
            n.unresolved_shuffle.partition_count,
            identity=n.unresolved_shuffle.identity,
        )
    raise SerdeError(f"empty physical plan node: {n}")

from ballista_tpu.serde.logical import (  # noqa: F401
    expr_to_proto,
    expr_from_proto,
    plan_to_proto,
    plan_from_proto,
)

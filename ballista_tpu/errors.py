"""Error types.

Mirrors the reference's single error enum with per-subsystem variants
(reference rust/core/src/error.rs:30-163) as a small exception hierarchy.
"""

from __future__ import annotations


class BallistaError(Exception):
    """Base error for all ballista_tpu failures."""


class NotImplementedError_(BallistaError):
    """Feature not implemented (reference error.rs NotImplemented variant)."""


class InternalError(BallistaError):
    """Invariant violation inside the engine."""


class PlanError(BallistaError):
    """Logical/physical planning failure (reference DataFusionError role)."""


class SchemaError(BallistaError):
    """Schema mismatch / unknown column."""


class SqlError(BallistaError):
    """SQL lex/parse/plan failure (reference error.rs Sql variant)."""


class SerdeError(BallistaError):
    """Plan (de)serialization failure."""


class IoError(BallistaError):
    """Filesystem / IPC failure (reference error.rs Io variant)."""


class RpcError(BallistaError):
    """Control-plane (gRPC) failure (reference Tonic/Grpc variants)."""


class ShuffleFetchError(RpcError):
    """A shuffle fetch from a peer executor failed mid-task. Carries the
    lost location (owning executor + map stage/partition + path) so the
    executor can report a `fetch_failed` status and the scheduler can
    recompute just that map partition (lineage-based shuffle recovery)
    instead of failing the job."""

    def __init__(
        self,
        message: str,
        *,
        executor_id: str = "",
        host: str = "",
        port: int = 0,
        path: str = "",
        stage_id: int = 0,
        map_partition: int = 0,
    ) -> None:
        super().__init__(message)
        self.executor_id = executor_id
        self.host = host
        self.port = port
        self.path = path
        self.stage_id = stage_id
        self.map_partition = map_partition


class ExecutionError(BallistaError):
    """Runtime failure while executing a physical plan."""

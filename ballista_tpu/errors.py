"""Error types.

Mirrors the reference's single error enum with per-subsystem variants
(reference rust/core/src/error.rs:30-163) as a small exception hierarchy.
"""

from __future__ import annotations


class BallistaError(Exception):
    """Base error for all ballista_tpu failures."""


class NotImplementedError_(BallistaError):
    """Feature not implemented (reference error.rs NotImplemented variant)."""


class InternalError(BallistaError):
    """Invariant violation inside the engine."""


class PlanError(BallistaError):
    """Logical/physical planning failure (reference DataFusionError role)."""


class SchemaError(BallistaError):
    """Schema mismatch / unknown column."""


class SqlError(BallistaError):
    """SQL lex/parse/plan failure (reference error.rs Sql variant)."""


class SerdeError(BallistaError):
    """Plan (de)serialization failure."""


class IoError(BallistaError):
    """Filesystem / IPC failure (reference error.rs Io variant)."""


class RpcError(BallistaError):
    """Control-plane (gRPC) failure (reference Tonic/Grpc variants)."""


class ExecutionError(BallistaError):
    """Runtime failure while executing a physical plan."""

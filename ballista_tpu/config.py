"""Session / executor configuration.

The reference flows a free-form string settings map from clients
(KeyValuePair settings, reference rust/core/proto/ballista.proto:428-447;
``batch.size`` set by the TPC-H harness, rust/benchmarks/tpch/src/main.rs:120-121)
and configures daemons via configure_me specs
(rust/executor/executor_config_spec.toml, rust/scheduler/scheduler_config_spec.toml).

Here both collapse into one typed-view-over-strings config object. The
executor-selection boundary (cpu | tpu backend) lives here, keeping the host
Arrow path the default as the reference's CPU executor path is.
"""

from __future__ import annotations

from typing import Dict, Iterator, Mapping, Optional

BALLISTA_BATCH_SIZE = "ballista.batch.size"
BALLISTA_BACKEND = "ballista.executor.backend"  # "cpu" (Arrow host kernels) | "tpu" (JAX/XLA)
BALLISTA_STAGE_FUSION = "ballista.tpu.stage_fusion"  # whole-stage SPMD compilation on/off
BALLISTA_MESH_SHAPE = "ballista.tpu.mesh"  # e.g. "data:8" or "data:4,model:2"
BALLISTA_SHUFFLE_PARTITIONS = "ballista.shuffle.partitions"
# compression for materialized shuffle pieces: "" (none) | "zstd" | "lz4"
BALLISTA_SHUFFLE_CODEC = "ballista.shuffle.codec"
# -- disaggregated shuffle tier (ISSUE 15) ----------------------------------
# where materialized shuffle pieces live:
#   "local"  — the producing executor's private work dir, served to peers
#              over Flight (the reference design; executor death loses the
#              pieces and lineage recompute recovers them)
#   "shared" — a shared-storage directory rooted at ballista.shuffle.dir
#              (NFS/fuse mount, or any path every node sees). A piece's
#              home becomes a PATH, not a process: executor death after map
#              completion is a non-event (no lineage recompute, no task
#              retries), and scaling the fleet in destroys no data.
# Readers resolve storage-homed pieces from the shared dir first; the
# Flight peer fetch stays as the local-tier path and the fallback ladder.
BALLISTA_SHUFFLE_TIER = "ballista.shuffle.tier"
BALLISTA_SHUFFLE_DIR = "ballista.shuffle.dir"
# -- elastic executor fleet (ISSUE 15, executor/runtime.py) -----------------
# StandaloneCluster autoscaler: grows/shrinks the executor fleet against
# the admission queue's cost-model-predicted backlog seconds. max = 0
# disables autoscaling entirely (the fixed-fleet default); with max > 0
# the fleet floats in [min, max] — scale-OUT adds executors while the
# predicted backlog exceeds target_backlog_s, scale-IN gracefully drains
# one executor per evaluation (stop offering slots, finish running tasks,
# retire) once the cluster is idle. On the shared shuffle tier a drain
# destroys no data, so scale-in completes running jobs with zero retries.
BALLISTA_FLEET_MIN = "ballista.fleet.min"
BALLISTA_FLEET_MAX = "ballista.fleet.max"
BALLISTA_FLEET_INTERVAL_S = "ballista.fleet.interval_s"
# predicted backlog seconds one evaluation tolerates before growing the
# fleet; also the growth denominator (desired extra executors ~= backlog /
# target), so a deep queue grows the fleet in one evaluation, not one
# executor per tick
BALLISTA_FLEET_TARGET_BACKLOG_S = "ballista.fleet.target_backlog_s"
BALLISTA_DEVICE_CACHE = "ballista.tpu.device_cache"  # keep encoded columns resident in HBM
# total bytes of cached device residency across stages; partitions beyond
# the budget stream (upload, compute, free) instead of pinning — how SF=100
# fact layouts run on a 16GB-HBM chip
BALLISTA_TPU_HBM_BUDGET = "ballista.tpu.hbm_budget_bytes"
# HBM-resident cross-stage exchange (ISSUE 16): a completed shuffle write
# ALSO registers its pieces in the executor's residency registry so a
# same-executor consumer resolves them with zero decode and zero re-upload.
# The disk/storage piece stays the authoritative home — eviction or
# executor death degrades to the storage -> Flight peer -> lineage ladder.
BALLISTA_TPU_EXCHANGE = "ballista.tpu.exchange"
# byte budget for registered exchange pieces per executor process; pieces
# past it are skipped (or evict colder entries when the cost model says the
# incomer saves more transfer time than the victims would)
BALLISTA_TPU_RESIDENCY_BUDGET = "ballista.tpu.residency_budget_bytes"
BALLISTA_SCAN_CACHE = "ballista.scan.cache"  # host-side decoded-table cache (parquet)
BALLISTA_SCAN_CACHE_CAP = "ballista.scan.cache_cap_bytes"
# experimental per-operator device offload (filter/projection masks, PK-FK
# join). Whole-stage fusion is the default TPU path; per-op offload only pays
# when host<->device latency is low, so it is opt-in.
BALLISTA_TPU_PER_OP = "ballista.tpu.per_op_dispatch"
BALLISTA_TPU_DEVICE_JOIN = "ballista.tpu.device_join"
BALLISTA_TPU_FUSE_VOLATILE = "ballista.tpu.fuse_volatile_sources"  # aggregate over non-scan sources
# distributed planner: collapse Partial->hash shuffle->Final aggregations
# into ONE mesh program (shard_map + psum over ICI, parallel/spmd_stage.py)
BALLISTA_TPU_SPMD = "ballista.tpu.spmd_stages"
# plan multi-partition aggregations as ONE SINGLE-mode aggregate over merged
# input instead of Partial/Final. On a single chip the partial/final split
# buys no parallelism and costs one d2h readback of partial states PER
# partition (~65ms latency + bandwidth each through the relay); coalescing
# restores the top-k readback pushdown (SINGLE-mode only) and makes the
# whole aggregation one dispatch + one small readback. "auto" = on when the
# backend is tpu and SPMD stage fusion is off (the distributed scheduler
# and the mesh dryrun keep the exchange shape).
BALLISTA_TPU_COALESCE_AGG = "ballista.tpu.coalesce_aggregates"
# byte cap (sum of leaf scan file sizes) above which coalescing is skipped:
# one driven partition materializes the whole chain, so huge inputs keep the
# Partial/Final split and stream file-by-file within the HBM budget
BALLISTA_TPU_COALESCE_MAX = "ballista.tpu.coalesce_max_bytes"
# high-cardinality sorted aggregation kernel: "layout" (chunked-segment
# tiles, default) | "pallas" (MXU one-hot matmul with RMW DMA windows,
# sum/count/avg only — measured slower on v5e, kept selectable)
BALLISTA_TPU_SORTED_KERNEL = "ballista.tpu.sorted_kernel"
# persisted device-layout cache (ops/layout_cache.py): warm starts skip the
# O(N log N) host prepare (decode/encode/rank/sort/materialize) for
# file-backed stages. "" disables; entries keyed by plan + file mtimes
BALLISTA_TPU_LAYOUT_CACHE_DIR = "ballista.tpu.layout_cache_dir"
BALLISTA_TPU_LAYOUT_CACHE_CAP = "ballista.tpu.layout_cache_cap_bytes"
# pipelined host->device ingestion (ops/stage.py, distributed/stages.py):
# worker threads for the prefetch stage (parquet read + dictionary decode +
# group ranking, and parallel shuffle-piece fetches). 0 = fully serial
# (the pre-pipeline path); the encode/upload consume stage stays ordered
# regardless, so results are bit-identical at any worker count.
BALLISTA_TPU_INGEST_WORKERS = "ballista.tpu.ingest_workers"
# max prefetched items in flight beyond the one being consumed, per
# pipeline stage. The file-read stage (whole decoded tables) and the
# prepare pipeline (ranked batches) each hold up to `depth` items, and the
# shuffle reader up to `depth` materialized pieces — so the worst-case
# host RSS bound is ~2*depth decoded tables, not depth batches
BALLISTA_TPU_INGEST_DEPTH = "ballista.tpu.ingest_depth"
# comma-separated directory allowlist for scan paths in plans arriving over
# the wire ("" = unrestricted, the standalone/local default). The reference
# executes any deserialized plan (rust/executor/src/flight_service.rs:90-192);
# a rewrite should not let an unauthenticated peer scan arbitrary host files.
BALLISTA_DATA_ROOTS = "ballista.executor.data_roots"
# -- failure recovery (scheduler/state.py, executor/execution_loop.py) ------
# how many times a failed task is requeued before the job fails with the
# full attempt history (the reference fails the job on the FIRST task
# failure, SURVEY §5 "no retry"). Counts ALL requeue causes: task errors,
# executor death, lost shuffle outputs, fetch failures.
BALLISTA_MAX_TASK_RETRIES = "ballista.shuffle.max_task_retries"
# transient-RPC resilience: attempts beyond the first for UNAVAILABLE /
# connect failures (execution errors surface immediately), and the jittered
# exponential backoff base between them
BALLISTA_RPC_RETRIES = "ballista.rpc.retries"
BALLISTA_RPC_BACKOFF_MS = "ballista.rpc.backoff_ms"
# -- multi-tenant serving (ISSUE 7) -----------------------------------------
# which tenant this client submits as ("" = the default unnamed tenant) and
# the optional per-job priority (higher schedules first within the tenant).
# Both ride ExecuteQueryParams as first-class fields; the scheduler persists
# them per job (tenants/{job}) so admission survives a restart.
BALLISTA_TENANT = "ballista.tenant.name"
BALLISTA_TENANT_PRIORITY = "ballista.tenant.priority"
# scheduler-side admission control: max tasks a single tenant may have
# in flight across the cluster (0 = unlimited). A tenant at its quota is
# skipped by assignment until its running tasks drain — a saturating
# tenant's SF=100 scan cannot starve another tenant's point query.
BALLISTA_TENANT_MAX_INFLIGHT = "ballista.tenant.max_inflight"
# weighted fair share: "alice:4,bob:1" gives alice 4x bob's share of
# assignment slots when both have pending work; unlisted tenants weigh 1.
BALLISTA_TENANT_WEIGHTS = "ballista.tenant.weights"
# plan-fingerprint result cache (scheduler-side): a completed job's result
# partition locations are indexed under sha256(normalized logical plan +
# input file mtimes + result-affecting settings); a repeated identical
# query over unchanged inputs completes instantly with ZERO executor tasks.
BALLISTA_RESULT_CACHE = "ballista.cache.results"
# result-cache bounds (ISSUE 8): max live resultcache/{fp} entries (0 =
# unbounded; past the cap the least-recently-HIT entries are deleted from
# the KV) and a TTL in seconds (0 = no expiry; an entry older than this is
# treated as a miss and deleted on lookup). Entries are location-only and
# tiny, but an unbounded long-lived scheduler would accumulate every
# distinct query it ever served.
BALLISTA_RESULT_CACHE_MAX_ENTRIES = "ballista.cache.results.max_entries"
BALLISTA_RESULT_CACHE_TTL_S = "ballista.cache.results.ttl_s"
# result-cache delta advancement (ISSUE 19): on a fingerprint miss whose
# content_key matches a cached entry and whose scan-file set is a strict
# SUPERSET of the entry's, plan a delta job over only the NEW files and
# fold its partials into the entry's stored resumable state instead of
# recomputing the full scan. Only order-insensitive aggregate shapes are
# eligible (integer sums, counts, min/max — f32-arithmetic sums and
# anything non-associative decline to the full run, recorded, never
# silent); the advanced result is bit-identical to a cold full run.
BALLISTA_CACHE_ADVANCE = "ballista.cache.advance"
# internal (scheduler-set, never client-set): present in a delta job's
# per-job settings, naming the user job whose cached result the delta's
# output advances. Rides TaskDefinition.settings AND the proto's
# delta_for field — provenance for logs/telemetry; executors run the
# task like any other.
BALLISTA_DELTA_FOR = "ballista.internal.delta_for"
# cross-job physical-plan sharing (scheduler-side): optimize+physical
# planning output is content-keyed (fingerprint sans mtimes), so N tenants
# submitting the same dashboard query plan it once.
BALLISTA_PLAN_CACHE = "ballista.cache.plans"
# per-tenant HBM-residency budget (ISSUE 19 satellite, PR 16 residue): max
# bytes of exchange-registry residency one tenant's published pieces may
# hold on a chip (0 = unlimited). Enforced BEFORE the cluster-global
# residency budget, with per-tenant LRU eviction among that tenant's own
# entries — one tenant's SF=100 shuffle cannot monopolize the registry
# that another tenant's dashboard queries rely on.
BALLISTA_TENANT_RESIDENCY_BUDGET = "ballista.tenant.residency_budget_bytes"
# per-tenant latency SLO deadlines (ISSUE 11): "alice:250,bob:2000" gives
# alice's jobs a 250ms target. Feeds admission ordering — a tenant whose
# oldest pending job has blown (or is past) its deadline is visited BEFORE
# the weighted fair-share order (deadline-aware fair share), and a job
# completing past its deadline counts an `slo_misses` speculation event.
# Unlisted tenants carry no SLO and keep the pure fair-share order.
BALLISTA_TENANT_SLO_MS = "ballista.tenant.slo_ms"
# -- speculative execution (ISSUE 11, scheduler/state.py) -------------------
# cost-model straggler detection: when a RUNNING task's elapsed time
# exceeds `multiplier` x its predicted cost (ops/costmodel.py task.run
# rates, warmed by sibling completions) AND the minimum-runtime floor, the
# scheduler dispatches a duplicate attempt to a DIFFERENT executor through
# the normal assignment + ledger path. First completion wins; the losing
# attempt's report is dropped by the stale-attempt guard. Results are
# bit-identical with speculation on or off.
BALLISTA_SPECULATION = "ballista.speculation"
BALLISTA_SPECULATION_MULTIPLIER = "ballista.speculation.multiplier"
# floor below which a task never speculates (cheap tasks finish before a
# duplicate could help; this is also why fault-free runs launch nothing
# under the defaults)
BALLISTA_SPECULATION_MIN_RUNTIME_MS = "ballista.speculation.min_runtime_ms"
# re-speculation bound (ISSUE 15 satellite, PR 11 residue): how many
# speculative duplicates one task may accumulate. A duplicate that ITSELF
# straggles past the same cost-model threshold may be re-speculated
# (superseding the straggling duplicate in the ledger) until this many
# have launched; 1 restores the old launch-once behavior.
BALLISTA_SPECULATION_MAX_ATTEMPTS = "ballista.speculation.max_attempts"
# -- shared-scan multi-query execution (ISSUE 13) ---------------------------
# scheduler-side scan sharing: concurrent DISTINCT jobs whose pending
# fused-aggregate stages read the same persisted layout (same scan files,
# same chunk cover) are grouped into one batched task — the executor runs
# the group as ONE device launch over ONE resident upload, each member's
# readback routed to its own job's shuffle piece, bit-identical to solo
# execution. Evidence-gated through the cost model's `stage.batch` rates (a
# batch predicted slower than the members' solo sum dispatches solo), and
# any incompatibility at the executor degrades the member to solo, never to
# a wrong answer.
BALLISTA_SHARED_SCAN = "ballista.shared_scan"
# most member tasks one batched dispatch may carry (the primary included)
BALLISTA_SHARED_SCAN_MAX_BATCH = "ballista.shared_scan.max_batch"
# client-side server-push job-status notifications (ISSUE 11 satellite): a
# server-streaming SubscribeJobStatus RPC mirroring SubscribeWork replaces
# the 5ms-floor adaptive status poll on the wait/stream paths; the poll
# stays as the automatic fallback whenever the stream is down or refused.
BALLISTA_PUSH_STATUS = "ballista.client.push_status"
# -- low-latency serving tier (ISSUE 8) -------------------------------------
# push-based task dispatch: executors open a server-streaming SubscribeWork
# stream and the scheduler pushes TaskDefinitions the moment assignment
# picks them. The PollWork loop stays as heartbeat + automatic dispatch
# fallback when the stream is down. Governs BOTH sides: an executor with it
# off never subscribes, a scheduler with it off refuses subscriptions.
BALLISTA_PUSH_DISPATCH = "ballista.executor.push_dispatch"
# adaptive idle poll backoff: while the push stream is healthy the PollWork
# heartbeat interval decays from 250ms toward this ceiling (seconds) and
# snaps back to 250ms the moment the stream drops — the steady-state RPC
# load of a large idle fleet falls ~8x without touching crash-tolerance
# semantics (the echo/lease machinery rides whatever polls happen).
BALLISTA_IDLE_POLL_MAX_S = "ballista.executor.idle_poll_max_s"
# persistent compiled-program (AOT) cache directory beside the layout
# cache: jitted device-stage programs are exported (jax.export), serialized
# to disk keyed on stage identity + shape bucket + jax/jaxlib/backend
# fingerprint, and reloaded by later processes — a warm disk tier under the
# in-memory jit cache, so a cold executor skips the Python trace (and, with
# the persistent XLA cache, the compile). "" disables.
BALLISTA_TPU_AOT_CACHE_DIR = "ballista.tpu.aot_cache"
# pre-warm at executor start: load every manifest entry of the AOT cache
# and compile it BEFORE the first task arrives, so a cold executor's first
# small query pays zero trace/compile. Off by default — interactive/test
# processes should not pay a bulk warm-up they may never amortize.
BALLISTA_TPU_PREWARM = "ballista.tpu.prewarm"
# client-side streaming result fetch: collect() starts fetching (and
# consuming) final-stage result partitions AS THEY COMPLETE, via the
# per-partition completion notifications on the running job status, instead
# of waiting for the whole job — time-to-first-batch drops to the first
# partition's latency. Results are bit-identical to the buffered path.
BALLISTA_STREAM_RESULTS = "ballista.client.stream_results"
# -- adaptive execution (ISSUE 10, ops/costmodel.py) ------------------------
# measured cost model behind device-vs-host routing: tier selection past
# the static ladder, partial offload (split a batch at the tier boundary
# instead of declining it wholesale), the general skew handler, and
# build-side switching on observed cardinality misestimates. OFF restores
# the pure static decline ladder exactly; routing never changes results —
# bit-identity to the host oracle is the invariant either way.
BALLISTA_TPU_COST_MODEL = "ballista.tpu.cost_model"
# persisted per-shape-bucket cost store beside the layout cache, keyed like
# the AOT cache on op/stage identity + shape bucket + backend fingerprint.
# "" keeps the store in-memory only (observations still steer routing
# within the process, nothing survives it).
BALLISTA_TPU_COST_MODEL_DIR = "ballista.tpu.cost_model_dir"
# -- concurrency analysis (ISSUE 14, utils/locks.py) ------------------------
# dynamic lock witness: project locks record acquired-while-held edges at
# runtime, assert the moment an acquisition inverts the canonical order in
# dev/analysis/lockorder.toml (both stacks attached), and dump a witness
# file for `python -m dev.analysis --check-witness`. Debug/CI mode —
# enabling is process-global and sticky. Env equivalents:
# BALLISTA_LOCK_WITNESS=1 / BALLISTA_LOCK_WITNESS_OUT=<path>.
BALLISTA_DEBUG_LOCK_WITNESS = "ballista.debug.lock_witness"
# -- replicated control plane (ISSUE 20) ------------------------------------
# TTL of the per-job ownership lease (leases/{job}) a scheduler replica
# mints with the planning commit and renews from its heartbeat thread at
# ttl/3. Expiry is the failover trigger: an idle peer adopts the dead
# replica's jobs by running restart recovery scoped to them, so this bounds
# the ownership-migration latency after a replica dies. Fencing (the CAS on
# the lease value in every owner write) makes a TOO-short TTL safe — a
# spurious expiry costs a migration, never corruption — but each migration
# re-runs scoped recovery, so production deployments want seconds, not
# milliseconds.
BALLISTA_SCHEDULER_LEASE_TTL_S = "ballista.scheduler.lease_ttl_s"
# -- deterministic fault injection (utils/chaos.py) -------------------------
# rate > 0 arms the registered injection sites; each (site, key) pair draws
# a DETERMINISTIC verdict from sha256(seed, site, key), so a chaos run is
# reproducible and recovery must deliver results bit-identical to the
# fault-free run. sites: comma-separated subset of chaos.SITES ("" = all).
BALLISTA_CHAOS_SEED = "ballista.chaos.seed"
BALLISTA_CHAOS_RATE = "ballista.chaos.rate"
BALLISTA_CHAOS_SITES = "ballista.chaos.sites"
# injected delay for the `task.slow` straggler site (ISSUE 11): a task
# whose (stage, partition, attempt) coordinate draws a slow verdict sleeps
# this long before executing — deterministic stragglers for the
# p99-under-chaos bench metric. The duplicate attempt is keyed on a
# DIFFERENT attempt number, so it draws a fresh verdict.
BALLISTA_CHAOS_SLOW_MS = "ballista.chaos.slow_ms"

DEFAULT_SETTINGS: Dict[str, str] = {
    # 32768 is the reference's hard-coded default batch size
    # (rust/core/src/serde/physical_plan/from_proto.rs:100-102).
    BALLISTA_BATCH_SIZE: "32768",
    BALLISTA_BACKEND: "cpu",
    BALLISTA_STAGE_FUSION: "true",
    BALLISTA_MESH_SHAPE: "data:1",
    BALLISTA_SHUFFLE_PARTITIONS: "16",
    BALLISTA_SHUFFLE_CODEC: "",
    # local tier = the reference design (peer-served work-dir pieces);
    # "shared" requires ballista.shuffle.dir to name the storage root
    BALLISTA_SHUFFLE_TIER: "local",
    BALLISTA_SHUFFLE_DIR: "",
    # autoscaling off by default: a fixed fleet behaves exactly as before
    BALLISTA_FLEET_MIN: "1",
    BALLISTA_FLEET_MAX: "0",
    BALLISTA_FLEET_INTERVAL_S: "0.5",
    BALLISTA_FLEET_TARGET_BACKLOG_S: "1.0",
    BALLISTA_DEVICE_CACHE: "true",
    BALLISTA_TPU_HBM_BUDGET: str(12 << 30),
    # on by default: the exchange tier is bit-identical by construction
    # (registry entries are the exact batches the authoritative piece
    # holds) and every degradation path is the pre-existing ladder
    BALLISTA_TPU_EXCHANGE: "true",
    # sized well below the HBM budget: exchange pieces are transient
    # stage-boundary intermediates, not the working set
    BALLISTA_TPU_RESIDENCY_BUDGET: str(1 << 30),
    BALLISTA_SCAN_CACHE: "true",
    BALLISTA_SCAN_CACHE_CAP: str(4 << 30),
    BALLISTA_TPU_PER_OP: "false",
    # on by default since the M:N multiplicity kernel (ops/join.py): the
    # device join is bit-identical to the host oracle for any build-key
    # multiplicity and steps aside with a reason past the admission tiers
    BALLISTA_TPU_DEVICE_JOIN: "true",
    BALLISTA_TPU_FUSE_VOLATILE: "false",
    BALLISTA_TPU_SPMD: "false",
    BALLISTA_TPU_COALESCE_AGG: "auto",
    # sized for TPC-H SF=100 (leaf parquet ~18 GB): narrow residency keeps
    # the DEVICE footprint at roughly on-disk scale (~2.2x below decoded
    # int32/f32), and the fact-agg top-k epilogue only exists on the
    # SINGLE-mode plan — a smaller cap silently pushed q3/q5 onto the
    # partial/final host path at exactly the scale the ≥5x target names
    BALLISTA_TPU_COALESCE_MAX: str(24 << 30),
    BALLISTA_TPU_SORTED_KERNEL: "layout",
    # cwd-relative by default (like .pytest_cache) so warm starts survive
    # process restarts without writing outside the working tree; set an
    # absolute path for daemons with volatile cwds, "" disables persistence
    BALLISTA_TPU_LAYOUT_CACHE_DIR: ".ballista_cache/layouts",
    BALLISTA_TPU_LAYOUT_CACHE_CAP: str(48 << 30),
    BALLISTA_TPU_INGEST_WORKERS: "2",
    BALLISTA_TPU_INGEST_DEPTH: "2",
    BALLISTA_DATA_ROOTS: "",
    BALLISTA_MAX_TASK_RETRIES: "3",
    BALLISTA_TENANT: "",
    BALLISTA_TENANT_PRIORITY: "0",
    BALLISTA_TENANT_MAX_INFLIGHT: "0",
    BALLISTA_TENANT_WEIGHTS: "",
    BALLISTA_RESULT_CACHE: "true",
    BALLISTA_RESULT_CACHE_MAX_ENTRIES: "1024",
    BALLISTA_RESULT_CACHE_TTL_S: "0",
    # advancement defaults OFF: it changes how a repeated query over grown
    # inputs executes (delta job + fold instead of a full run); the
    # bit-identity invariant is fuzz-checked but the workload class is
    # opt-in like streaming ingestion itself
    BALLISTA_CACHE_ADVANCE: "false",
    BALLISTA_TENANT_RESIDENCY_BUDGET: "0",
    BALLISTA_PLAN_CACHE: "true",
    BALLISTA_PUSH_DISPATCH: "true",
    BALLISTA_IDLE_POLL_MAX_S: "2",
    # cwd-relative beside the layout cache (same rationale: warm starts
    # survive process restarts without writing outside the working tree)
    BALLISTA_TPU_AOT_CACHE_DIR: ".ballista_cache/aot",
    BALLISTA_TPU_PREWARM: "false",
    BALLISTA_STREAM_RESULTS: "false",
    # default ON with the static ladder as cold-start prior + safety cap: a
    # cold (or absent, or corrupt) store reproduces pre-adaptive routing
    BALLISTA_TPU_COST_MODEL: "true",
    # cwd-relative beside the layout/AOT caches (same rationale)
    BALLISTA_TPU_COST_MODEL_DIR: ".ballista_cache/costmodel",
    BALLISTA_RPC_RETRIES: "3",
    BALLISTA_RPC_BACKOFF_MS: "50",
    BALLISTA_SCHEDULER_LEASE_TTL_S: "5",
    BALLISTA_DEBUG_LOCK_WITNESS: "false",
    BALLISTA_CHAOS_SEED: "0",
    BALLISTA_CHAOS_RATE: "0",
    BALLISTA_CHAOS_SITES: "",
    BALLISTA_CHAOS_SLOW_MS: "1000",
    BALLISTA_TENANT_SLO_MS: "",
    # speculation defaults ON: the 500ms floor + 4x slack mean fault-free
    # runs (tasks well under the floor, or within slack of prediction)
    # never launch a duplicate — only genuine stragglers do
    BALLISTA_SPECULATION: "true",
    BALLISTA_SPECULATION_MULTIPLIER: "4",
    BALLISTA_SPECULATION_MIN_RUNTIME_MS: "500",
    BALLISTA_SPECULATION_MAX_ATTEMPTS: "2",
    BALLISTA_PUSH_STATUS: "true",
    # shared-scan batching defaults ON: a batch is only formed from
    # co-pending compatible stages, degrades to solo on any doubt, and is
    # bit-identical to solo execution by construction
    BALLISTA_SHARED_SCAN: "true",
    BALLISTA_SHARED_SCAN_MAX_BATCH: "8",
}


class BallistaConfig(Mapping[str, str]):
    """Immutable string->string settings map with typed accessors."""

    def __init__(self, settings: Optional[Mapping[str, str]] = None) -> None:
        merged = dict(DEFAULT_SETTINGS)
        if settings:
            merged.update({str(k): str(v) for k, v in settings.items()})
        self._settings = merged

    # Mapping interface ----------------------------------------------------
    def __getitem__(self, key: str) -> str:
        return self._settings[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._settings)

    def __len__(self) -> int:
        return len(self._settings)

    # Typed accessors ------------------------------------------------------
    def batch_size(self) -> int:
        return int(self._settings[BALLISTA_BATCH_SIZE])

    def backend(self) -> str:
        return self._settings[BALLISTA_BACKEND]

    def stage_fusion(self) -> bool:
        return self._settings[BALLISTA_STAGE_FUSION].lower() in ("1", "true", "yes")

    def shuffle_codec(self) -> str:
        c = self._settings[BALLISTA_SHUFFLE_CODEC].strip().lower()
        if c in ("", "none", "off"):
            return ""
        if c not in ("zstd", "lz4"):
            raise ValueError(f"unsupported shuffle codec {c!r} (zstd|lz4)")
        return c

    def shuffle_partitions(self) -> int:
        return int(self._settings[BALLISTA_SHUFFLE_PARTITIONS])

    def shuffle_tier(self) -> str:
        """Where shuffle pieces live: "local" (executor work dirs, peer-
        served over Flight) or "shared" (the disaggregated storage tier,
        ISSUE 15)."""
        t = self._settings[BALLISTA_SHUFFLE_TIER].strip().lower()
        if t not in ("local", "shared"):
            raise ValueError(f"unknown shuffle tier {t!r} (local|shared)")
        return t

    def shuffle_dir(self) -> str:
        """Expanded shared-storage root for the "shared" shuffle tier;
        "" = unset (required when the tier is shared)."""
        import os

        d = self._settings[BALLISTA_SHUFFLE_DIR].strip()
        return os.path.expanduser(d) if d else ""

    def shuffle_storage_root(self) -> str:
        """The shared-storage root when the shared tier is ACTIVE, else "".
        The one check writers/readers consult: a shared tier without a
        configured directory is a misconfiguration and raises here (never
        silently degrades to local — the operator asked for durability)."""
        if self.shuffle_tier() != "shared":
            return ""
        d = self.shuffle_dir()
        if not d:
            raise ValueError(
                "ballista.shuffle.tier=shared requires ballista.shuffle.dir"
            )
        return d

    def fleet_min(self) -> int:
        """Autoscaler floor (ISSUE 15): the fleet never drains below this."""
        return max(1, int(self._settings[BALLISTA_FLEET_MIN]))

    def fleet_max(self) -> int:
        """Autoscaler ceiling; 0 disables autoscaling (fixed fleet)."""
        return max(0, int(self._settings[BALLISTA_FLEET_MAX]))

    def fleet_interval_s(self) -> float:
        """Seconds between autoscaler evaluations."""
        return max(0.05, float(self._settings[BALLISTA_FLEET_INTERVAL_S]))

    def fleet_target_backlog_s(self) -> float:
        """Predicted backlog seconds one evaluation tolerates before the
        fleet grows (also the growth denominator)."""
        return max(
            1e-3, float(self._settings[BALLISTA_FLEET_TARGET_BACKLOG_S])
        )

    def device_cache(self) -> bool:
        return self._settings[BALLISTA_DEVICE_CACHE].lower() in ("1", "true", "yes")

    def scan_cache(self) -> bool:
        return self._settings[BALLISTA_SCAN_CACHE].lower() in ("1", "true", "yes")

    def scan_cache_cap(self) -> int:
        return int(self._settings[BALLISTA_SCAN_CACHE_CAP])

    def tpu_per_op(self) -> bool:
        return self._settings[BALLISTA_TPU_PER_OP].lower() in ("1", "true", "yes")

    def tpu_device_join(self) -> bool:
        return self._settings[BALLISTA_TPU_DEVICE_JOIN].lower() in ("1", "true", "yes")

    def tpu_fuse_volatile(self) -> bool:
        return self._settings[BALLISTA_TPU_FUSE_VOLATILE].lower() in ("1", "true", "yes")

    def tpu_spmd(self) -> bool:
        return self._settings[BALLISTA_TPU_SPMD].lower() in ("1", "true", "yes")

    def tpu_coalesce_aggregates(self) -> bool:
        v = self._settings[BALLISTA_TPU_COALESCE_AGG].strip().lower()
        if v == "auto":
            return self.backend() == "tpu" and not self.tpu_spmd()
        return v in ("1", "true", "yes")

    def tpu_coalesce_max_bytes(self) -> int:
        return int(self._settings[BALLISTA_TPU_COALESCE_MAX])

    def tpu_layout_cache_dir(self) -> str:
        """Expanded layout-cache directory; "" = persistence disabled."""
        import os

        d = self._settings[BALLISTA_TPU_LAYOUT_CACHE_DIR].strip()
        return os.path.expanduser(d) if d else ""

    def tpu_layout_cache_cap(self) -> int:
        return int(self._settings[BALLISTA_TPU_LAYOUT_CACHE_CAP])

    def tpu_sorted_kernel(self) -> str:
        k = self._settings[BALLISTA_TPU_SORTED_KERNEL].strip().lower()
        if k not in ("layout", "pallas"):
            raise ValueError(f"unknown sorted kernel {k!r} (layout|pallas)")
        return k

    def tpu_hbm_budget(self) -> int:
        return int(self._settings[BALLISTA_TPU_HBM_BUDGET])

    def tpu_exchange(self) -> bool:
        """HBM-resident cross-stage exchange tier (ISSUE 16)."""
        return self._settings[BALLISTA_TPU_EXCHANGE].lower() in (
            "1", "true", "yes"
        )

    def residency_budget(self) -> int:
        """Byte budget for registered exchange pieces per executor."""
        return int(self._settings[BALLISTA_TPU_RESIDENCY_BUDGET])

    def tpu_ingest_workers(self) -> int:
        """Prefetch-stage worker threads; 0 = serial ingest (no threads)."""
        return max(0, int(self._settings[BALLISTA_TPU_INGEST_WORKERS]))

    def tpu_ingest_depth(self) -> int:
        """Bound on prefetched items in flight (host-RSS cap)."""
        return max(1, int(self._settings[BALLISTA_TPU_INGEST_DEPTH]))

    def max_task_retries(self) -> int:
        """Requeues allowed per task before the job fails (0 = reference
        behavior: first failure kills the job)."""
        return max(0, int(self._settings[BALLISTA_MAX_TASK_RETRIES]))

    def tenant(self) -> str:
        """Submitting tenant name; "" = the default (unnamed) tenant."""
        return self._settings[BALLISTA_TENANT].strip()

    def tenant_priority(self) -> int:
        """Per-job priority within the tenant (higher schedules first)."""
        return max(0, int(self._settings[BALLISTA_TENANT_PRIORITY]))

    def tenant_max_inflight(self) -> int:
        """Per-tenant in-flight task quota (0 = unlimited)."""
        return max(0, int(self._settings[BALLISTA_TENANT_MAX_INFLIGHT]))

    def tenant_weights(self) -> Dict[str, int]:
        """Fair-share weights parsed from "alice:4,bob:1"; absent -> 1."""
        out: Dict[str, int] = {}
        for part in self._settings[BALLISTA_TENANT_WEIGHTS].split(","):
            part = part.strip()
            if not part:
                continue
            name, _, w = part.rpartition(":")
            if not name:
                raise ValueError(
                    f"bad {BALLISTA_TENANT_WEIGHTS} entry {part!r} "
                    "(expected tenant:weight)"
                )
            out[name.strip()] = max(1, int(w))
        return out

    def tenant_slos(self) -> Dict[str, float]:
        """Per-tenant latency SLO deadlines in ms parsed from
        "alice:250,bob:2000"; absent -> no SLO for that tenant."""
        out: Dict[str, float] = {}
        for part in self._settings[BALLISTA_TENANT_SLO_MS].split(","):
            part = part.strip()
            if not part:
                continue
            name, _, ms = part.rpartition(":")
            if not name:
                raise ValueError(
                    f"bad {BALLISTA_TENANT_SLO_MS} entry {part!r} "
                    "(expected tenant:milliseconds)"
                )
            out[name.strip()] = max(1.0, float(ms))
        return out

    def speculation(self) -> bool:
        """Speculative duplicate attempts for cost-model-flagged stragglers
        (ISSUE 11)."""
        return self._settings[BALLISTA_SPECULATION].lower() in ("1", "true", "yes")

    def speculation_multiplier(self) -> float:
        """Slack factor over the predicted task cost before a RUNNING task
        counts as a straggler."""
        return max(1.0, float(self._settings[BALLISTA_SPECULATION_MULTIPLIER]))

    def speculation_min_runtime_s(self) -> float:
        """Minimum elapsed seconds before any task may speculate — cheap
        tasks never do."""
        return max(
            0.0, float(self._settings[BALLISTA_SPECULATION_MIN_RUNTIME_MS])
        ) / 1000.0

    def speculation_max_attempts(self) -> int:
        """Most speculative duplicates one task may accumulate (ISSUE 15
        satellite): past the first, only a duplicate that itself straggles
        earns a successor. Minimum 1 (the launch-once behavior)."""
        return max(1, int(self._settings[BALLISTA_SPECULATION_MAX_ATTEMPTS]))

    def shared_scan(self) -> bool:
        """Shared-scan multi-query batching (ISSUE 13): concurrent jobs'
        compatible fused-aggregate stages dispatch as one batched task."""
        return self._settings[BALLISTA_SHARED_SCAN].lower() in ("1", "true", "yes")

    def shared_scan_max_batch(self) -> int:
        """Most member tasks per batched dispatch (minimum 2)."""
        return max(2, int(self._settings[BALLISTA_SHARED_SCAN_MAX_BATCH]))

    def push_status(self) -> bool:
        """Client-side server-push job-status notifications (ISSUE 11)."""
        return self._settings[BALLISTA_PUSH_STATUS].lower() in ("1", "true", "yes")

    def result_cache(self) -> bool:
        return self._settings[BALLISTA_RESULT_CACHE].lower() in ("1", "true", "yes")

    def result_cache_max_entries(self) -> int:
        """Live result-cache entry cap (0 = unbounded)."""
        return max(0, int(self._settings[BALLISTA_RESULT_CACHE_MAX_ENTRIES]))

    def result_cache_ttl_s(self) -> float:
        """Result-cache entry time-to-live in seconds (0 = no expiry)."""
        return max(0.0, float(self._settings[BALLISTA_RESULT_CACHE_TTL_S]))

    def cache_advance(self) -> bool:
        """Result-cache delta advancement over grown scan-file sets
        (ISSUE 19). Requires the result cache itself."""
        return self._settings[BALLISTA_CACHE_ADVANCE].lower() in ("1", "true", "yes")

    def tenant_residency_budget(self) -> int:
        """Per-tenant exchange-registry residency cap in bytes (0 =
        unlimited; ISSUE 19 satellite)."""
        return max(0, int(self._settings[BALLISTA_TENANT_RESIDENCY_BUDGET]))

    def plan_cache(self) -> bool:
        return self._settings[BALLISTA_PLAN_CACHE].lower() in ("1", "true", "yes")

    def push_dispatch(self) -> bool:
        """Push-based task dispatch over SubscribeWork (ISSUE 8)."""
        return self._settings[BALLISTA_PUSH_DISPATCH].lower() in ("1", "true", "yes")

    def idle_poll_max_s(self) -> float:
        """Ceiling of the adaptive idle-poll backoff while the push stream
        is healthy; the floor is the 250ms reference interval."""
        return max(0.25, float(self._settings[BALLISTA_IDLE_POLL_MAX_S]))

    def tpu_aot_cache_dir(self) -> str:
        """Expanded AOT program-cache directory; "" = disabled."""
        import os

        d = self._settings[BALLISTA_TPU_AOT_CACHE_DIR].strip()
        return os.path.expanduser(d) if d else ""

    def tpu_prewarm(self) -> bool:
        """Load + compile every AOT-cache manifest entry at executor start."""
        return self._settings[BALLISTA_TPU_PREWARM].lower() in ("1", "true", "yes")

    def stream_results(self) -> bool:
        """Client-side streaming result fetch (ISSUE 8)."""
        return self._settings[BALLISTA_STREAM_RESULTS].lower() in ("1", "true", "yes")

    def tpu_cost_model(self) -> bool:
        """Adaptive execution (ISSUE 10): measured-cost routing on top of
        the static decline ladder. False = pure static ladder."""
        return self._settings[BALLISTA_TPU_COST_MODEL].lower() in ("1", "true", "yes")

    def tpu_cost_model_dir(self) -> str:
        """Expanded cost-store directory; "" = in-memory only."""
        import os

        d = self._settings[BALLISTA_TPU_COST_MODEL_DIR].strip()
        return os.path.expanduser(d) if d else ""

    def rpc_retries(self) -> int:
        """Transient-RPC retry attempts beyond the first call."""
        return max(0, int(self._settings[BALLISTA_RPC_RETRIES]))

    def rpc_backoff_s(self) -> float:
        """Jittered-exponential backoff base, in seconds."""
        return max(0.0, float(self._settings[BALLISTA_RPC_BACKOFF_MS])) / 1000.0

    def scheduler_lease_ttl_s(self) -> float:
        """Job-ownership lease TTL (ISSUE 20); the failover detection bound."""
        ttl = float(self._settings[BALLISTA_SCHEDULER_LEASE_TTL_S])
        if ttl <= 0:
            raise ValueError(
                f"ballista.scheduler.lease_ttl_s must be > 0, got {ttl}"
            )
        return ttl

    def debug_lock_witness(self) -> bool:
        # ISSUE 14: arm the dynamic lock-order witness (utils/locks.py)
        return self._settings[BALLISTA_DEBUG_LOCK_WITNESS].lower() in ("1", "true", "yes")

    def chaos_seed(self) -> int:
        return int(self._settings[BALLISTA_CHAOS_SEED])

    def chaos_rate(self) -> float:
        r = float(self._settings[BALLISTA_CHAOS_RATE])
        if not 0.0 <= r <= 1.0:
            raise ValueError(f"ballista.chaos.rate must be in [0, 1], got {r}")
        return r

    def chaos_slow_ms(self) -> float:
        """Injected straggler delay for the task.slow chaos site."""
        return max(0.0, float(self._settings[BALLISTA_CHAOS_SLOW_MS]))

    def chaos_sites(self):
        """Enabled injection sites; [] = all registered sites."""
        return [
            s.strip()
            for s in self._settings[BALLISTA_CHAOS_SITES].split(",")
            if s.strip()
        ]

    def data_roots(self):
        """Directory allowlist for wire-plan scan paths; [] = unrestricted."""
        return [
            r.strip()
            for r in self._settings[BALLISTA_DATA_ROOTS].split(",")
            if r.strip()
        ]

    def mesh_shape(self) -> Dict[str, int]:
        """Parse "data:4,model:2" into {"data": 4, "model": 2}."""
        out: Dict[str, int] = {}
        for part in self._settings[BALLISTA_MESH_SHAPE].split(","):
            part = part.strip()
            if not part:
                continue
            name, _, n = part.partition(":")
            out[name.strip()] = int(n)
        return out

    def explicit_settings(self) -> Dict[str, str]:
        """Settings that differ from the defaults — what a client should
        transmit per job so it overrides only what the user actually set
        (sending the full map would clobber executor-local tuning with
        client-side defaults)."""
        return {
            k: v
            for k, v in self._settings.items()
            if DEFAULT_SETTINGS.get(k) != v
        }

    def with_setting(self, key: str, value: str) -> "BallistaConfig":
        s = dict(self._settings)
        s[key] = value
        return BallistaConfig(s)

    def to_dict(self) -> Dict[str, str]:
        return dict(self._settings)

    def __repr__(self) -> str:
        return f"BallistaConfig({self._settings!r})"

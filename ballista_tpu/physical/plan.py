"""Physical plan base classes.

An ExecutionPlan mirrors the reference's (DataFusion's) trait: a schema, an
output partitioning, children, and ``execute(partition)`` yielding Arrow
record batches (reference rust/core/src/execution_plans/query_stage.rs:59-85
shows the passthrough pattern). ``TaskContext`` carries session config and the
kernel backend (cpu Arrow oracle vs. tpu JAX lowering) — the executor-selection
boundary from BASELINE's north star.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import pyarrow as pa

from ballista_tpu.config import BallistaConfig
from ballista_tpu.errors import PlanError


class Partitioning:
    """Output partitioning declaration (reference PhysicalHashRepartition /
    output_partitioning())."""

    def __init__(self, scheme: str, n: int, exprs: Optional[list] = None) -> None:
        assert scheme in ("unknown", "round_robin", "hash")
        self.scheme = scheme
        self.n = n
        self.exprs = exprs or []

    @classmethod
    def unknown(cls, n: int) -> "Partitioning":
        return cls("unknown", n)

    @classmethod
    def round_robin(cls, n: int) -> "Partitioning":
        return cls("round_robin", n)

    @classmethod
    def hash(cls, exprs: list, n: int) -> "Partitioning":
        return cls("hash", n, exprs)

    def partition_count(self) -> int:
        return self.n

    def __repr__(self) -> str:
        if self.scheme == "hash":
            return f"Hash([{', '.join(str(e) for e in self.exprs)}], {self.n})"
        return f"{self.scheme}({self.n})"


class TaskContext:
    """Per-task runtime context: config, kernel backend, shuffle fetcher."""

    def __init__(
        self,
        config: Optional[BallistaConfig] = None,
        shuffle_fetcher=None,
        work_dir: Optional[str] = None,
        job_id: str = "",
        attempt: int = 0,
        executor_id: str = "",
    ) -> None:
        self.config = config or BallistaConfig()
        # shuffle_fetcher: callable(PartitionLocation) -> Iterator[RecordBatch];
        # bound by the executor runtime for ShuffleReaderExec.
        self.shuffle_fetcher = shuffle_fetcher
        self.work_dir = work_dir
        self.job_id = job_id
        # which attempt of the task this context serves: part of the chaos
        # injection key so a retried attempt draws a fresh fault verdict
        self.attempt = attempt
        # which executor runs this task: the HBM-resident exchange registry
        # (ops/exchange.py, ISSUE 16) keys entries per executor, so a
        # StandaloneCluster's co-resident executors never see false "local"
        # hits. Empty (the in-process/local-engine default) disables the
        # exchange tier for this context.
        self.executor_id = executor_id

    @property
    def batch_size(self) -> int:
        return self.config.batch_size()

    @property
    def backend(self) -> str:
        return self.config.backend()


class ExecutionPlan:
    """Base physical operator."""

    def schema(self) -> pa.Schema:
        raise NotImplementedError

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> List["ExecutionPlan"]:
        return []

    def with_children(self, children: List["ExecutionPlan"]) -> "ExecutionPlan":
        if children:
            raise PlanError(f"{type(self).__name__} takes no children")
        return self

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        raise NotImplementedError

    # -- display -----------------------------------------------------------
    def fmt(self) -> str:
        return type(self).__name__

    def display_indent(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.fmt()]
        for c in self.children():
            lines.append(c.display_indent(indent + 1))
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.display_indent()


def collect_partition(
    plan: ExecutionPlan, partition: int, ctx: TaskContext
) -> pa.Table:
    """Drain one partition into a Table (reference utils.rs collect_stream)."""
    batches = list(plan.execute(partition, ctx))
    if not batches:
        return pa.table(
            {f.name: pa.array([], type=f.type) for f in plan.schema()},
            schema=plan.schema(),
        )
    return pa.Table.from_batches(batches, schema=plan.schema())


def collect_all(plan: ExecutionPlan, ctx: TaskContext) -> pa.Table:
    """Drain every partition (reference executor CollectExec select_all,
    rust/executor/src/collect.rs:70-101)."""
    tables = [
        collect_partition(plan, p, ctx)
        for p in range(plan.output_partitioning().partition_count())
    ]
    return pa.concat_tables(tables)


def batch_table(table: pa.Table, batch_size: int) -> Iterator[pa.RecordBatch]:
    """Re-chunk a table into batches of at most batch_size rows."""
    if table.num_rows == 0:
        yield from table.to_batches()
        return
    for b in table.combine_chunks().to_batches(max_chunksize=batch_size):
        yield b

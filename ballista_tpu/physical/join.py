"""Join operators.

HashJoinExec follows the reference's collect-left build model
(HashJoinExecNode, rust/core/proto/ballista.proto:386-397; serde
rust/core/src/serde/physical_plan/from_proto.rs:176-214): the left child is
collected once as the build side, the right child is probed per-partition.
SEMI/ANTI joins (added beyond the reference's Inner/Left/Right for TPC-H
subquery decorrelation) build on the right and probe left, preserving left
partitioning.
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ballista_tpu.errors import PlanError
from ballista_tpu.logical.plan import JoinType
from ballista_tpu.physical.joinutil import combined_key_codes, join_indices, take_table
from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
    collect_all,
    collect_partition,
)
from ballista_tpu.utils.locks import make_lock


class HashJoinExec(ExecutionPlan):
    def __init__(
        self,
        left: ExecutionPlan,
        right: ExecutionPlan,
        on: List[Tuple[str, str]],  # (left column name, right column name)
        join_type: JoinType,
        filter=None,  # residual PhysicalExpr over concat(left, right) schema
        partitioned: bool = False,
    ) -> None:
        self.left = left
        self.right = right
        self.on = on
        self.join_type = join_type
        self.filter = filter
        # both inputs hash-co-partitioned on the join keys: each partition
        # pair joins independently (the planner arranges this for outer
        # joins, removing the single-partition probe wall — every key lands
        # in exactly one partition, so per-partition unmatched rows are
        # globally unmatched)
        self.partitioned = partitioned
        if filter is not None and join_type not in (JoinType.SEMI, JoinType.ANTI):
            raise PlanError("join residual filter only supported for SEMI/ANTI")
        if join_type in (JoinType.SEMI, JoinType.ANTI):
            self._schema = left.schema()
        else:
            self._schema = pa.schema(list(left.schema()) + list(right.schema()))
        self._build_lock = make_lock("physical.join._build_lock")
        self._build_table: Optional[pa.Table] = None  # guarded-by: self._build_lock

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        if self.join_type in (JoinType.SEMI, JoinType.ANTI):
            return self.left.output_partitioning()
        return self.right.output_partitioning()

    def children(self) -> List[ExecutionPlan]:
        return [self.left, self.right]

    def with_children(self, children: List[ExecutionPlan]) -> "HashJoinExec":
        return HashJoinExec(
            children[0], children[1], self.on, self.join_type,
            filter=self.filter, partitioned=self.partitioned,
        )

    # executes an arbitrary child plan while holding the build lock —
    # static call resolution cannot chase plan dispatch, so the reachable
    # lock set is declared (witness-verified)
    # may-acquire: group:exec_substrate
    def _collect_build(self, side: ExecutionPlan, ctx: TaskContext) -> pa.Table:
        with self._build_lock:
            if self._build_table is None:
                self._build_table = collect_all(side, ctx)
            return self._build_table

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        left_keys = [n for n, _ in self.on]
        right_keys = [n for _, n in self.on]

        if self.join_type in (JoinType.SEMI, JoinType.ANTI):
            # build on RIGHT, probe LEFT partitions
            build = self._collect_build(self.right, ctx)
            probe = collect_partition(self.left, partition, ctx)
            bcodes, pcodes = combined_key_codes(
                [build.column(k) for k in right_keys],
                [probe.column(k) for k in left_keys],
            )
            keep_idx = None
            if (self.filter is None and ctx.backend == "tpu"
                    and ctx.config.tpu_device_join()):
                # EXISTS / NOT EXISTS as device membership counting (q22):
                # the per-probe counts plane decides kept rows — counts > 0
                # keeps SEMI rows, counts == 0 keeps ANTI rows — exactly
                # the host oracle's semi_right/anti_right selections, so
                # results are bit-identical. Declines (None, with a
                # recorded reason) fall through to the host path.
                from ballista_tpu.ops import aotcache, costmodel
                from ballista_tpu.ops.join import device_membership_counts

                aotcache.configure(ctx.config)
                costmodel.configure(ctx.config)
                counts = device_membership_counts(bcodes, pcodes)
                if counts is not None:
                    keep = counts > 0 if self.join_type == JoinType.SEMI \
                        else counts == 0
                    keep_idx = np.nonzero(keep)[0]
            if keep_idx is None:
                if self.filter is None:
                    how = "semi_right" if self.join_type == JoinType.SEMI else "anti_right"
                    keep_idx, _ = join_indices(bcodes, pcodes, how)
                else:
                    keep_idx = self._filtered_semi_indices(build, probe, bcodes, pcodes)
            out = probe.take(pa.array(keep_idx))
            yield from batch_table(out, ctx.batch_size)
            return

        if self.partitioned:
            build = collect_partition(self.left, partition, ctx)
        else:
            build = self._collect_build(self.left, ctx)
        probe = collect_partition(self.right, partition, ctx)
        device_declined = False
        if (self.join_type == JoinType.INNER and ctx.backend == "tpu"
                and ctx.config.tpu_device_join()):
            # device M:N join: sorted paired binary search + bounded-width
            # gather on TPU, duplicate build keys included; declines (None,
            # always with a recorded reason) fall through to the host join.
            # The cost model (ISSUE 10) rides the config: partial offload,
            # extended tiers, and build-side switching on observed
            # cardinality misestimates — all bit-identical to the host.
            from ballista_tpu.ops import aotcache, costmodel
            from ballista_tpu.ops.join import try_device_inner_join

            aotcache.configure(ctx.config)
            costmodel.configure(ctx.config)
            res = try_device_inner_join(
                build, probe, left_keys, right_keys, config=ctx.config
            )
            if res is not None:
                left_idx, right_idx = res
                left_out = take_table(build, left_idx)
                right_out = take_table(probe, right_idx)
                cols = list(left_out.columns) + list(right_out.columns)
                out = pa.table(cols, schema=self._schema)
                yield from batch_table(out, ctx.batch_size)
                return
            device_declined = True
        bcodes, pcodes = combined_key_codes(
            [build.column(k) for k in left_keys],
            [probe.column(k) for k in right_keys],
        )
        how = {
            JoinType.INNER: "inner",
            JoinType.LEFT: "left",
            JoinType.RIGHT: "right",
            JoinType.FULL: "full",
        }[self.join_type]
        if (
            how in ("left", "full")
            and not self.partitioned
            and self.right.output_partitioning().partition_count() > 1
        ):
            raise PlanError(
                f"{how} join requires co-partitioned inputs or a "
                "single-partition probe side"
            )
        if device_declined:
            # the host join after a device decline is the device's
            # alternative cost: measure it so tier selection learns what
            # host-wholesale actually costs at this scale
            from ballista_tpu.ops import costmodel

            with costmodel.timed("join.host", len(bcodes) + len(pcodes),
                                 engine="host", predictive=False):
                left_idx, right_idx = join_indices(bcodes, pcodes, how)
        else:
            left_idx, right_idx = join_indices(bcodes, pcodes, how)
        left_out = take_table(build, left_idx)
        right_out = take_table(probe, right_idx)
        cols = list(left_out.columns) + list(right_out.columns)
        out = pa.table(cols, schema=self._schema)
        yield from batch_table(out, ctx.batch_size)

    def _filtered_semi_indices(
        self,
        build: pa.Table,
        probe: pa.Table,
        bcodes: np.ndarray,
        pcodes: np.ndarray,
    ) -> np.ndarray:
        """SEMI/ANTI with a residual predicate: expand the inner join on the
        equi keys, evaluate the filter over concat(probe-cols, build-cols),
        keep probe rows with >=1 surviving match (SEMI) or none (ANTI)."""
        import pyarrow.compute as pc

        build_idx, probe_idx = join_indices(bcodes, pcodes, "inner")
        matched = np.zeros(probe.num_rows, dtype=bool)
        if len(probe_idx):
            probe_rows = probe.take(pa.array(probe_idx))
            build_rows = build.take(pa.array(build_idx))
            combined_schema = pa.schema(list(probe.schema) + list(build.schema))
            combined = pa.table(
                list(probe_rows.columns) + list(build_rows.columns),
                schema=combined_schema,
            ).combine_chunks()
            batches = combined.to_batches()
            offset = 0
            for b in batches:
                mask = self.filter.evaluate(b)
                mask_np = pc.fill_null(mask, False).to_numpy(zero_copy_only=False)
                hits = probe_idx[offset: offset + b.num_rows][mask_np.astype(bool)]
                matched[hits] = True
                offset += b.num_rows
        if self.join_type == JoinType.SEMI:
            return np.nonzero(matched)[0]
        return np.nonzero(~matched)[0]

    def fmt(self) -> str:
        on = ", ".join(f"{l} = {r}" for l, r in self.on)
        extra = f", filter={self.filter}" if self.filter is not None else ""
        return f"HashJoinExec: type={self.join_type.value}, on=[{on}]{extra}"


class CrossJoinExec(ExecutionPlan):
    """Cartesian product: left collected as build, right probed per-partition."""

    def __init__(self, left: ExecutionPlan, right: ExecutionPlan) -> None:
        self.left = left
        self.right = right
        self._schema = pa.schema(list(left.schema()) + list(right.schema()))
        self._build_lock = make_lock("physical.join._build_lock")
        self._build_table: Optional[pa.Table] = None  # guarded-by: self._build_lock

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return self.right.output_partitioning()

    def children(self) -> List[ExecutionPlan]:
        return [self.left, self.right]

    def with_children(self, children: List[ExecutionPlan]) -> "CrossJoinExec":
        return CrossJoinExec(children[0], children[1])

    # may-acquire: group:exec_substrate
    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        with self._build_lock:
            if self._build_table is None:
                self._build_table = collect_all(self.left, ctx)
            # read under the lock: the unguarded read-after-release here
            # was the ISSUE 14 sweep's first guarded-by finding
            build = self._build_table
        probe = collect_partition(self.right, partition, ctx)
        nb, np_ = build.num_rows, probe.num_rows
        if nb == 0 or np_ == 0:
            return
        left_idx = np.tile(np.arange(nb, dtype=np.int64), np_)
        right_idx = np.repeat(np.arange(np_, dtype=np.int64), nb)
        left_out = build.take(pa.array(left_idx))
        right_out = probe.take(pa.array(right_idx))
        cols = list(left_out.columns) + list(right_out.columns)
        out = pa.table(cols, schema=self._schema)
        yield from batch_table(out, ctx.batch_size)

    def fmt(self) -> str:
        return "CrossJoinExec"

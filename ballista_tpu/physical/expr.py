"""Physical expressions: evaluated against Arrow RecordBatches.

The host (CPU) kernel path uses pyarrow.compute — the correctness oracle and
default executor backend, playing the role DataFusion's physical expressions
play in the reference (compiled there via DefaultPhysicalPlanner,
rust/core/src/serde/physical_plan/from_proto.rs:348-365). The TPU backend
(ballista_tpu.ops) lowers whole operator pipelines instead of single exprs.
"""

from __future__ import annotations

import datetime
from typing import Any, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.errors import ExecutionError, PlanError
from ballista_tpu.logical import expr as lx


class PhysicalExpr:
    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        raise NotImplementedError

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        raise NotImplementedError

    def children(self) -> List["PhysicalExpr"]:
        return []

    def __str__(self) -> str:
        raise NotImplementedError


def _as_array(value: Any, length: int, dtype: Optional[pa.DataType] = None) -> pa.Array:
    """Broadcast a scalar result to an array of the batch length."""
    if isinstance(value, (pa.Array, pa.ChunkedArray)):
        if isinstance(value, pa.ChunkedArray):
            return value.combine_chunks()
        return value
    if isinstance(value, pa.Scalar):
        return pa.repeat(value, length)
    return pa.repeat(pa.scalar(value, type=dtype), length)


class ColumnExpr(PhysicalExpr):
    def __init__(self, name: str, index: int) -> None:
        self.name = name
        self.index = index

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return batch.column(self.index)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return schema.field(self.index).type

    def __str__(self) -> str:
        return f"{self.name}@{self.index}"


class LiteralExpr(PhysicalExpr):
    def __init__(self, value: Any, dtype: pa.DataType) -> None:
        self.value = value
        self.dtype = dtype

    def scalar(self) -> pa.Scalar:
        return pa.scalar(self.value, type=self.dtype)

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return pa.repeat(self.scalar(), batch.num_rows)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.dtype

    def __str__(self) -> str:
        return repr(self.value)


_CMP_FN = {
    "eq": pc.equal,
    "neq": pc.not_equal,
    "lt": pc.less,
    "lteq": pc.less_equal,
    "gt": pc.greater,
    "gteq": pc.greater_equal,
}

_ARITH_FN = {
    "plus": pc.add,
    "minus": pc.subtract,
    "multiply": pc.multiply,
}


def _modulo(left: pa.Array, right: pa.Array) -> pa.Array:
    l = left.to_numpy(zero_copy_only=False)
    r = right.to_numpy(zero_copy_only=False)
    return pa.array(np.mod(l, r))


class BinaryPhysicalExpr(PhysicalExpr):
    def __init__(self, left: PhysicalExpr, op: str, right: PhysicalExpr) -> None:
        self.left = left
        self.op = op
        self.right = right

    def children(self) -> List[PhysicalExpr]:
        return [self.left, self.right]

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        n = batch.num_rows
        lv = _as_array(self.left.evaluate(batch), n)
        rv = _as_array(self.right.evaluate(batch), n)
        op = self.op
        if op in _CMP_FN:
            return _CMP_FN[op](lv, rv)
        if op == "and":
            return pc.and_kleene(lv, rv)
        if op == "or":
            return pc.or_kleene(lv, rv)
        if op == "like":
            return pc.match_like(lv, self._pattern())
        if op == "not_like":
            return pc.invert(pc.match_like(lv, self._pattern()))
        if op in _ARITH_FN:
            return _ARITH_FN[op](lv, rv)
        if op == "divide":
            return pc.divide(lv, rv)
        if op == "modulo":
            return _modulo(lv, rv)
        raise ExecutionError(f"unsupported binary op {op!r}")

    def _pattern(self) -> str:
        if not isinstance(self.right, LiteralExpr):
            raise ExecutionError("LIKE pattern must be a literal")
        return str(self.right.value)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        if self.op in _CMP_FN or self.op in ("and", "or", "like", "not_like"):
            return pa.bool_()
        return lx.coerce_numeric(
            self.left.data_type(schema), self.right.data_type(schema)
        )

    def __str__(self) -> str:
        return f"({self.left} {self.op} {self.right})"


class NotExpr(PhysicalExpr):
    def __init__(self, expr: PhysicalExpr) -> None:
        self.expr = expr

    def children(self) -> List[PhysicalExpr]:
        return [self.expr]

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return pc.invert(_as_array(self.expr.evaluate(batch), batch.num_rows))

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        return f"NOT {self.expr}"


class NegativeExpr(PhysicalExpr):
    def __init__(self, expr: PhysicalExpr) -> None:
        self.expr = expr

    def children(self) -> List[PhysicalExpr]:
        return [self.expr]

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        return pc.negate(_as_array(self.expr.evaluate(batch), batch.num_rows))

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.expr.data_type(schema)

    def __str__(self) -> str:
        return f"(- {self.expr})"


class IsNullExpr(PhysicalExpr):
    def __init__(self, expr: PhysicalExpr, negated: bool = False) -> None:
        self.expr = expr
        self.negated = negated

    def children(self) -> List[PhysicalExpr]:
        return [self.expr]

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        v = _as_array(self.expr.evaluate(batch), batch.num_rows)
        return pc.is_valid(v) if self.negated else pc.is_null(v)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        return f"{self.expr} IS {'NOT ' if self.negated else ''}NULL"


class CastExpr(PhysicalExpr):
    def __init__(self, expr: PhysicalExpr, dtype: pa.DataType, safe: bool = False) -> None:
        self.expr = expr
        self.dtype = dtype
        self.safe = safe  # TryCast: null on failure

    def children(self) -> List[PhysicalExpr]:
        return [self.expr]

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        v = _as_array(self.expr.evaluate(batch), batch.num_rows)
        return pc.cast(v, self.dtype, safe=not self.safe)

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.dtype

    def __str__(self) -> str:
        return f"CAST({self.expr} AS {self.dtype})"


class InListExpr(PhysicalExpr):
    """expr [NOT] IN (members). Literal members use one hashed pc.is_in;
    expression members evaluate the probe ONCE and fold equality with
    Kleene OR. Both follow SQL three-valued logic: a NULL probe (or, for
    the expression form, NULL members that prevent a definite answer)
    yields NULL, so NOT IN never resurrects NULL rows."""

    def __init__(
        self,
        expr: PhysicalExpr,
        values: List[Any],
        negated: bool,
        value_exprs: Optional[List[PhysicalExpr]] = None,
    ) -> None:
        self.expr = expr
        self.values = values  # literals (ignored when value_exprs is set)
        self.negated = negated
        self.value_exprs = value_exprs

    def children(self) -> List[PhysicalExpr]:
        return [self.expr] + list(self.value_exprs or [])

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        v = _as_array(self.expr.evaluate(batch), batch.num_rows)
        if self.value_exprs is None:
            non_null = [x for x in self.values if x is not None]
            if not non_null:
                # IN (NULL, ...): never definitely true or false
                member = pa.nulls(len(v), pa.bool_())
            else:
                member = pc.is_in(v, value_set=pa.array(non_null))
                if len(non_null) < len(self.values):
                    # a NULL member makes non-matches indefinite (NULL)
                    member = pc.if_else(
                        member, member, pa.scalar(None, pa.bool_())
                    )
                # is_in returns FALSE for a null probe; SQL says NULL
                member = pc.if_else(
                    pc.is_valid(v), member, pa.scalar(None, pa.bool_())
                )
        else:
            member = None
            for ve in self.value_exprs:
                m = _as_array(ve.evaluate(batch), batch.num_rows)
                eq = pc.equal(v, m)
                member = eq if member is None else pc.or_kleene(member, eq)
        return pc.invert(member) if self.negated else member

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        members = self.value_exprs if self.value_exprs is not None else self.values
        return f"{self.expr} {'NOT ' if self.negated else ''}IN {members}"


class BetweenExpr(PhysicalExpr):
    def __init__(
        self, expr: PhysicalExpr, low: PhysicalExpr, high: PhysicalExpr, negated: bool
    ) -> None:
        self.expr = expr
        self.low = low
        self.high = high
        self.negated = negated

    def children(self) -> List[PhysicalExpr]:
        return [self.expr, self.low, self.high]

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        n = batch.num_rows
        v = _as_array(self.expr.evaluate(batch), n)
        lo = _as_array(self.low.evaluate(batch), n)
        hi = _as_array(self.high.evaluate(batch), n)
        result = pc.and_kleene(pc.greater_equal(v, lo), pc.less_equal(v, hi))
        return pc.invert(result) if self.negated else result

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return pa.bool_()

    def __str__(self) -> str:
        return f"{self.expr} BETWEEN {self.low} AND {self.high}"


class CaseExpr(PhysicalExpr):
    def __init__(
        self,
        base: Optional[PhysicalExpr],
        when_then: List[Tuple[PhysicalExpr, PhysicalExpr]],
        else_expr: Optional[PhysicalExpr],
        dtype: pa.DataType,
    ) -> None:
        self.base = base
        self.when_then = when_then
        self.else_expr = else_expr
        self.dtype = dtype

    def children(self) -> List[PhysicalExpr]:
        out = [] if self.base is None else [self.base]
        for w, t in self.when_then:
            out += [w, t]
        if self.else_expr is not None:
            out.append(self.else_expr)
        return out

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        n = batch.num_rows
        base = None if self.base is None else _as_array(self.base.evaluate(batch), n)
        # evaluate arms back-to-front with if_else
        if self.else_expr is not None:
            acc = pc.cast(_as_array(self.else_expr.evaluate(batch), n), self.dtype)
        else:
            acc = pa.nulls(n, type=self.dtype)
        for w, t in reversed(self.when_then):
            wv = _as_array(w.evaluate(batch), n)
            if base is not None:
                cond = pc.equal(base, wv)
            else:
                cond = wv
            cond = pc.fill_null(cond, False)
            tv = pc.cast(_as_array(t.evaluate(batch), n), self.dtype)
            acc = pc.if_else(cond, tv, acc)
        return acc

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.dtype

    def __str__(self) -> str:
        return "CASE..END"


def _extract_part(arrays: List[pa.Array], part: str) -> pa.Array:
    part = part.lower()
    fn = {
        "year": pc.year,
        "month": pc.month,
        "day": pc.day,
        "hour": pc.hour,
        "minute": pc.minute,
        "second": pc.second,
    }.get(part)
    if fn is None:
        raise ExecutionError(f"unsupported date part {part!r}")
    return pc.cast(fn(arrays[0]), pa.int64())


class ScalarFunctionExpr(PhysicalExpr):
    def __init__(self, fn: str, args: List[PhysicalExpr], dtype: pa.DataType) -> None:
        self.fn = fn
        self.args = args
        self.dtype = dtype

    def children(self) -> List[PhysicalExpr]:
        return list(self.args)

    def evaluate(self, batch: pa.RecordBatch) -> pa.Array:
        n = batch.num_rows
        argv = [_as_array(a.evaluate(batch), n) for a in self.args]
        fn = self.fn
        simple = {
            "sqrt": pc.sqrt,
            "sin": pc.sin,
            "cos": pc.cos,
            "tan": pc.tan,
            "asin": pc.asin,
            "acos": pc.acos,
            "atan": pc.atan,
            "exp": pc.exp,
            "ln": pc.ln,
            "log2": pc.log2,
            "log10": pc.log10,
            "log": pc.log10,
            "floor": pc.floor,
            "ceil": pc.ceil,
            "round": pc.round,
            "trunc": pc.trunc,
            "abs": pc.abs,
            "signum": pc.sign,
            "lower": pc.utf8_lower,
            "upper": pc.utf8_upper,
            "trim": pc.utf8_trim_whitespace,
            "ltrim": pc.utf8_ltrim_whitespace,
            "rtrim": pc.utf8_rtrim_whitespace,
            "btrim": pc.utf8_trim_whitespace,
            "length": pc.utf8_length,
            "char_length": pc.utf8_length,
            "octet_length": pc.binary_length,
        }
        if fn in simple:
            out = simple[fn](argv[0])
            if fn in ("length", "char_length", "octet_length"):
                out = pc.cast(out, pa.int64())
            return out
        if fn == "concat":
            return pc.binary_join_element_wise(*argv, "")
        if fn in ("substr", "substring"):
            start = self._const(1)  # 1-based SQL
            length = self._const(2) if len(self.args) > 2 else None
            if length is not None:
                return pc.utf8_slice_codeunits(
                    argv[0], start=start - 1, stop=start - 1 + length
                )
            return pc.utf8_slice_codeunits(argv[0], start=start - 1)
        if fn == "replace":
            return pc.replace_substring(
                argv[0], pattern=self._const(1), replacement=self._const(2)
            )
        if fn == "strpos":
            return pc.cast(
                pc.add(pc.find_substring(argv[0], pattern=self._const(1)), 1),
                pa.int64(),
            )
        if fn == "starts_with":
            return pc.starts_with(argv[0], pattern=self._const(1))
        if fn in ("extract", "date_part"):
            # extract(part, expr) — part is arg 0 as a string literal
            return _extract_part([argv[1]], self._const(0))
        if fn == "date_trunc":
            unit = self._const(0)
            return pc.floor_temporal(argv[1], unit=unit)
        if fn == "to_timestamp":
            return pc.cast(argv[0], pa.timestamp("us"))
        if fn == "now":
            return pa.repeat(
                pa.scalar(datetime.datetime.now(), type=pa.timestamp("us")), n
            )
        if fn == "coalesce":
            acc = argv[0]
            for other in argv[1:]:
                acc = pc.if_else(pc.is_valid(acc), acc, other)
            return acc
        if fn == "nullif":
            eq = pc.fill_null(pc.equal(argv[0], argv[1]), False)
            return pc.if_else(eq, pa.nulls(n, type=argv[0].type), argv[0])
        if fn in ("md5", "sha224", "sha256", "sha384", "sha512"):
            import hashlib

            h = getattr(hashlib, fn)
            vals = argv[0].to_pylist()
            return pa.array(
                [None if v is None else h(str(v).encode()).hexdigest() for v in vals]
            )
        raise ExecutionError(f"unsupported scalar function {fn!r}")

    def _const(self, i: int) -> Any:
        a = self.args[i]
        if not isinstance(a, LiteralExpr):
            raise ExecutionError(f"{self.fn} arg {i} must be a literal")
        return a.value

    def data_type(self, schema: pa.Schema) -> pa.DataType:
        return self.dtype

    def __str__(self) -> str:
        return f"{self.fn}({', '.join(str(a) for a in self.args)})"


# ---------------------------------------------------------------------------
# Logical -> physical expression compilation
# ---------------------------------------------------------------------------


def create_physical_expr(e: lx.Expr, input_schema: pa.Schema) -> PhysicalExpr:
    """Compile a logical expression against an input schema.

    The reference delegates this to DataFusion's DefaultPhysicalPlanner on a
    throwaway context (rust/core/src/serde/physical_plan/from_proto.rs:348-365).
    """
    if isinstance(e, lx.Column):
        idx = e.index_in(input_schema)
        return ColumnExpr(e.flat_name(), idx)
    if isinstance(e, lx.Literal):
        return LiteralExpr(e.value, e.dtype)
    if isinstance(e, lx.Alias):
        return create_physical_expr(e.expr, input_schema)
    if isinstance(e, lx.BinaryExpr):
        return BinaryPhysicalExpr(
            create_physical_expr(e.left, input_schema),
            e.op,
            create_physical_expr(e.right, input_schema),
        )
    if isinstance(e, lx.Not):
        return NotExpr(create_physical_expr(e.expr, input_schema))
    if isinstance(e, lx.Negative):
        return NegativeExpr(create_physical_expr(e.expr, input_schema))
    if isinstance(e, lx.IsNull):
        return IsNullExpr(create_physical_expr(e.expr, input_schema), negated=False)
    if isinstance(e, lx.IsNotNull):
        return IsNullExpr(create_physical_expr(e.expr, input_schema), negated=True)
    if isinstance(e, lx.Between):
        return BetweenExpr(
            create_physical_expr(e.expr, input_schema),
            create_physical_expr(e.low, input_schema),
            create_physical_expr(e.high, input_schema),
            e.negated,
        )
    if isinstance(e, lx.InList):
        if all(isinstance(v, lx.Literal) for v in e.values):
            values = [v.value for v in e.values]
            return InListExpr(
                create_physical_expr(e.expr, input_schema), values, e.negated
            )
        # non-literal members evaluate per row inside InListExpr (the probe
        # is computed once, not once per member)
        return InListExpr(
            create_physical_expr(e.expr, input_schema),
            [],
            e.negated,
            [create_physical_expr(v, input_schema) for v in e.values],
        )
    if isinstance(e, lx.Like):
        base = BinaryPhysicalExpr(
            create_physical_expr(e.expr, input_schema),
            "like",
            create_physical_expr(e.pattern, input_schema),
        )
        return NotExpr(base) if e.negated else base
    if isinstance(e, lx.Case):
        dtype = e.data_type(input_schema)
        return CaseExpr(
            None if e.expr is None else create_physical_expr(e.expr, input_schema),
            [
                (
                    create_physical_expr(w, input_schema),
                    create_physical_expr(t, input_schema),
                )
                for w, t in e.when_then
            ],
            None
            if e.else_expr is None
            else create_physical_expr(e.else_expr, input_schema),
            dtype,
        )
    if isinstance(e, lx.TryCast):
        return CastExpr(create_physical_expr(e.expr, input_schema), e.dtype, safe=True)
    if isinstance(e, lx.Cast):
        return CastExpr(create_physical_expr(e.expr, input_schema), e.dtype, safe=False)
    if isinstance(e, lx.ScalarFunction):
        dtype = e.data_type(input_schema)
        return ScalarFunctionExpr(
            e.fn, [create_physical_expr(a, input_schema) for a in e.args], dtype
        )
    raise PlanError(f"cannot compile logical expr {e!r} ({type(e).__name__})")

"""Physical planner: logical plan -> execution plan.

Plays the role DataFusion's DefaultPhysicalPlanner plays for the reference
(invoked at rust/scheduler/src/lib.rs:325-331). Key structural choices match
the reference engine's:

- aggregates plan as Partial (per partition) -> Merge -> Final, the shape the
  distributed planner later cuts into stages (rust/scheduler/src/planner.rs:149-171)
- sorts and global limits merge partitions first (MergeExec)
- hash joins collect-left build; LEFT/FULL joins merge the probe side
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import pyarrow as pa

from ballista_tpu.datasource import (
    CsvTableSource,
    MemoryTableSource,
    ParquetTableSource,
)
from ballista_tpu.errors import PlanError
from ballista_tpu.logical import expr as lx
from ballista_tpu.logical import plan as lp
from ballista_tpu.physical.aggregate import AggregateFunc, AggregateMode, HashAggregateExec
from ballista_tpu.physical.basic import (
    CoalesceBatchesExec,
    EmptyExec,
    FilterExec,
    GlobalLimitExec,
    LocalLimitExec,
    MergeExec,
    ProjectionExec,
    SortExec,
)
from ballista_tpu.physical.expr import ColumnExpr, LiteralExpr, create_physical_expr
from ballista_tpu.physical.join import CrossJoinExec, HashJoinExec
from ballista_tpu.physical.plan import ExecutionPlan, Partitioning
from ballista_tpu.physical.repartition import RepartitionExec
from ballista_tpu.physical.scan import CsvScanExec, MemoryScanExec, ParquetScanExec
from ballista_tpu.physical.union import UnionExec


class PhysicalPlanner:
    def __init__(
        self,
        batch_size: int = 32768,
        coalesce_aggregates: bool = False,
        coalesce_max_bytes: int = 24 << 30,
        spmd_joins: bool = False,
    ) -> None:
        self.batch_size = batch_size
        # single-chip device execution: plan aggregations SINGLE over merged
        # input so the device stage runs once with the top-k pushdown
        # applicable, instead of a per-partition Partial each paying a d2h
        # readback of its full partial state (config.BALLISTA_TPU_COALESCE_AGG)
        self.coalesce_aggregates = coalesce_aggregates
        self.coalesce_max_bytes = coalesce_max_bytes
        # SPMD stage fusion on (config.BALLISTA_TPU_SPMD): co-partition
        # INNER joins too, so the DistributedPlanner can collapse the
        # exchange pair into one SpmdJoinExec mesh program — broadcast
        # joins carry no exchange to eliminate and stay per-partition
        self.spmd_joins = spmd_joins

    @staticmethod
    def _leaf_scan_bytes(node: ExecutionPlan) -> int:
        """On-disk bytes of the file-backed leaf scans under a subtree
        (compressed parquet under-counts the decoded size, so the coalesce
        cap should stay well below physical memory limits)."""
        import os

        if isinstance(node, (ParquetScanExec, CsvScanExec)):
            try:
                return sum(
                    os.path.getsize(f) for f in node.source.files
                    if os.path.exists(f)
                )
            except OSError:
                return 0
        if isinstance(node, MemoryScanExec):
            return sum(
                b.nbytes for part in node.source.partitions for b in part
            )
        return sum(PhysicalPlanner._leaf_scan_bytes(c) for c in node.children())

    def create_physical_plan(self, plan: lp.LogicalPlan) -> ExecutionPlan:
        p = self._plan(plan)
        # schema parity check: physical output must match logical
        lnames = plan.schema().names
        pnames = p.schema().names
        if lnames != pnames:
            raise PlanError(
                f"physical schema {pnames} != logical schema {lnames}\n{plan}\n{p}"
            )
        self._annotate_topk(p)
        return p

    @staticmethod
    def _annotate_topk(root: ExecutionPlan) -> None:
        """Mark Limit(Sort(Projection?(Aggregate))) chains on the aggregate:
        the device aggregate stages (ops/factagg.py candidate pool,
        ops/stage.py fused lexicographic top-k epilogue) use the annotation
        to read back only ~k rows instead of every group. Host execution
        ignores it: the aggregate still emits every group unless a device
        stage honors the hint, and the Sort/Limit above always re-applies
        the full ordering, so the annotation can only ever shrink the set of
        rows the aggregate returns — never reorder or widen it.

        The annotation resolves the LONGEST PREFIX of sort keys that are
        aggregate outputs into ``keys`` (ops/stage.py lowers each to
        order-preserving int lanes and sorts lexicographically).
        ``covered`` is True when that prefix is the whole ORDER BY — the
        device selection is then exactly the host selection; otherwise the
        consumer must detect boundary ties on the fused lanes and fall back
        (un-fused trailing tie-breakers could admit a different row).
        ``agg_index``/``descending``/``strict`` mirror the first key for
        the single-score consumers (factagg's block-max candidate pool)."""
        from ballista_tpu.physical import expr as px
        from ballista_tpu.physical.aggregate import AggregateMode, HashAggregateExec
        from ballista_tpu.physical.basic import GlobalLimitExec, ProjectionExec, SortExec

        def walk(node: ExecutionPlan) -> None:
            for c in node.children():
                walk(c)
            if not isinstance(node, GlobalLimitExec) or not node.limit:
                return
            s = node.children()[0]
            if not isinstance(s, SortExec) or not s.sort_keys:
                return
            p = s.input
            proj = None
            if isinstance(p, ProjectionExec):
                proj, p = p, p.input
            if not isinstance(p, HashAggregateExec) or p.mode != AggregateMode.SINGLE:
                return
            ngroup = len(p.group_exprs)
            keys = []
            for expr, asc, _nulls in s.sort_keys:
                if not isinstance(expr, px.ColumnExpr):
                    break
                idx = expr.index
                if proj is not None:
                    e = proj.exprs[idx][0]
                    if not isinstance(e, px.ColumnExpr):
                        break
                    idx = e.index
                if idx < ngroup:
                    break  # a group key, not an aggregate value
                keys.append({"agg_index": idx - ngroup, "descending": not asc})
            if not keys:
                return
            p._topk_pushdown = {
                "agg_index": keys[0]["agg_index"],
                "descending": keys[0]["descending"],
                "k": int(node.limit) + int(getattr(node, "skip", 0) or 0),
                "keys": keys,
                "covered": len(keys) == len(s.sort_keys),
                # sort keys beyond the first make tie order deterministic;
                # single-score consumers must detect boundary ties then
                "strict": len(s.sort_keys) > 1,
            }

        walk(root)

    # ------------------------------------------------------------------
    def _plan(self, plan: lp.LogicalPlan) -> ExecutionPlan:
        if isinstance(plan, lp.TableScan):
            return self._plan_scan(plan)
        if isinstance(plan, lp.Projection):
            input = self._plan(plan.input)
            in_schema = input.schema()
            exprs = [
                (create_physical_expr(e, in_schema), e.output_name())
                for e in plan.exprs
            ]
            return ProjectionExec(input, exprs)
        if isinstance(plan, lp.Filter):
            input = self._plan(plan.input)
            pred = create_physical_expr(plan.predicate, input.schema())
            # hint the scan so provably-empty parquet row groups are
            # skipped (statistics pruning; the filter itself still runs)
            target = input
            if isinstance(target, ProjectionExec) and all(
                isinstance(e, ColumnExpr) for e, _ in target.exprs
            ):
                target = target.input
            if isinstance(target, ParquetScanExec) and target.prune_predicate is None:
                from ballista_tpu.ops.stage import substitute_columns

                try:
                    if target is input:
                        target.prune_predicate = pred
                    else:
                        # rebase through the rename-only projection
                        mapping = [e for e, _ in input.exprs]
                        target.prune_predicate = substitute_columns(pred, mapping)
                except Exception:
                    pass  # pruning is best-effort; the filter is authoritative
            return FilterExec(input, pred)
        if isinstance(plan, lp.Aggregate):
            return self._plan_aggregate(plan)
        if isinstance(plan, lp.Distinct):
            # DISTINCT = group by all columns with no aggregates; alias each
            # key to its full (possibly qualified) field name so the output
            # schema matches the logical Distinct exactly
            group_exprs = []
            for f in plan.input.schema():
                bare = f.name.split(".")[-1]
                rel = f.name.split(".")[0] if "." in f.name else None
                group_exprs.append(lx.Alias(lx.Column(bare, rel), f.name))
            agg = lp.Aggregate(plan.input, group_exprs, [])
            return self._plan_aggregate(agg)
        if isinstance(plan, lp.Sort):
            input = self._plan(plan.input)
            if input.output_partitioning().partition_count() > 1:
                input = MergeExec(input)
            keys = [
                (
                    create_physical_expr(se.expr, input.schema()),
                    se.ascending,
                    se.nulls_first,
                )
                for se in plan.sort_exprs
            ]
            return SortExec(input, keys)
        if isinstance(plan, lp.Limit):
            input = self._plan(plan.input)
            if input.output_partitioning().partition_count() > 1:
                input = MergeExec(LocalLimitExec(input, plan.skip + plan.n))
            return GlobalLimitExec(input, plan.n, plan.skip)
        if isinstance(plan, lp.Join):
            return self._plan_join(plan)
        if isinstance(plan, lp.CrossJoin):
            return CrossJoinExec(self._plan(plan.left), self._plan(plan.right))
        if isinstance(plan, lp.Repartition):
            input = self._plan(plan.input)
            if plan.scheme == lp.PartitionScheme.HASH:
                exprs = [create_physical_expr(e, input.schema()) for e in plan.hash_exprs]
                return RepartitionExec(input, Partitioning.hash(exprs, plan.n))
            return RepartitionExec(input, Partitioning.round_robin(plan.n))
        if isinstance(plan, lp.EmptyRelation):
            return EmptyExec(plan.produce_one_row, plan.schema())
        if isinstance(plan, lp.SubqueryAlias):
            input = self._plan(plan.input)
            # zero-copy rename projection to the qualified names
            exprs = [
                (ColumnExpr(f.name, i), plan.schema().field(i).name)
                for i, f in enumerate(input.schema())
            ]
            return ProjectionExec(input, exprs)
        if isinstance(plan, lp.Union):
            return UnionExec([self._plan(c) for c in plan.inputs])
        if isinstance(plan, lp.Window):
            return self._plan_window(plan)
        raise PlanError(f"no physical plan for {type(plan).__name__}")

    def _plan_window(self, plan: lp.LogicalPlan) -> ExecutionPlan:
        from ballista_tpu.physical.window import WindowExec, WindowFuncDesc

        input = self._plan(plan.input)
        if input.output_partitioning().partition_count() > 1:
            input = MergeExec(input)
        in_schema = input.schema()
        funcs = []
        for e in plan.window_exprs:
            w = e.expr if isinstance(e, lx.Alias) else e
            if not isinstance(w, lx.WindowExpr):
                raise PlanError(f"window list entry is not a window expr: {e}")
            arg = (
                create_physical_expr(w.arg, in_schema) if w.arg is not None else None
            )
            funcs.append(
                WindowFuncDesc(
                    w.fn,
                    arg,
                    [create_physical_expr(p, in_schema) for p in w.partition_by],
                    [
                        (create_physical_expr(o.expr, in_schema), o.ascending)
                        for o in w.order_by
                    ],
                    e.output_name(),
                    e.data_type(in_schema),
                    w.frame,
                )
            )
        return WindowExec(input, funcs)

    # ------------------------------------------------------------------
    def _plan_scan(self, plan: lp.TableScan) -> ExecutionPlan:
        src = plan.source
        if isinstance(src, CsvTableSource):
            return CsvScanExec(src, plan.projection)
        if isinstance(src, ParquetTableSource):
            return ParquetScanExec(src, plan.projection)
        if isinstance(src, MemoryTableSource):
            return MemoryScanExec(src, plan.projection)
        raise PlanError(f"unknown table source {type(src).__name__}")

    # ------------------------------------------------------------------
    def _plan_aggregate(self, plan: lp.Aggregate) -> ExecutionPlan:
        input = self._plan(plan.input)
        exact_floats = getattr(plan, "exact_floats", False)
        in_schema = input.schema()
        group_exprs = [
            (create_physical_expr(e, in_schema), e.output_name())
            for e in plan.group_exprs
        ]
        funcs: List[AggregateFunc] = []
        any_distinct = False
        for e in plan.aggr_exprs:
            agg = e
            if isinstance(agg, lx.Alias):
                agg = agg.expr
            if not isinstance(agg, lx.AggregateExpr):
                raise PlanError(f"aggregate list entry is not an aggregate: {e}")
            if agg.distinct:
                if agg.fn != "count":
                    raise PlanError(
                        f"DISTINCT is only supported for COUNT, not {agg.fn.upper()}"
                    )
                any_distinct = True
            if isinstance(agg.expr, lx.Wildcard):
                pexpr = LiteralExpr(1, pa.int64())
                input_type = pa.int64()
            else:
                pexpr = create_physical_expr(agg.expr, in_schema)
                input_type = agg.expr.data_type(in_schema)
            fn = agg.fn if not agg.distinct else f"{agg.fn}_distinct"
            funcs.append(
                AggregateFunc(fn, pexpr, e.output_name(), e.data_type(in_schema), input_type)
            )

        single_partition = input.output_partitioning().partition_count() == 1
        coalesce = self.coalesce_aggregates and (
            self._leaf_scan_bytes(input) <= self.coalesce_max_bytes
        )
        if any_distinct or single_partition or coalesce:
            # DISTINCT aggregates need global visibility; single-partition
            # inputs skip the pointless partial/final split; coalesced mode
            # (single-chip TPU) trades the split for one device dispatch.
            # Coalescing is size-guarded: one driven partition materializes
            # the whole input chain, so past the byte cap the Partial/Final
            # split stays (streams file-by-file within the HBM budget —
            # how SF=100 fits a 16GB chip).
            merged = input if single_partition else MergeExec(input)
            return HashAggregateExec(AggregateMode.SINGLE, merged, group_exprs,
                                     funcs, exact_floats=exact_floats)

        partial = HashAggregateExec(AggregateMode.PARTIAL, input, group_exprs,
                                    funcs, exact_floats=exact_floats)
        if group_exprs:
            # parallel final: hash-exchange partial states on the group keys,
            # then finalize per partition (keys are disjoint across
            # partitions). The reference merges to one partition instead
            # (rust/scheduler/src/planner.rs:149-171 + MergeExec).
            n = partial.output_partitioning().partition_count()
            key_cols = [
                ColumnExpr(name, i) for i, (_, name) in enumerate(group_exprs)
            ]
            exchange = RepartitionExec(partial, Partitioning.hash(key_cols, n))
            return HashAggregateExec(AggregateMode.FINAL, exchange, group_exprs, funcs)
        merged = MergeExec(partial)
        return HashAggregateExec(AggregateMode.FINAL, merged, group_exprs, funcs)

    # ------------------------------------------------------------------
    def _plan_join(self, plan: lp.Join) -> ExecutionPlan:
        left = self._plan(plan.left)
        right = self._plan(plan.right)
        on: List[Tuple[str, str]] = []
        for lcol, rcol in plan.on:
            on.append(
                (
                    left.schema().field(lcol.index_in(left.schema())).name,
                    right.schema().field(rcol.index_in(right.schema())).name,
                )
            )
        partitioned = False
        copartition = plan.join_type in (lp.JoinType.LEFT, lp.JoinType.FULL)
        if (
            self.spmd_joins
            and plan.join_type == lp.JoinType.INNER
            and plan.filter is None
        ):
            # SPMD: give inner joins the same co-partitioned shape so the
            # distributed planner can fuse the exchange into a mesh program
            copartition = True
        if copartition:
            nl = left.output_partitioning().partition_count()
            nr = right.output_partitioning().partition_count()
            if nr > 1 or nl > 1:
                # co-partition BOTH sides on the join keys so every key
                # lands in one partition and each pair joins independently
                # — outer rows stay correct with no single-partition merge
                # (the old MergeExec scalability wall). A side already
                # hash-partitioned on exactly its join keys keeps its
                # existing exchange (no redundant shuffle).
                def hashed_on(side, names):
                    part = side.output_partitioning()
                    return (
                        part.scheme == "hash"
                        and len(part.exprs) == len(names)
                        and all(
                            isinstance(e, ColumnExpr) and e.name == k
                            for e, k in zip(part.exprs, names)
                        )
                    )

                lnames = [l for l, _ in on]
                rnames = [r for _, r in on]
                l_ok = hashed_on(left, lnames)
                r_ok = hashed_on(right, rnames)
                if l_ok and (not r_ok or nl >= nr):
                    n = nl
                elif r_ok:
                    n = nr
                else:
                    n = max(nl, nr)
                if not (l_ok and nl == n):
                    lexprs = [
                        ColumnExpr(lname, left.schema().names.index(lname))
                        for lname in lnames
                    ]
                    left = RepartitionExec(left, Partitioning.hash(lexprs, n))
                if not (r_ok and nr == n):
                    rexprs = [
                        ColumnExpr(rname, right.schema().names.index(rname))
                        for rname in rnames
                    ]
                    right = RepartitionExec(right, Partitioning.hash(rexprs, n))
                partitioned = True
        if plan.join_type in (lp.JoinType.SEMI, lp.JoinType.ANTI):
            # residual predicates evaluate over concat(left, right) during
            # the join itself (the right side is absent from the output)
            pfilter = None
            if plan.filter is not None:
                concat_schema = pa.schema(
                    list(left.schema()) + list(right.schema())
                )
                pfilter = create_physical_expr(plan.filter, concat_schema)
            return HashJoinExec(left, right, on, plan.join_type, filter=pfilter)
        join: ExecutionPlan = HashJoinExec(
            left, right, on, plan.join_type, partitioned=partitioned
        )
        if plan.filter is not None:
            join = FilterExec(join, create_physical_expr(plan.filter, join.schema()))
        return join

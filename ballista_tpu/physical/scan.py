"""Scan operators: CSV / Parquet / in-memory.

One partition per input file, as the reference's DataFusion scans do
(CsvExec/ParquetExec, referenced from rust/core/src/serde/physical_plan/from_proto.rs:85-131).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Tuple

import pyarrow as pa
import pyarrow.csv
import pyarrow.parquet

# host decoded-table cache (parquet), capped by total bytes, FIFO-evicted.
# Keys are (path, mtime, cols); a rewritten file gets a new key and the old
# entry for the same (path, cols) is dropped eagerly.
import threading as _threading

_TABLE_CACHE: Dict[tuple, pa.Table] = {}
_TABLE_CACHE_BYTES = [0]
_TABLE_CACHE_MU = _threading.Lock()


def _cache_get(key: tuple) -> Optional[pa.Table]:
    with _TABLE_CACHE_MU:
        return _TABLE_CACHE.get(key)


def _maybe_cache(key: tuple, table: pa.Table, cap: int) -> None:
    nbytes = table.nbytes
    if nbytes > cap:
        return
    with _TABLE_CACHE_MU:
        # drop stale entries for the same (path, cols) with older mtimes
        path, _mtime, cols = key
        for k in [k for k in _TABLE_CACHE if k[0] == path and k[2] == cols and k != key]:
            _TABLE_CACHE_BYTES[0] -= _TABLE_CACHE[k].nbytes
            del _TABLE_CACHE[k]
        # FIFO eviction to fit
        while _TABLE_CACHE_BYTES[0] + nbytes > cap and _TABLE_CACHE:
            k = next(iter(_TABLE_CACHE))
            _TABLE_CACHE_BYTES[0] -= _TABLE_CACHE[k].nbytes
            del _TABLE_CACHE[k]
        _TABLE_CACHE[key] = table
        _TABLE_CACHE_BYTES[0] += nbytes

from ballista_tpu.datasource import CsvTableSource, MemoryTableSource, ParquetTableSource
from ballista_tpu.physical.plan import ExecutionPlan, Partitioning, TaskContext, batch_table


class CsvScanExec(ExecutionPlan):
    def __init__(self, source: CsvTableSource, projection: Optional[List[int]] = None) -> None:
        self.source = source
        self.projection = projection
        full = source.schema()
        if projection is None:
            self._schema = full
        else:
            self._schema = pa.schema([full.field(i) for i in projection])

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(len(self.source.files))

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        path = self.source.files[partition]
        full = self.source.schema()
        read_opts = pa.csv.ReadOptions(
            column_names=None if self.source.has_header else full.names,
            block_size=1 << 24,
        )
        convert_opts = pa.csv.ConvertOptions(
            column_types={f.name: f.type for f in full},
            include_columns=[f.name for f in self._schema] if self.projection is not None else None,
        )
        parse_opts = pa.csv.ParseOptions(delimiter=self.source.delimiter)
        table = pa.csv.read_csv(
            path, read_options=read_opts, parse_options=parse_opts,
            convert_options=convert_opts,
        )
        table = table.select(self._schema.names).cast(self._schema)
        yield from batch_table(table, ctx.batch_size)

    def fmt(self) -> str:
        return f"CsvScanExec: {self.source.path} projection={self.projection}"


class ParquetScanExec(ExecutionPlan):
    def __init__(
        self, source: ParquetTableSource, projection: Optional[List[int]] = None,
        batch_size: int = 32768,
    ) -> None:
        self.source = source
        self.projection = projection
        full = source.schema()
        if projection is None:
            self._schema = full
        else:
            self._schema = pa.schema([full.field(i) for i in projection])

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(len(self.source.files))

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        path = self.source.files[partition]
        cols = self._schema.names if self.projection is not None else None
        # decoded-table cache: repeated queries skip parquet decode (the
        # host-side analog of the device column cache). Files too large to
        # ever fit stream instead of materializing.
        cap = ctx.config.scan_cache_cap()
        if ctx.config.scan_cache() and os.path.getsize(path) * 4 <= cap:
            key = (path, os.path.getmtime(path), tuple(cols) if cols else None)
            table = _cache_get(key)
            if table is None:
                table = pa.parquet.read_table(path, columns=cols)
                _maybe_cache(key, table, cap)
            yield from table.to_batches(max_chunksize=ctx.batch_size)
            return
        pf = pa.parquet.ParquetFile(path)
        for batch in pf.iter_batches(batch_size=ctx.batch_size, columns=cols):
            yield batch

    def fmt(self) -> str:
        return f"ParquetScanExec: {self.source.path} projection={self.projection}"


class MemoryScanExec(ExecutionPlan):
    def __init__(self, source: MemoryTableSource, projection: Optional[List[int]] = None) -> None:
        self.source = source
        self.projection = projection
        full = source.schema()
        if projection is None:
            self._schema = full
        else:
            self._schema = pa.schema([full.field(i) for i in projection])

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(self.source.num_partitions())

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        for batch in self.source.partitions[partition]:
            if self.projection is not None:
                batch = batch.select(self._schema.names)
            yield batch

    def fmt(self) -> str:
        return f"MemoryScanExec: projection={self.projection}"

"""Scan operators: CSV / Parquet / in-memory.

One partition per input file, as the reference's DataFusion scans do
(CsvExec/ParquetExec, referenced from rust/core/src/serde/physical_plan/from_proto.rs:85-131).
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Tuple

import pyarrow as pa
import pyarrow.csv
import pyarrow.parquet

# host decoded-table cache (parquet), capped by total bytes, FIFO-evicted.
# Keys are (path, mtime, cols); a rewritten file gets a new key and the old
# entry for the same (path, cols) is dropped eagerly.
from ballista_tpu.utils.locks import make_lock

_TABLE_CACHE: Dict[tuple, pa.Table] = {}  # guarded-by: _TABLE_CACHE_MU
_TABLE_CACHE_BYTES = [0]  # guarded-by: _TABLE_CACHE_MU
_TABLE_CACHE_MU = make_lock("physical.scan._TABLE_CACHE_MU")


def _cache_get(key: tuple) -> Optional[pa.Table]:
    with _TABLE_CACHE_MU:
        return _TABLE_CACHE.get(key)


def _maybe_cache(key: tuple, table: pa.Table, cap: int) -> None:
    nbytes = table.nbytes
    if nbytes > cap:
        return
    with _TABLE_CACHE_MU:
        # drop stale entries for the same (path, cols) with older mtimes
        path, _mtime, cols = key
        for k in [k for k in _TABLE_CACHE if k[0] == path and k[2] == cols and k != key]:
            _TABLE_CACHE_BYTES[0] -= _TABLE_CACHE[k].nbytes
            del _TABLE_CACHE[k]
        # FIFO eviction to fit
        while _TABLE_CACHE_BYTES[0] + nbytes > cap and _TABLE_CACHE:
            k = next(iter(_TABLE_CACHE))
            _TABLE_CACHE_BYTES[0] -= _TABLE_CACHE[k].nbytes
            del _TABLE_CACHE[k]
        _TABLE_CACHE[key] = table
        _TABLE_CACHE_BYTES[0] += nbytes

from ballista_tpu.datasource import CsvTableSource, MemoryTableSource, ParquetTableSource
from ballista_tpu.physical.plan import ExecutionPlan, Partitioning, TaskContext, batch_table


class CsvScanExec(ExecutionPlan):
    def __init__(self, source: CsvTableSource, projection: Optional[List[int]] = None) -> None:
        self.source = source
        self.projection = projection
        full = source.schema()
        if projection is None:
            self._schema = full
        else:
            self._schema = pa.schema([full.field(i) for i in projection])

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(len(self.source.files))

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        path = self.source.files[partition]
        full = self.source.schema()
        read_opts = pa.csv.ReadOptions(
            column_names=None if self.source.has_header else full.names,
            block_size=1 << 24,
        )
        convert_opts = pa.csv.ConvertOptions(
            column_types={f.name: f.type for f in full},
            include_columns=[f.name for f in self._schema] if self.projection is not None else None,
        )
        parse_opts = pa.csv.ParseOptions(delimiter=self.source.delimiter)
        table = pa.csv.read_csv(
            path, read_options=read_opts, parse_options=parse_opts,
            convert_options=convert_opts,
        )
        table = table.select(self._schema.names).cast(self._schema)
        yield from batch_table(table, ctx.batch_size)

    def fmt(self) -> str:
        return f"CsvScanExec: {self.source.path} projection={self.projection}"


class ParquetScanExec(ExecutionPlan):
    def __init__(
        self, source: ParquetTableSource, projection: Optional[List[int]] = None,
        batch_size: int = 32768,
    ) -> None:
        self.source = source
        self.projection = projection
        full = source.schema()
        if projection is None:
            self._schema = full
        else:
            self._schema = pa.schema([full.field(i) for i in projection])
        # best-effort predicate hint set by the physical planner when a
        # FilterExec sits directly above: row groups whose min/max statistics
        # prove no row can match are skipped on the streaming path. The
        # filter above still runs, so this is purely an IO reduction.
        self.prune_predicate = None

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(len(self.source.files))

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        path = self.source.files[partition]
        cols = self._schema.names if self.projection is not None else None
        # decoded-table cache: repeated queries skip parquet decode (the
        # host-side analog of the device column cache). Files too large to
        # ever fit stream instead of materializing.
        cap = ctx.config.scan_cache_cap()
        if ctx.config.scan_cache() and os.path.getsize(path) * 4 <= cap:
            key = (path, os.path.getmtime(path), tuple(cols) if cols else None)
            table = _cache_get(key)
            if table is None:
                table = pa.parquet.read_table(path, columns=cols)
                _maybe_cache(key, table, cap)
            yield from table.to_batches(max_chunksize=ctx.batch_size)
            return
        pf = pa.parquet.ParquetFile(path)
        row_groups = prune_row_groups(pf, self.prune_predicate)
        if not row_groups:
            return
        for batch in pf.iter_batches(
            batch_size=ctx.batch_size, columns=cols, row_groups=row_groups
        ):
            yield batch

    def fmt(self) -> str:
        return f"ParquetScanExec: {self.source.path} projection={self.projection}"


def _stat_conjuncts(predicate) -> List[tuple]:
    """Extract (column name, op, literal) conjuncts usable against row-group
    statistics; unrecognized parts are ignored (conservative)."""
    from ballista_tpu.physical import expr as px

    out: List[tuple] = []

    def walk(e) -> None:
        if isinstance(e, px.BinaryPhysicalExpr):
            if e.op == "and":
                walk(e.left)
                walk(e.right)
                return
            flipped = {"lt": "gt", "lteq": "gteq", "gt": "lt", "gteq": "lteq",
                       "eq": "eq"}
            if e.op in flipped:
                l, r = e.left, e.right
                if isinstance(l, px.ColumnExpr) and isinstance(r, px.LiteralExpr):
                    out.append((l.name, e.op, r.value))
                elif isinstance(l, px.LiteralExpr) and isinstance(r, px.ColumnExpr):
                    out.append((r.name, flipped[e.op], l.value))
        elif isinstance(e, px.BetweenExpr) and not e.negated:
            if (
                isinstance(e.expr, px.ColumnExpr)
                and isinstance(e.low, px.LiteralExpr)
                and isinstance(e.high, px.LiteralExpr)
            ):
                out.append((e.expr.name, "gteq", e.low.value))
                out.append((e.expr.name, "lteq", e.high.value))

    walk(predicate)
    return out


def prune_row_groups(pf, predicate) -> List[int]:
    """Row groups that might contain matching rows (all of them when the
    predicate is absent or statistics are unusable). Mirrors the reference
    engine's parquet row-group filtering role; the proof obligation is
    one-sided — a group is skipped only when its min/max make a conjunct
    unsatisfiable."""
    md = pf.metadata
    n = md.num_row_groups
    if predicate is None or n == 0:
        return list(range(n))
    conjuncts = _stat_conjuncts(predicate)
    if not conjuncts:
        return list(range(n))
    # metadata columns are flattened parquet LEAVES, not arrow fields —
    # indexing by arrow-schema position shifts under nested columns and
    # would consult the wrong statistics. Map by leaf path instead; only
    # top-level primitive columns (path == name) participate.
    rg0 = md.row_group(0)
    file_cols = {}
    for i in range(md.num_columns):
        p = rg0.column(i).path_in_schema
        if "." not in p:
            file_cols[p] = i
    keep: List[int] = []
    for g in range(n):
        rg = md.row_group(g)
        dead = False
        for name, op, lit in conjuncts:
            ci = file_cols.get(name)
            if ci is None or lit is None:
                continue
            col = rg.column(ci)
            st = col.statistics
            if st is None or not st.has_min_max:
                continue
            try:
                if op == "lt" and not (st.min < lit):
                    dead = True
                elif op == "lteq" and not (st.min <= lit):
                    dead = True
                elif op == "gt" and not (st.max > lit):
                    dead = True
                elif op == "gteq" and not (st.max >= lit):
                    dead = True
                elif op == "eq" and not (st.min <= lit <= st.max):
                    dead = True
            except TypeError:
                continue  # incomparable stats (e.g. binary vs py value)
            if dead:
                break
        if not dead:
            keep.append(g)
    return keep


class MemoryScanExec(ExecutionPlan):
    def __init__(self, source: MemoryTableSource, projection: Optional[List[int]] = None) -> None:
        self.source = source
        self.projection = projection
        full = source.schema()
        if projection is None:
            self._schema = full
        else:
            self._schema = pa.schema([full.field(i) for i in projection])

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(self.source.num_partitions())

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        for batch in self.source.partitions[partition]:
            if self.projection is not None:
                batch = batch.select(self._schema.names)
            yield batch

    def fmt(self) -> str:
        return f"MemoryScanExec: projection={self.projection}"

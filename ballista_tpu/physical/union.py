"""UnionExec: concatenates child partitions (UNION ALL)."""

from __future__ import annotations

from typing import Iterator, List

import pyarrow as pa

from ballista_tpu.physical.plan import ExecutionPlan, Partitioning, TaskContext


class UnionExec(ExecutionPlan):
    def __init__(self, inputs: List[ExecutionPlan]) -> None:
        self.inputs = inputs
        self._schema = inputs[0].schema()

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        total = sum(i.output_partitioning().partition_count() for i in self.inputs)
        return Partitioning.unknown(total)

    def children(self) -> List[ExecutionPlan]:
        return list(self.inputs)

    def with_children(self, children: List[ExecutionPlan]) -> "UnionExec":
        return UnionExec(children)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        offset = partition
        for child in self.inputs:
            n = child.output_partitioning().partition_count()
            if offset < n:
                for batch in child.execute(offset, ctx):
                    # normalize field names across union branches
                    yield pa.RecordBatch.from_arrays(
                        list(batch.columns), schema=self._schema
                    )
                return
            offset -= n
        raise IndexError(f"partition {partition} out of range")

    def fmt(self) -> str:
        return "UnionExec"

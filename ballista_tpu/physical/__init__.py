from ballista_tpu.physical.plan import (  # noqa: F401
    ExecutionPlan,
    Partitioning,
    TaskContext,
)

"""Vectorized join index computation (host path).

Sort + binary-search join over dense int64 key codes — deliberately the same
algorithm the TPU backend lowers with jnp.searchsorted/gather
(ballista_tpu/ops/join.py), so host and device paths share semantics.

Key normalization: every key column (any Arrow type, incl. strings) is
factorized to int64 codes jointly across both sides; composite keys combine
code columns into one dense int64. Null keys never match (SQL semantics).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


def _codes_for(left: pa.Array, right: pa.Array) -> Tuple[np.ndarray, np.ndarray, int]:
    """Jointly factorize two arrays to int64 codes; null -> -1."""
    lc = left.combine_chunks() if isinstance(left, pa.ChunkedArray) else left
    rc = right.combine_chunks() if isinstance(right, pa.ChunkedArray) else right
    combined = pa.chunked_array([lc, rc]).combine_chunks()
    # fast path: integer-typed, no nulls, and a value range small enough that
    # downstream composite packing can't overflow — use shifted values directly
    if pa.types.is_integer(combined.type) and combined.null_count == 0:
        vals = combined.to_numpy(zero_copy_only=False).astype(np.int64)
        lo = int(vals.min()) if len(vals) else 0
        hi = int(vals.max()) if len(vals) else 0
        if hi - lo < (1 << 32):
            codes = vals - lo
            n_left = len(lc)
            return codes[:n_left], codes[n_left:], hi - lo + 1
    dict_arr = pc.dictionary_encode(combined)
    if isinstance(dict_arr, pa.ChunkedArray):
        dict_arr = dict_arr.combine_chunks()
    codes_all = dict_arr.indices
    codes = codes_all.to_numpy(zero_copy_only=False)
    codes = np.where(np.isnan(codes), -1, codes).astype(np.int64) if codes.dtype.kind == "f" else codes.astype(np.int64)
    if codes_all.null_count:
        mask = codes_all.is_valid().to_numpy(zero_copy_only=False)
        codes = np.where(mask, codes, -1)
    n_left = len(lc)
    card = len(dict_arr.dictionary)
    return codes[:n_left], codes[n_left:], card


def _refactorize(
    lcodes: np.ndarray, rcodes: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Re-map arbitrary int64 codes to dense [0, n_distinct) codes, so the
    cardinality is bounded by the total row count (overflow-safe repacking)."""
    combined = np.concatenate([lcodes, rcodes])
    _, dense = np.unique(combined, return_inverse=True)
    dense = dense.astype(np.int64)
    card = int(dense.max()) + 1 if len(dense) else 0
    return dense[: len(lcodes)], dense[len(lcodes):], card


def combined_key_codes(
    left_cols: List[pa.Array], right_cols: List[pa.Array]
) -> Tuple[np.ndarray, np.ndarray]:
    """Reduce (possibly composite) join keys on both sides to single int64
    code arrays; rows with any null key get code -1."""
    assert len(left_cols) == len(right_cols) and left_cols
    lcodes, rcodes, card = _codes_for(left_cols[0], right_cols[0])
    lnull = lcodes < 0
    rnull = rcodes < 0
    for lcol, rcol in zip(left_cols[1:], right_cols[1:]):
        lc2, rc2, card2 = _codes_for(lcol, rcol)
        lnull |= lc2 < 0
        rnull |= rc2 < 0
        if card2 and card > (1 << 62) // max(card2, 1):
            # packing would overflow int64: compress accumulated codes to a
            # dense range first (distinct count <= row count)
            lcodes, rcodes, card = _refactorize(lcodes, rcodes)
        lcodes = lcodes * card2 + np.maximum(lc2, 0)
        rcodes = rcodes * card2 + np.maximum(rc2, 0)
        card = card * card2 if card2 else card
    lcodes = np.where(lnull, -1, lcodes)
    rcodes = np.where(rnull, -1, rcodes)
    return lcodes, rcodes


def join_indices(
    left_codes: np.ndarray, right_codes: np.ndarray, how: str
) -> Tuple[np.ndarray, np.ndarray]:
    """Compute row indices (left_idx, right_idx) realizing the join.

    -1 in either output marks a null-padded side (outer joins). For
    ``semi``/``anti`` only left_idx is meaningful (right_idx empty).
    """
    order = np.argsort(left_codes, kind="stable")
    lsorted = left_codes[order]
    # exclude null build keys from matching by searching only the >=0 region
    first_valid = int(np.searchsorted(lsorted, 0, "left"))
    valid_sorted = lsorted[first_valid:]
    valid_order = order[first_valid:]

    probe_valid = right_codes >= 0
    starts = np.searchsorted(valid_sorted, right_codes, "left")
    ends = np.searchsorted(valid_sorted, right_codes, "right")
    counts = np.where(probe_valid, ends - starts, 0)

    if how == "semi_right":
        keep = counts > 0
        return np.nonzero(keep)[0], np.empty(0, np.int64)
    if how == "anti_right":
        keep = counts == 0
        return np.nonzero(keep)[0], np.empty(0, np.int64)

    total = int(counts.sum())
    probe_idx = np.repeat(np.arange(len(right_codes), dtype=np.int64), counts)
    if total:
        offsets = np.concatenate(([0], np.cumsum(counts)[:-1]))
        flat = (
            np.arange(total, dtype=np.int64)
            - np.repeat(offsets, counts)
            + np.repeat(starts, counts)
        )
        build_idx = valid_order[flat]
    else:
        build_idx = np.empty(0, np.int64)

    if how == "inner":
        return build_idx, probe_idx
    if how == "right":  # keep all probe (right) rows
        unmatched = np.nonzero(counts == 0)[0]
        left_idx = np.concatenate([build_idx, np.full(len(unmatched), -1, np.int64)])
        right_idx = np.concatenate([probe_idx, unmatched.astype(np.int64)])
        return left_idx, right_idx
    if how in ("left", "full"):
        matched_build = np.zeros(len(left_codes), dtype=bool)
        if total:
            matched_build[build_idx] = True
        unmatched_build = np.nonzero(~matched_build)[0]
        left_idx = np.concatenate([build_idx, unmatched_build.astype(np.int64)])
        right_idx = np.concatenate(
            [probe_idx, np.full(len(unmatched_build), -1, np.int64)]
        )
        if how == "full":
            unmatched_probe = np.nonzero(counts == 0)[0]
            left_idx = np.concatenate([left_idx, np.full(len(unmatched_probe), -1, np.int64)])
            right_idx = np.concatenate([right_idx, unmatched_probe.astype(np.int64)])
        return left_idx, right_idx
    if how == "semi":  # left semi: left rows with >=1 match
        matched_build = np.zeros(len(left_codes), dtype=bool)
        if total:
            matched_build[build_idx] = True
        return np.nonzero(matched_build)[0], np.empty(0, np.int64)
    if how == "anti":  # left anti
        matched_build = np.zeros(len(left_codes), dtype=bool)
        if total:
            matched_build[build_idx] = True
        return np.nonzero(~matched_build)[0], np.empty(0, np.int64)
    raise ValueError(f"unknown join type {how!r}")


def take_table(table: pa.Table, indices: np.ndarray) -> pa.Table:
    """Take with -1 meaning null row."""
    if len(indices) and (indices < 0).any():
        idx = pa.array(
            np.where(indices < 0, 0, indices), mask=(indices < 0)
        )
    else:
        idx = pa.array(indices)
    return table.take(idx)

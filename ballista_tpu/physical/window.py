"""Window function execution.

WindowExec computes row_number / rank / dense_rank and the five aggregates
with SQL frame semantics: whole-partition when no ORDER BY is given, the
standard peer-inclusive running frame (RANGE UNBOUNDED PRECEDING..CURRENT
ROW) with ORDER BY, and explicit ROWS BETWEEN frames. Strategy: merge to
one partition, sort by (partition keys, order keys), compute partition/peer
boundaries once, then every function is a vectorized pass — cumcounts for
ranking, prefix sums / accumulates (plus padded sliding windows for bounded
min/max) for aggregates. Output rows come back in sorted order (row order
is unspecified unless the query adds ORDER BY).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.errors import PlanError
from ballista_tpu.physical.expr import PhysicalExpr, _as_array
from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
    collect_partition,
)


class WindowFuncDesc:
    def __init__(
        self,
        fn: str,
        arg: Optional[PhysicalExpr],
        partition_by: List[PhysicalExpr],
        order_by: List[Tuple[PhysicalExpr, bool]],  # (expr, ascending)
        name: str,
        dtype: pa.DataType,
        frame: Optional[Tuple[str, Optional[float], Optional[float]]] = None,
    ) -> None:
        self.fn = fn
        self.arg = arg
        self.partition_by = partition_by
        self.order_by = order_by
        self.name = name
        self.dtype = dtype
        # (mode, start, end) frame; None side = unbounded; the whole value
        # None = SQL default (resolved at execution)
        self.frame = frame


def _codes(arr: pa.Array) -> np.ndarray:
    d = pc.dictionary_encode(arr)
    if isinstance(d, pa.ChunkedArray):
        d = d.combine_chunks()
    out = d.indices.to_numpy(zero_copy_only=False).astype(np.int64)
    return out


class WindowExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, funcs: List[WindowFuncDesc]) -> None:
        self.input = input
        self.funcs = funcs
        fields = list(input.schema())
        fields += [pa.field(f.name, f.dtype) for f in funcs]
        self._schema = pa.schema(fields)

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "WindowExec":
        return WindowExec(children[0], self.funcs)

    def execute(self, partition: int, ctx: TaskContext):
        assert partition == 0
        table = collect_partition(self.input, 0, ctx)
        if table.num_rows == 0:
            yield from self._schema.empty_table().to_batches()
            return
        batch = table.combine_chunks().to_batches()[0]
        n = batch.num_rows
        out_cols = list(table.combine_chunks().columns)
        for f in self.funcs:
            out_cols.append(self._compute(f, batch, n))
        yield from batch_table(
            pa.table(out_cols, schema=self._schema), ctx.batch_size
        )

    # ------------------------------------------------------------------
    def _compute(self, f: WindowFuncDesc, batch: pa.RecordBatch, n: int) -> pa.Array:
        # sort order: partition keys then order keys
        sort_cols = {}
        sort_keys = []
        for i, e in enumerate(f.partition_by):
            cn = f"__p{i}"
            sort_cols[cn] = _as_array(e.evaluate(batch), n)
            sort_keys.append((cn, "ascending"))
        for i, (e, asc) in enumerate(f.order_by):
            cn = f"__o{i}"
            sort_cols[cn] = _as_array(e.evaluate(batch), n)
            sort_keys.append((cn, "ascending" if asc else "descending"))
        if sort_cols:
            key_table = pa.table(sort_cols)
            order = pc.sort_indices(key_table, sort_keys=sort_keys).to_numpy()
        else:
            order = np.arange(n, dtype=np.int64)
        inv = np.empty(n, dtype=np.int64)
        inv[order] = np.arange(n, dtype=np.int64)

        # partition ids in sorted order
        if f.partition_by:
            pcodes = np.zeros(n, dtype=np.int64)
            for i in range(len(f.partition_by)):
                c = _codes(sort_cols[f"__p{i}"])[order]
                pcodes = pcodes * (int(c.max()) + 1 if len(c) else 1) + c
            new_part = np.empty(n, dtype=bool)
            new_part[0] = True
            new_part[1:] = pcodes[1:] != pcodes[:-1]
        else:
            new_part = np.zeros(n, dtype=bool)
            new_part[0] = True
        part_id = np.cumsum(new_part) - 1
        part_start = np.maximum.accumulate(np.where(new_part, np.arange(n), 0))

        if f.fn == "row_number":
            vals = np.arange(n) - part_start + 1
            return pa.array(vals[inv], type=pa.int64())
        if f.fn in ("rank", "dense_rank"):
            # order-key change detection within a partition
            changed = np.ones(n, dtype=bool)
            if f.order_by:
                ocodes = np.zeros(n, dtype=np.int64)
                for i in range(len(f.order_by)):
                    c = _codes(sort_cols[f"__o{i}"])[order]
                    ocodes = ocodes * (int(c.max()) + 1 if len(c) else 1) + c
                changed[1:] = (ocodes[1:] != ocodes[:-1]) | new_part[1:]
            if f.fn == "rank":
                change_pos = np.maximum.accumulate(np.where(changed, np.arange(n), 0))
                vals = change_pos - part_start + 1
            else:
                dense = np.cumsum(changed)
                base = np.maximum.accumulate(np.where(new_part, dense, 0))
                vals = dense - base + 1
            return pa.array(vals[inv], type=pa.int64())

        # aggregates: whole-partition (no ORDER BY), the standard
        # peer-inclusive running frame (ORDER BY, no explicit frame — RANGE
        # UNBOUNDED PRECEDING..CURRENT ROW), or an explicit ROWS frame.
        assert f.arg is not None or f.fn == "count"
        if f.arg is not None:
            argv = _as_array(f.arg.evaluate(batch), n)
            av = argv.to_numpy(zero_copy_only=False).astype(np.float64)[order]
            valid = pc.is_valid(argv).to_numpy(zero_copy_only=False)[order]
        else:
            av = np.ones(n, dtype=np.float64)
            valid = np.ones(n, dtype=bool)
        starts_idx = np.flatnonzero(new_part)
        seg_ends = np.append(starts_idx[1:], n)
        explicit = None  # per-row [lo, hi) bounds, when not a plain ROWS frame
        running = False  # explicit bounds with lo == partition start
        frame = f.frame
        if frame is None:
            if f.order_by:
                # RANGE default: rows tied on the order keys are peers and
                # every peer sees the same (full peer-run) value
                frame = ("rows", None, 0)
                ocodes = np.zeros(n, dtype=np.int64)
                for i in range(len(f.order_by)):
                    c = _codes(sort_cols[f"__o{i}"])[order]
                    ocodes = ocodes * (int(c.max()) + 1 if len(c) else 1) + c
                changed = np.ones(n, dtype=bool)
                changed[1:] = (ocodes[1:] != ocodes[:-1]) | new_part[1:]
                run_starts = np.flatnonzero(changed)
                nxt = np.append(run_starts[1:], n)
                explicit = (part_start, nxt[np.cumsum(changed) - 1])
                running = True
            else:
                frame = ("rows", None, None)
        mode, fstart, fend = frame
        if mode == "range" and explicit is None:
            # bounds via value search on the (sorted) single order key;
            # PRECEDING/FOLLOWING track the ordering direction
            karr = sort_cols["__o0"]
            if not (
                pa.types.is_integer(karr.type)
                or pa.types.is_floating(karr.type)
                or pa.types.is_decimal(karr.type)
            ):
                raise PlanError(
                    f"RANGE frames require a numeric ORDER BY key, got {karr.type}"
                )
            kv = karr.to_numpy(zero_copy_only=False).astype(np.float64)[order]
            running = fstart is None
            asc = f.order_by[0][1]
            sign = 1.0 if asc else -1.0
            kvs = kv * sign  # ascending view of the ordering
            lo = np.empty(n, dtype=np.int64)
            hi = np.empty(n, dtype=np.int64)
            for s0, e0 in zip(starts_idx, seg_ends):
                seg = kvs[s0:e0]
                # NULL order keys (NaN here) sort to the end of each
                # partition (sort_indices null_placement at_end) and form
                # one trailing peer group: offset bounds resolve to the
                # peer run itself, UNBOUNDED bounds keep the partition edge
                nan = np.isnan(seg)
                nn = int((~nan).sum())
                if nan[:nn].any():
                    raise PlanError(
                        "RANGE frames: non-contiguous null order keys"
                    )
                sub = seg[:nn]
                lo[s0:s0 + nn] = (
                    s0
                    if fstart is None
                    else s0 + np.searchsorted(sub, sub + fstart, side="left")
                )
                hi[s0:s0 + nn] = (
                    e0
                    if fend is None
                    else s0 + np.searchsorted(sub, sub + fend, side="right")
                )
                if nn < e0 - s0:
                    lo[s0 + nn:e0] = s0 if fstart is None else s0 + nn
                    hi[s0 + nn:e0] = e0
            explicit = (lo, hi)
        nparts = int(part_id[-1]) + 1
        if (fstart, fend) == (None, None) and explicit is None:
            cnt = np.zeros(nparts)
            np.add.at(cnt, part_id, valid.astype(np.float64))
            if f.fn == "count":
                vals = cnt[part_id][inv]
                return pc.cast(pa.array(vals), f.dtype)
            if f.fn in ("sum", "avg"):
                agg = np.zeros(nparts)
                np.add.at(agg, part_id, np.where(valid, av, 0.0))
                if f.fn == "avg":
                    agg = agg / np.maximum(cnt, 1)
            elif f.fn == "min":
                agg = np.full(nparts, np.inf)
                np.minimum.at(agg, part_id, np.where(valid, av, np.inf))
            elif f.fn == "max":
                agg = np.full(nparts, -np.inf)
                np.maximum.at(agg, part_id, np.where(valid, av, -np.inf))
            else:
                raise PlanError(f"unsupported window function {f.fn}")
            vals = agg[part_id][inv]
            # a partition with no valid input rows aggregates to NULL
            empty = (cnt == 0)[part_id][inv]
            return pc.cast(pa.array(vals, mask=empty), f.dtype)
        vals, null_mask = _framed_aggregate(
            f.fn, av, valid, part_start, part_id, new_part,
            (fstart, fend), explicit, running,
        )
        arr = pa.array(vals[inv], mask=null_mask[inv] if null_mask is not None else None)
        return pc.cast(arr, f.dtype)

    def fmt(self) -> str:
        return "WindowExec: " + ", ".join(
            f"{f.fn}(...) AS {f.name}" for f in self.funcs
        )


def _framed_aggregate(
    fn: str,
    av: np.ndarray,
    valid: np.ndarray,
    part_start: np.ndarray,
    part_id: np.ndarray,
    new_part: np.ndarray,
    frame,
    explicit: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    running: bool = False,
) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Framed aggregates over rows already sorted by (partition keys, order
    keys). Per row i the window is rows [i+start, i+end] clamped to its
    partition — or, when `explicit` carries per-row [lo, hi) bounds (the
    peer-inclusive running default, RANGE frames), exactly those rows.
    sum/count/avg vectorize via prefix sums (windows never cross partition
    bounds, so one global prefix array suffices); min/max run per partition
    with accumulate / padded sliding windows (ROWS) or a sparse table
    (explicit bounds). Returns (values, null mask for empty windows)."""
    n = len(av)
    start, end = frame
    # per-row partition bounds [ps, pe)
    starts_idx = np.flatnonzero(new_part)
    ends = np.append(starts_idx[1:], n)
    ps = part_start
    pe = ends[part_id]
    idx = np.arange(n)
    if explicit is not None:
        lo, hi = explicit
        hi = np.maximum(hi, lo)
    else:
        lo = ps if start is None else np.clip(idx + start, ps, pe)
        hi = pe if end is None else np.clip(idx + end + 1, ps, pe)
        hi = np.maximum(hi, lo)  # empty window

    if fn in ("sum", "avg", "count"):
        pref = np.concatenate([[0.0], np.cumsum(np.where(valid, av, 0.0))])
        prefc = np.concatenate([[0.0], np.cumsum(valid.astype(np.float64))])
        s = pref[hi] - pref[lo]
        c = prefc[hi] - prefc[lo]
        if fn == "count":
            return c, None
        if fn == "avg":
            return s / np.maximum(c, 1), (c == 0)
        return s, (c == 0)

    if fn not in ("min", "max"):
        raise PlanError(f"unsupported framed window function {fn}")
    fill = np.inf if fn == "min" else -np.inf
    acc = np.minimum.accumulate if fn == "min" else np.maximum.accumulate
    red = np.minimum if fn == "min" else np.maximum
    v = np.where(valid, av, fill)
    out = np.empty(n, dtype=np.float64)
    for s0, e0 in zip(starts_idx, ends):
        seg = v[s0:e0]
        m = len(seg)
        if explicit is not None and running:
            # lo pinned at the partition start: one prefix accumulate,
            # indexed at each row's (exclusive) end — the common
            # running-default shape
            run = acc(seg) if m else seg
            R = hi[s0:e0] - s0
            res = np.where(R > 0, run[np.maximum(R - 1, 0)], fill)
            out[s0:e0] = res
            continue
        if explicit is not None:
            # arbitrary monotone [lo, hi) per row: O(1) range min/max via a
            # sparse table (O(m log m) build)
            L = lo[s0:e0] - s0
            R = hi[s0:e0] - s0
            w = R - L
            table = [seg]
            span = 1
            while span * 2 <= m:
                prev = table[-1]
                table.append(red(prev[: m - span * 2 + 1], prev[span: m - span + 1]))
                span *= 2
            res = np.full(m, fill)
            nonempty = w > 0
            if nonempty.any():
                k = np.zeros(m, dtype=np.int64)
                k[nonempty] = np.floor(np.log2(w[nonempty])).astype(np.int64)
                a = np.full(m, fill)
                b = np.full(m, fill)
                for kk in np.unique(k[nonempty]):
                    sel = nonempty & (k == kk)
                    t = table[kk]
                    a[sel] = t[L[sel]]
                    b[sel] = t[R[sel] - (1 << kk)]
                res = np.where(nonempty, red(a, b), fill)
            out[s0:e0] = res
            continue
        # clamp offsets into [-m, m] so a huge frame bound costs O(m), not
        # O(bound). Clamping BOTH directions (not just toward the segment)
        # keeps cs <= ce for any start <= end frame, so the sliding-window
        # width below stays positive even for a same-side frame wider than
        # the segment (e.g. 5 FOLLOWING..10 FOLLOWING over 3 rows — its
        # windows then index only fill padding and yield NULL)
        iseg = np.arange(m)
        cs = None if start is None else min(max(start, -m), m)
        ce = None if end is None else min(max(end, -m), m)
        if cs is None and ce is None:
            out[s0:e0] = acc(seg)[-1] if m else fill
        elif cs is None:
            run = acc(seg)
            out[s0:e0] = run[np.clip(iseg + ce, 0, m - 1)]
            if ce < 0:  # first rows have empty windows
                out[s0:e0][iseg + ce < 0] = fill
        elif ce is None:
            run = acc(seg[::-1])[::-1]
            out[s0:e0] = run[np.clip(iseg + cs, 0, m - 1)]
            if cs > 0:
                out[s0:e0][iseg + cs > m - 1] = fill
        elif ce - cs + 1 <= 0:
            # only reachable for an inverted frame (start > end); clamping
            # preserves bound order, so well-formed frames never land here
            out[s0:e0] = fill
        else:
            w = ce - cs + 1
            pad_before = -min(cs, 0)
            padded = np.concatenate(
                [np.full(pad_before, fill), seg,
                 np.full(max(ce, 0) + max(cs, 0), fill)]
            )
            # window for row i starts at padded[i + cs + pad_before]
            view = np.lib.stride_tricks.sliding_window_view(padded, w)
            sel = view[iseg + cs + pad_before]
            out[s0:e0] = sel.min(axis=1) if fn == "min" else sel.max(axis=1)
    # rows whose frame holds no (valid) rows are NULL per SQL (the fill
    # sentinel survives only when nothing real entered the window; genuine
    # +-inf inputs in an otherwise-real window are indistinguishable — a
    # documented corner)
    return out, out == fill

"""Window function execution.

WindowExec computes analytic functions over full partitions (unbounded
frame): row_number / rank / dense_rank and the five aggregates. Strategy:
merge to one partition, sort by (partition keys, order keys), compute
partition boundaries once, then every function is a vectorized pass —
cumcounts for ranking, segment-aggregate + broadcast-back for aggregates.
Output rows come back in sorted order (row order is unspecified unless the
query adds ORDER BY).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.errors import PlanError
from ballista_tpu.physical.expr import PhysicalExpr, _as_array
from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
    collect_partition,
)


class WindowFuncDesc:
    def __init__(
        self,
        fn: str,
        arg: Optional[PhysicalExpr],
        partition_by: List[PhysicalExpr],
        order_by: List[Tuple[PhysicalExpr, bool]],  # (expr, ascending)
        name: str,
        dtype: pa.DataType,
    ) -> None:
        self.fn = fn
        self.arg = arg
        self.partition_by = partition_by
        self.order_by = order_by
        self.name = name
        self.dtype = dtype


def _codes(arr: pa.Array) -> np.ndarray:
    d = pc.dictionary_encode(arr)
    if isinstance(d, pa.ChunkedArray):
        d = d.combine_chunks()
    out = d.indices.to_numpy(zero_copy_only=False).astype(np.int64)
    return out


class WindowExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, funcs: List[WindowFuncDesc]) -> None:
        self.input = input
        self.funcs = funcs
        fields = list(input.schema())
        fields += [pa.field(f.name, f.dtype) for f in funcs]
        self._schema = pa.schema(fields)

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "WindowExec":
        return WindowExec(children[0], self.funcs)

    def execute(self, partition: int, ctx: TaskContext):
        assert partition == 0
        table = collect_partition(self.input, 0, ctx)
        if table.num_rows == 0:
            yield from self._schema.empty_table().to_batches()
            return
        batch = table.combine_chunks().to_batches()[0]
        n = batch.num_rows
        out_cols = list(table.combine_chunks().columns)
        for f in self.funcs:
            out_cols.append(self._compute(f, batch, n))
        yield from batch_table(
            pa.table(out_cols, schema=self._schema), ctx.batch_size
        )

    # ------------------------------------------------------------------
    def _compute(self, f: WindowFuncDesc, batch: pa.RecordBatch, n: int) -> pa.Array:
        # sort order: partition keys then order keys
        sort_cols = {}
        sort_keys = []
        for i, e in enumerate(f.partition_by):
            cn = f"__p{i}"
            sort_cols[cn] = _as_array(e.evaluate(batch), n)
            sort_keys.append((cn, "ascending"))
        for i, (e, asc) in enumerate(f.order_by):
            cn = f"__o{i}"
            sort_cols[cn] = _as_array(e.evaluate(batch), n)
            sort_keys.append((cn, "ascending" if asc else "descending"))
        if sort_cols:
            key_table = pa.table(sort_cols)
            order = pc.sort_indices(key_table, sort_keys=sort_keys).to_numpy()
        else:
            order = np.arange(n, dtype=np.int64)
        inv = np.empty(n, dtype=np.int64)
        inv[order] = np.arange(n, dtype=np.int64)

        # partition ids in sorted order
        if f.partition_by:
            pcodes = np.zeros(n, dtype=np.int64)
            for i in range(len(f.partition_by)):
                c = _codes(sort_cols[f"__p{i}"])[order]
                pcodes = pcodes * (int(c.max()) + 1 if len(c) else 1) + c
            new_part = np.empty(n, dtype=bool)
            new_part[0] = True
            new_part[1:] = pcodes[1:] != pcodes[:-1]
        else:
            new_part = np.zeros(n, dtype=bool)
            new_part[0] = True
        part_id = np.cumsum(new_part) - 1
        part_start = np.maximum.accumulate(np.where(new_part, np.arange(n), 0))

        if f.fn == "row_number":
            vals = np.arange(n) - part_start + 1
            return pa.array(vals[inv], type=pa.int64())
        if f.fn in ("rank", "dense_rank"):
            # order-key change detection within a partition
            changed = np.ones(n, dtype=bool)
            if f.order_by:
                ocodes = np.zeros(n, dtype=np.int64)
                for i in range(len(f.order_by)):
                    c = _codes(sort_cols[f"__o{i}"])[order]
                    ocodes = ocodes * (int(c.max()) + 1 if len(c) else 1) + c
                changed[1:] = (ocodes[1:] != ocodes[:-1]) | new_part[1:]
            if f.fn == "rank":
                change_pos = np.maximum.accumulate(np.where(changed, np.arange(n), 0))
                vals = change_pos - part_start + 1
            else:
                dense = np.cumsum(changed)
                base = np.maximum.accumulate(np.where(new_part, dense, 0))
                vals = dense - base + 1
            return pa.array(vals[inv], type=pa.int64())

        # partition aggregates
        assert f.arg is not None or f.fn == "count"
        if f.arg is not None:
            argv = _as_array(f.arg.evaluate(batch), n)
            av = argv.to_numpy(zero_copy_only=False).astype(np.float64)[order]
            valid = pc.is_valid(argv).to_numpy(zero_copy_only=False)[order]
        else:
            av = np.ones(n, dtype=np.float64)
            valid = np.ones(n, dtype=bool)
        nparts = int(part_id[-1]) + 1
        if f.fn == "count":
            agg = np.zeros(nparts)
            np.add.at(agg, part_id, valid.astype(np.float64))
        elif f.fn in ("sum", "avg"):
            agg = np.zeros(nparts)
            np.add.at(agg, part_id, np.where(valid, av, 0.0))
            if f.fn == "avg":
                cnt = np.zeros(nparts)
                np.add.at(cnt, part_id, valid.astype(np.float64))
                agg = agg / np.maximum(cnt, 1)
        elif f.fn == "min":
            agg = np.full(nparts, np.inf)
            np.minimum.at(agg, part_id, np.where(valid, av, np.inf))
        elif f.fn == "max":
            agg = np.full(nparts, -np.inf)
            np.maximum.at(agg, part_id, np.where(valid, av, -np.inf))
        else:
            raise PlanError(f"unsupported window function {f.fn}")
        vals = agg[part_id][inv]
        return pc.cast(pa.array(vals), f.dtype)

    def fmt(self) -> str:
        return "WindowExec: " + ", ".join(
            f"{f.fn}(...) AS {f.name}" for f in self.funcs
        )

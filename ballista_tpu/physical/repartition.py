"""RepartitionExec: hash / round-robin redistribution.

Reference: PhysicalRepartition (rust/core/proto/ballista.proto:415-422,
serde from_proto.rs:133-164). In the distributed path the planner replaces
this with a stage boundary (shuffle write + shuffle read); this operator is
the in-process fallback and defines the row->partition hash contract shared
by the shuffle writer and the TPU all_to_all exchange.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.physical.expr import PhysicalExpr, _as_array
from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
)
from ballista_tpu.utils.locks import make_lock


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Stable 64-bit mix; the row-hash contract for hash partitioning."""
    z = (x.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def hash_rows(arrays: List[pa.Array], num_partitions: int) -> np.ndarray:
    """Map each row to a partition id by hashing key columns. Uses the C++
    kernel when available (bit-identical scheme), numpy otherwise."""
    from ballista_tpu.native import native_hash_rows

    native = native_hash_rows(arrays, num_partitions)
    if native is not None:
        return native.astype(np.int64)
    n = len(arrays[0])
    acc = np.zeros(n, dtype=np.uint64)
    for arr in arrays:
        a = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
        if pa.types.is_date32(a.type):
            a = a.cast(pa.int32())
        elif pa.types.is_date64(a.type) or pa.types.is_timestamp(a.type):
            a = a.cast(pa.int64())
        # NULL keys hash deterministically to 0 (NaN->int is platform-
        # dependent; a mixed cluster must agree on NULL's partition)
        null_mask = None
        if a.null_count:
            null_mask = pc.is_null(a).to_numpy(zero_copy_only=False)
            a = pc.fill_null(a, pa.scalar(0, type=a.type) if not pa.types.is_string(a.type) else "")
        if pa.types.is_integer(a.type) or pa.types.is_boolean(a.type):
            vals = pc.cast(a, pa.int64()).to_numpy(zero_copy_only=False).astype(np.int64)
            h = _splitmix64(vals.view(np.uint64) if vals.dtype == np.int64 else vals.astype(np.uint64))
        elif pa.types.is_floating(a.type):
            vals = a.to_numpy(zero_copy_only=False)
            h = _splitmix64(np.asarray(vals, dtype=np.float64).view(np.uint64))
        else:
            # strings / other: stable FNV-1a over utf8 bytes (python loop;
            # string partition keys are off the TPC-H hot path)
            h = np.empty(n, dtype=np.uint64)
            for i, v in enumerate(a.to_pylist()):
                if v is None:
                    h[i] = np.uint64(0)
                    continue
                acc2 = np.uint64(0xCBF29CE484222325)
                for b in str(v).encode():
                    acc2 = np.uint64((int(acc2) ^ b) * 0x100000001B3 & 0xFFFFFFFFFFFFFFFF)
                h[i] = acc2
        if null_mask is not None:
            h = np.where(null_mask, np.uint64(0), h)
        acc = _splitmix64(acc ^ h)
    return (acc % np.uint64(num_partitions)).astype(np.int64)


def split_by_partition(
    batch: pa.RecordBatch, part_ids: np.ndarray, n_out: int
) -> List[pa.RecordBatch]:
    """One-pass split: counting-sort row indices by partition (C++ kernel
    when available), then a single take + per-partition zero-copy slices —
    O(n + P) instead of P full-batch filters."""
    from ballista_tpu.native import native_partition_indices

    res = native_partition_indices(np.asarray(part_ids, dtype=np.int32), n_out)
    if res is None:
        order = np.argsort(part_ids, kind="stable")
        sorted_ids = np.asarray(part_ids)[order]
        offsets = np.searchsorted(sorted_ids, np.arange(n_out + 1))
        indices = order
    else:
        indices, offsets = res
    taken = batch.take(pa.array(indices))
    return [
        taken.slice(int(offsets[m]), int(offsets[m + 1] - offsets[m]))
        for m in range(n_out)
    ]


class RepartitionExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, partitioning: Partitioning) -> None:
        self.input = input
        self.partitioning = partitioning
        self._lock = make_lock("physical.repartition._lock")
        self._splits: Optional[List[pa.Table]] = None  # guarded-by: self._lock

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def output_partitioning(self) -> Partitioning:
        return self.partitioning

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "RepartitionExec":
        return RepartitionExec(children[0], self.partitioning)

    def split_batch(self, batch: pa.RecordBatch) -> List[pa.RecordBatch]:
        """Split one batch into num_partitions batches (shuffle-writer entry)."""
        n_out = self.partitioning.partition_count()
        if self.partitioning.scheme == "hash":
            keys = [
                _as_array(e.evaluate(batch), batch.num_rows)
                for e in self.partitioning.exprs
            ]
            part_ids = hash_rows(keys, n_out)
        else:
            part_ids = np.arange(batch.num_rows, dtype=np.int64) % n_out
        return split_by_partition(batch, part_ids, n_out)

    # executes the input plan while holding the lock (see join.py note)
    # may-acquire: group:exec_substrate
    def _materialize(self, ctx: TaskContext) -> List[pa.Table]:
        with self._lock:
            if self._splits is None:
                n_out = self.partitioning.partition_count()
                buckets: List[List[pa.RecordBatch]] = [[] for _ in range(n_out)]
                for p in range(self.input.output_partitioning().partition_count()):
                    for batch in self.input.execute(p, ctx):
                        for i, piece in enumerate(self.split_batch(batch)):
                            if piece.num_rows:
                                buckets[i].append(piece)
                self._splits = [
                    pa.Table.from_batches(bs, schema=self.schema())
                    if bs
                    else self.schema().empty_table()
                    for bs in buckets
                ]
            return self._splits

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        splits = self._materialize(ctx)
        yield from batch_table(splits[partition], ctx.batch_size)

    def fmt(self) -> str:
        return f"RepartitionExec: {self.partitioning!r}"

"""Row-preserving / reshaping operators: projection, filter, limits, coalesce,
merge, sort, empty, distinct.

Mirrors the reference's physical node set (PhysicalPlanNode variants,
rust/core/proto/ballista.proto:294-312): ProjectionExec, FilterExec,
GlobalLimitExec, LocalLimitExec, CoalesceBatchesExec, MergeExec, SortExec,
EmptyExec.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.errors import PlanError
from ballista_tpu.physical.expr import PhysicalExpr, _as_array
from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
    collect_partition,
)


class ProjectionExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, exprs: List[Tuple[PhysicalExpr, str]]) -> None:
        self.input = input
        self.exprs = exprs
        in_schema = input.schema()
        self._schema = pa.schema(
            [pa.field(name, e.data_type(in_schema)) for e, name in exprs]
        )

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "ProjectionExec":
        return ProjectionExec(children[0], self.exprs)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        # always the host Arrow path: a stand-alone device projection pays
        # h2d + d2h per batch with nothing fused around it; projections that
        # matter fuse into FusedAggregateStage / FactAggregateStage instead
        for batch in self.input.execute(partition, ctx):
            arrays = []
            for (e, _name), field in zip(self.exprs, self._schema):
                arr = _as_array(e.evaluate(batch), batch.num_rows)
                if arr.type != field.type:
                    arr = pc.cast(arr, field.type)
                arrays.append(arr)
            yield pa.RecordBatch.from_arrays(arrays, schema=self._schema)

    def fmt(self) -> str:
        return "ProjectionExec: " + ", ".join(f"{e} AS {n}" for e, n in self.exprs)


class FilterExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, predicate: PhysicalExpr) -> None:
        self.input = input
        self.predicate = predicate

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "FilterExec":
        return FilterExec(children[0], self.predicate)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        use_tpu = ctx.backend == "tpu" and ctx.config.tpu_per_op()
        if use_tpu:
            from ballista_tpu.ops.dispatch import tpu_filter
        for batch in self.input.execute(partition, ctx):
            if use_tpu:
                out = tpu_filter(batch, self.predicate)
                if out is not None:
                    if out.num_rows:
                        yield out
                    continue
            mask = _as_array(self.predicate.evaluate(batch), batch.num_rows)
            mask = pc.fill_null(mask, False)
            out = batch.filter(mask)
            if out.num_rows:
                yield out

    def fmt(self) -> str:
        return f"FilterExec: {self.predicate}"


class LocalLimitExec(ExecutionPlan):
    """Limit applied per partition (reference LocalLimitExecNode)."""

    def __init__(self, input: ExecutionPlan, limit: int) -> None:
        self.input = input
        self.limit = limit

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "LocalLimitExec":
        return LocalLimitExec(children[0], self.limit)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        remaining = self.limit
        for batch in self.input.execute(partition, ctx):
            if remaining <= 0:
                return
            if batch.num_rows > remaining:
                yield batch.slice(0, remaining)
                return
            remaining -= batch.num_rows
            yield batch

    def fmt(self) -> str:
        return f"LocalLimitExec: {self.limit}"


class GlobalLimitExec(ExecutionPlan):
    """Limit over a single input partition (reference GlobalLimitExecNode)."""

    def __init__(self, input: ExecutionPlan, limit: int, skip: int = 0) -> None:
        self.input = input
        self.limit = limit
        self.skip = skip

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "GlobalLimitExec":
        return GlobalLimitExec(children[0], self.limit, self.skip)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        assert partition == 0
        to_skip = self.skip
        remaining = self.limit
        for batch in self.input.execute(0, ctx):
            if to_skip >= batch.num_rows:
                to_skip -= batch.num_rows
                continue
            if to_skip:
                batch = batch.slice(to_skip)
                to_skip = 0
            if remaining <= 0:
                return
            if batch.num_rows > remaining:
                yield batch.slice(0, remaining)
                return
            remaining -= batch.num_rows
            yield batch

    def fmt(self) -> str:
        return f"GlobalLimitExec: {self.limit}"


class CoalesceBatchesExec(ExecutionPlan):
    """Re-chunk small batches up to a target size (reference
    CoalesceBatchesExecNode)."""

    def __init__(self, input: ExecutionPlan, target_batch_size: int) -> None:
        self.input = input
        self.target_batch_size = target_batch_size

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def output_partitioning(self) -> Partitioning:
        return self.input.output_partitioning()

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "CoalesceBatchesExec":
        return CoalesceBatchesExec(children[0], self.target_batch_size)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        buf: List[pa.RecordBatch] = []
        rows = 0
        for batch in self.input.execute(partition, ctx):
            buf.append(batch)
            rows += batch.num_rows
            if rows >= self.target_batch_size:
                table = pa.Table.from_batches(buf, schema=self.schema())
                yield from batch_table(table, self.target_batch_size)
                buf, rows = [], 0
        if buf:
            table = pa.Table.from_batches(buf, schema=self.schema())
            yield from batch_table(table, self.target_batch_size)

    def fmt(self) -> str:
        return f"CoalesceBatchesExec: target={self.target_batch_size}"


class MergeExec(ExecutionPlan):
    """N -> 1 partition merge (reference MergeExecNode / CollectExec)."""

    def __init__(self, input: ExecutionPlan) -> None:
        self.input = input

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "MergeExec":
        return MergeExec(children[0])

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        assert partition == 0
        for p in range(self.input.output_partitioning().partition_count()):
            yield from self.input.execute(p, ctx)

    def fmt(self) -> str:
        return "MergeExec"


class SortExec(ExecutionPlan):
    """Full sort of one input partition (reference SortExecNode; the planner
    merges partitions first)."""

    def __init__(
        self,
        input: ExecutionPlan,
        sort_keys: List[Tuple[PhysicalExpr, bool, bool]],  # (expr, ascending, nulls_first)
        fetch: Optional[int] = None,
    ) -> None:
        self.input = input
        self.sort_keys = sort_keys
        self.fetch = fetch

    def schema(self) -> pa.Schema:
        return self.input.schema()

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "SortExec":
        return SortExec(children[0], self.sort_keys, self.fetch)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        assert partition == 0
        table = collect_partition(self.input, 0, ctx)
        if table.num_rows == 0:
            yield from table.to_batches()
            return
        n = table.num_rows
        key_arrays = []
        names = []
        batch = table.combine_chunks().to_batches()[0]
        for i, (expr, asc, nulls_first) in enumerate(self.sort_keys):
            key_arrays.append(_as_array(expr.evaluate(batch), n))
            names.append(f"__sort_{i}")
        # pyarrow's sort_keys are (name, order) pairs with one GLOBAL
        # null_placement — per-key nulls_first is expressed by leading each
        # nullable key with its validity column (no nulls), so the key's own
        # nulls only ever compare against other nulls and the global
        # placement is irrelevant
        columns: Dict[str, pa.Array] = {}
        sort_opts = []
        for i, ((_, asc, nf), arr) in enumerate(zip(self.sort_keys, key_arrays)):
            if arr.null_count:
                columns[f"__nv_{i}"] = pc.is_null(arr)
                # True (null) first <=> descending on the bool validity key
                sort_opts.append((f"__nv_{i}", "descending" if nf else "ascending"))
            columns[names[i]] = arr
            sort_opts.append((names[i], "ascending" if asc else "descending"))
        key_table = pa.table(columns)
        indices = pc.sort_indices(key_table, sort_keys=sort_opts)
        if self.fetch is not None:
            indices = indices.slice(0, self.fetch)
        sorted_table = table.take(indices)
        yield from batch_table(sorted_table, ctx.batch_size)

    def fmt(self) -> str:
        keys = ", ".join(
            f"{e} {'ASC' if asc else 'DESC'}" for e, asc, _ in self.sort_keys
        )
        return f"SortExec: [{keys}]" + (f" fetch={self.fetch}" if self.fetch else "")


class EmptyExec(ExecutionPlan):
    """Empty relation, optionally one null-filled row (reference EmptyExecNode)."""

    def __init__(self, produce_one_row: bool, schema: pa.Schema) -> None:
        self.produce_one_row = produce_one_row
        self._schema = schema

    def schema(self) -> pa.Schema:
        if self.produce_one_row and len(self._schema) == 0:
            # a zero-column batch cannot carry a row count in Arrow; the
            # one-row case declares (and emits) a placeholder null column so
            # FROM-less SELECTs see num_rows == 1 AND consumers that trust
            # the declared schema (e.g. shuffle writers opening IPC files)
            # match the emitted batches
            return pa.schema([pa.field("__placeholder", pa.null())])
        return self._schema

    def output_partitioning(self) -> Partitioning:
        return Partitioning.unknown(1)

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        if self.produce_one_row:
            schema = self.schema()
            arrays = [pa.nulls(1, type=f.type) for f in schema]
            yield pa.RecordBatch.from_arrays(arrays, schema=schema)

    def fmt(self) -> str:
        return f"EmptyExec: produce_one_row={self.produce_one_row}"

"""Hash aggregation with Partial / Final / Single modes.

Mirrors the reference's HashAggregateExec two-phase split
(rust/core/proto/ballista.proto:370-384; the distributed planner cuts stages
at Final-mode aggregates, rust/scheduler/src/planner.rs:149-171):

- Partial: per-partition group-by producing *state* columns
  (sum -> sum; avg -> sum+count; count -> count; min/max -> min/max)
- Final: re-groups partial states by key and merges them
- Single: both phases fused (used when the input is one partition or for
  DISTINCT aggregates)

Host kernels use pyarrow's C++ hash group-by; the TPU backend lowers the same
plan through ballista_tpu.ops.groupby (dictionary-coded keys + segment ops).
"""

from __future__ import annotations

import enum
from typing import Iterator, List, Optional, Tuple

import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.errors import PlanError
from ballista_tpu.physical.expr import PhysicalExpr, _as_array
from ballista_tpu.physical.plan import (
    ExecutionPlan,
    Partitioning,
    TaskContext,
    batch_table,
    collect_partition,
)


class AggregateMode(enum.Enum):
    PARTIAL = "partial"
    FINAL = "final"
    SINGLE = "single"


class AggregateFunc:
    """One aggregate: fn in {sum, min, max, avg, count, count_distinct}."""

    def __init__(self, fn: str, expr: PhysicalExpr, name: str, dtype: pa.DataType,
                 input_type: pa.DataType) -> None:
        self.fn = fn
        self.expr = expr
        self.name = name
        self.dtype = dtype  # final output type
        self.input_type = input_type

    def state_fields(self) -> List[pa.Field]:
        if self.fn == "sum":
            return [pa.field(f"{self.name}[sum]", self.dtype)]
        if self.fn == "min":
            return [pa.field(f"{self.name}[min]", self.dtype)]
        if self.fn == "max":
            return [pa.field(f"{self.name}[max]", self.dtype)]
        if self.fn == "count":
            return [pa.field(f"{self.name}[count]", pa.int64())]
        if self.fn == "avg":
            return [
                pa.field(f"{self.name}[sum]", pa.float64()),
                pa.field(f"{self.name}[count]", pa.int64()),
            ]
        raise PlanError(f"no partial state for {self.fn!r}")

    def __repr__(self) -> str:
        return f"{self.fn.upper()}({self.expr}) AS {self.name}"


def needs_exact_float_minmax(agg) -> bool:
    """True when this aggregate's result is equality-consumed (decorrelated
    scalar subquery) AND it computes float MIN/MAX — the f32 device paths
    would round the value so it matches nothing; they must decline."""
    return getattr(agg, "exact_floats", False) and any(
        a.fn in ("min", "max") and pa.types.is_floating(a.input_type)
        for a in agg.aggr_funcs
    )


def _sum_type(dt: pa.DataType) -> pa.DataType:
    if pa.types.is_integer(dt):
        return pa.int64()
    return pa.float64()


def _cast_to_schema(columns, schema: pa.Schema) -> pa.Table:
    """Assemble output columns under a schema, casting where types differ."""
    arrays = []
    for col, field in zip(columns, schema):
        arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        if arr.type != field.type:
            arr = pc.cast(arr, field.type)
        arrays.append(arr)
    return pa.table(arrays, schema=schema)


class HashAggregateExec(ExecutionPlan):
    def __init__(
        self,
        mode: AggregateMode,
        input: ExecutionPlan,
        group_exprs: List[Tuple[PhysicalExpr, str]],
        aggr_funcs: List[AggregateFunc],
        exact_floats: bool = False,
    ) -> None:
        self.mode = mode
        self.input = input
        self.group_exprs = group_exprs
        self.aggr_funcs = aggr_funcs
        # float MIN/MAX results are equality-consumed (decorrelated scalar
        # subquery, q2): the f32 device paths must decline
        self.exact_floats = exact_floats
        in_schema = input.schema()

        group_fields = []
        if mode == AggregateMode.FINAL:
            # positional: keys arrive as the first k input columns
            for i, (_, name) in enumerate(group_exprs):
                f = in_schema.field(i)
                group_fields.append(pa.field(name, f.type))
        else:
            for e, name in group_exprs:
                group_fields.append(pa.field(name, e.data_type(in_schema)))

        if mode == AggregateMode.PARTIAL:
            agg_fields = [f for a in aggr_funcs for f in a.state_fields()]
        else:
            agg_fields = [pa.field(a.name, a.dtype) for a in aggr_funcs]
        self._schema = pa.schema(group_fields + agg_fields)

    def schema(self) -> pa.Schema:
        return self._schema

    def output_partitioning(self) -> Partitioning:
        if self.mode == AggregateMode.PARTIAL:
            return self.input.output_partitioning()
        if self.mode == AggregateMode.FINAL:
            # final aggregation runs per input partition (the planner ensures
            # keys are hash-disjoint across partitions, or input is merged)
            return Partitioning.unknown(
                self.input.output_partitioning().partition_count()
            )
        return Partitioning.unknown(1)

    def children(self) -> List[ExecutionPlan]:
        return [self.input]

    def with_children(self, children: List[ExecutionPlan]) -> "HashAggregateExec":
        return HashAggregateExec(
            self.mode, children[0], self.group_exprs, self.aggr_funcs,
            exact_floats=self.exact_floats,
        )

    # ------------------------------------------------------------------
    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        if ctx.backend == "tpu" and self.mode in (AggregateMode.PARTIAL, AggregateMode.SINGLE):
            from ballista_tpu.ops.dispatch import tpu_hash_aggregate
            out = tpu_hash_aggregate(self, partition, ctx)
            if out is not None:
                if self.mode == AggregateMode.SINGLE:
                    # the fused stage produces partial states; merge them to
                    # final values with the host merge (tiny input)
                    out = self._final(out)
                yield from batch_table(out, ctx.batch_size)
                return
        table = collect_partition(self.input, partition, ctx)
        if self.mode == AggregateMode.PARTIAL:
            out = self._partial(table)
        elif self.mode == AggregateMode.FINAL:
            out = self._final(table)
        else:
            out = self._single(table)
        yield from batch_table(out, ctx.batch_size)

    # -- phase implementations -----------------------------------------
    def _eval_inputs(self, table: pa.Table) -> Tuple[pa.Table, List[str], List[List[str]]]:
        """Materialize key columns and aggregate input columns."""
        if table.num_rows == 0:
            batch = pa.RecordBatch.from_arrays(
                [pa.array([], type=f.type) for f in table.schema], schema=table.schema
            )
        else:
            batch = table.combine_chunks().to_batches()[0]
        n = batch.num_rows
        cols = {}
        key_names = []
        for i, (e, _name) in enumerate(self.group_exprs):
            kn = f"__g{i}"
            cols[kn] = _as_array(e.evaluate(batch), n) if n else pa.array([], type=e.data_type(table.schema))
            key_names.append(kn)
        agg_in_names: List[List[str]] = []
        for j, a in enumerate(self.aggr_funcs):
            an = f"__a{j}"
            cols[an] = (
                _as_array(a.expr.evaluate(batch), n)
                if n
                else pa.array([], type=a.input_type)
            )
            agg_in_names.append([an])
        return pa.table(cols), key_names, agg_in_names

    def _partial(self, table: pa.Table) -> pa.Table:
        t, keys, agg_ins = self._eval_inputs(table)
        specs = []  # (col, fn, options, out_name_in_result)
        for a, (an,) in zip(self.aggr_funcs, agg_ins):
            if a.fn == "sum":
                specs.append((an, "sum", None))
            elif a.fn == "min":
                specs.append((an, "min", None))
            elif a.fn == "max":
                specs.append((an, "max", None))
            elif a.fn == "count":
                specs.append((an, "count", pc.CountOptions(mode="only_valid")))
            elif a.fn == "avg":
                specs.append((an, "sum", None))
                specs.append((an, "count", pc.CountOptions(mode="only_valid")))
            else:
                raise PlanError(f"partial mode cannot handle {a.fn}")
        result = self._group_aggregate(t, keys, specs)
        out_cols = [result[0].column(k) for k in range(len(keys))]
        out_cols += [result[1][i] for i in range(len(specs))]
        return _cast_to_schema(out_cols, self._schema)

    def _final(self, table: pa.Table) -> pa.Table:
        k = len(self.group_exprs)
        keys = [f"__g{i}" for i in range(k)]
        cols = {keys[i]: table.column(i) for i in range(k)}
        specs = []
        col_idx = k
        # merge state columns
        merged_names: List[List[int]] = []
        for a in self.aggr_funcs:
            state_n = len(a.state_fields())
            idxs = []
            for s in range(state_n):
                cn = f"__s{col_idx}"
                cols[cn] = table.column(col_idx)
                f = a.state_fields()[s]
                if a.fn in ("sum", "count", "avg"):
                    specs.append((cn, "sum", None))
                elif a.fn == "min":
                    specs.append((cn, "min", None))
                elif a.fn == "max":
                    specs.append((cn, "max", None))
                idxs.append(len(specs) - 1)
                col_idx += 1
            merged_names.append(idxs)
        t = pa.table(cols)
        key_tbl, agg_arrays = self._group_aggregate(t, keys, specs)
        out_arrays = [key_tbl.column(i) for i in range(k)]
        for a, idxs in zip(self.aggr_funcs, merged_names):
            if a.fn == "avg":
                s = agg_arrays[idxs[0]]
                c = agg_arrays[idxs[1]]
                out_arrays.append(pc.divide(pc.cast(s, pa.float64()), pc.cast(c, pa.float64())))
            elif a.fn == "count":
                # COUNT is never NULL: merging zero partial states (a global
                # aggregate whose input had no rows) must finalize to 0, but
                # pc.sum over an empty state column yields null
                out_arrays.append(pc.fill_null(agg_arrays[idxs[0]], 0))
            else:
                out_arrays.append(agg_arrays[idxs[0]])
        return _cast_to_schema(out_arrays, self._schema)

    def _single(self, table: pa.Table) -> pa.Table:
        t, keys, agg_ins = self._eval_inputs(table)
        specs = []
        for a, (an,) in zip(self.aggr_funcs, agg_ins):
            if a.fn == "avg":
                specs.append((an, "mean", None))
            elif a.fn == "count":
                specs.append((an, "count", pc.CountOptions(mode="only_valid")))
            elif a.fn == "count_distinct":
                specs.append((an, "count_distinct", None))
            else:
                specs.append((an, a.fn, None))
        key_tbl, agg_arrays = self._group_aggregate(t, keys, specs)
        out_arrays = [key_tbl.column(i) for i in range(len(keys))]
        out_arrays += agg_arrays
        return _cast_to_schema(out_arrays, self._schema)

    @staticmethod
    def _group_aggregate(t: pa.Table, keys: List[str], specs) -> Tuple[pa.Table, List[pa.ChunkedArray]]:
        """Run pyarrow hash group-by; return (key table, agg arrays in spec order).

        With no keys, produces the scalar-aggregate single row.
        """
        aggregations = [
            (col, fn) if opts is None else (col, fn, opts) for col, fn, opts in specs
        ]
        if keys:
            gb = t.group_by(keys, use_threads=False)
            res = gb.aggregate(aggregations)
            key_tbl = res.select(keys)
            agg_arrays = []
            for (col, fn, _opts) in specs:
                agg_arrays.append(res.column(f"{col}_{fn}"))
            return key_tbl, agg_arrays
        # scalar aggregation (no GROUP BY): aggregate over whole table
        agg_arrays = []
        for (col, fn, opts) in specs:
            arr = t.column(col)
            if fn == "sum":
                v = pc.sum(arr)
            elif fn == "min":
                v = pc.min(arr)
            elif fn == "max":
                v = pc.max(arr)
            elif fn == "mean":
                v = pc.mean(arr)
            elif fn == "count":
                v = pc.count(arr, mode="only_valid")
            elif fn == "count_distinct":
                v = pc.count_distinct(arr)
            else:
                raise PlanError(f"unknown scalar agg {fn}")
            agg_arrays.append(pa.chunked_array([pa.array([v.as_py()], type=v.type)]))
        return pa.table({}), agg_arrays

    def fmt(self) -> str:
        g = ", ".join(f"{e} AS {n}" for e, n in self.group_exprs)
        a = ", ".join(repr(x) for x in self.aggr_funcs)
        return f"HashAggregateExec: mode={self.mode.value}, gby=[{g}], aggr=[{a}]"

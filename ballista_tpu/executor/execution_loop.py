"""Executor poll loop + push-subscribe loop + task execution.

The reference's pull model (rust/executor/src/execution_loop.rs): every 250ms
the executor calls PollWork with its metadata, whether it can accept a task,
and the statuses of tasks that finished since the last poll (heartbeat and
work queue in one RPC). Returned TaskDefinitions are decoded and run on a
bounded task pool; results become Completed/Failed statuses pushed on the
next poll (ref as_task_status, execution_loop.rs:112-140).

The 250ms poll was a POC simplification (PAPER.md: "proof-of-concept"); at
serving QPS it puts half a poll interval of dead time in front of every
task. ISSUE 8 adds the push path: the executor opens ONE server-streaming
SubscribeWork stream and the scheduler pushes TaskDefinitions the moment
assignment picks them. The poll loop stays — as the heartbeat (statuses,
lease refresh, running_echo for ledger reconciliation) and as the AUTOMATIC
dispatch fallback: while the stream is healthy polls say
can_accept_task=False and their interval decays toward
ballista.executor.idle_poll_max_s; the moment the stream drops, the
interval snaps back to 250ms and polls pull work again, until the
re-subscribe (jittered backoff) succeeds.

Unlike the reference, task execution happens in-process rather than through
a loopback Flight call to the executor's own data plane
(ref execution_loop.rs:93-101 + the NOTE at flight_service.rs:90-91 saying
exactly this should happen).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import traceback
from typing import Optional

from ballista_tpu.config import BallistaConfig
from ballista_tpu.distributed.stages import ShuffleWriterExec
from ballista_tpu.executor.flight_service import flight_shuffle_fetcher
from ballista_tpu.physical.plan import TaskContext
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.rpc import SchedulerGrpcClient
from ballista_tpu.utils.locks import make_lock

log = logging.getLogger("ballista.executor")

POLL_INTERVAL_SECS = 0.25  # ref execution_loop.rs:75


class PollLoop:
    def __init__(
        self,
        scheduler: SchedulerGrpcClient,
        metadata: pb.ExecutorMetadata,
        work_dir: str,
        config: Optional[BallistaConfig] = None,
        concurrent_tasks: int = 4,  # ref executor_config_spec.toml default
        on_death=None,
    ) -> None:
        from ballista_tpu.utils.chaos import chaos_from_config

        from ballista_tpu.utils import locks as _locks

        self.scheduler = scheduler
        self.metadata = metadata
        self.work_dir = work_dir
        self.config = config or BallistaConfig()
        # ISSUE 14: arm the dynamic lock-order witness when configured
        _locks.maybe_enable_from_config(self.config)
        self.concurrent_tasks = concurrent_tasks
        self._available = threading.Semaphore(concurrent_tasks)
        self._finished: "queue.Queue[pb.TaskStatus]" = queue.Queue()
        self._stop = threading.Event()
        # lifecycle state shared between the poll thread and start()/stop()
        # callers (the queue/semaphore/event above are internally
        # thread-safe and need no extra guard)
        self._mu = make_lock("executor.execution_loop._mu")
        self._thread: Optional[threading.Thread] = None  # guarded-by: self._mu
        # shuffle-dir GC: the reference never collects work dirs
        # (SURVEY §5 "Nothing garbage-collects work dirs")
        self.shuffle_ttl_seconds = 3600.0
        self._last_gc = time.time()  # guarded-by: self._mu
        # deterministic fault injection (utils/chaos.py): "executor.death"
        # hard-stops this loop mid-run — on_death (wired by the runtime to
        # also shut the Flight data plane) makes the death total, so the
        # executor's completed shuffle outputs really become unreachable
        self._chaos = chaos_from_config(self.config)
        self._poll_n = 0  # poll-thread only: chaos key rotation
        self.on_death = on_death
        # tasks currently executing here, echoed in every poll so the
        # scheduler can reconcile assignments whose response never reached
        # us (lost-in-transit PollWork replies would otherwise orphan the
        # task in Running forever). The echo carries the ATTEMPT so a
        # restarted scheduler's ledger re-adoption never accepts a stale
        # attempt's vouch (ISSUE 6).
        self._inflight_mu = make_lock("executor.execution_loop._inflight_mu")
        # (job, stage, part) -> (PartitionId, attempt)
        # guarded-by: self._inflight_mu
        self._inflight: dict = {}
        # statuses popped from _finished by a poll whose RPC is still in
        # flight: a failed delivery requeues them, so drain() must not
        # declare the executor empty while any are outstanding
        self._delivering = 0  # guarded-by: self._inflight_mu
        # -- push dispatch (ISSUE 8) ------------------------------------
        self._push_enabled = self.config.push_dispatch()
        self._idle_poll_max = self.config.idle_poll_max_s()
        # set while the SubscribeWork stream is live: polls become pure
        # heartbeats (can_accept_task=False) and their interval decays
        self._stream_ok = threading.Event()
        self._subscribe_thread: Optional[threading.Thread] = None  # guarded-by: self._mu
        self._push_call = None  # live stream call, for cancel; guarded-by: self._mu
        self._poll_interval = POLL_INTERVAL_SECS  # guarded-by: self._mu
        # kicks the poll loop out of a decayed idle wait: a finishing task
        # must deliver its status NOW (job completion latency), and a
        # dropped stream must start fallback polling NOW — the backoff only
        # ever delays true idle heartbeats
        self._wake = threading.Event()
        # graceful scale-in (ISSUE 15): once set, this executor stops
        # offering slots (polls become pure heartbeats, the push stream is
        # cancelled and never re-opened) but keeps running — and reporting
        # — its in-flight tasks until they drain. drain() waits for that.
        self._draining = threading.Event()

    # ------------------------------------------------------------------
    def start(self) -> None:
        t = threading.Thread(target=self.run, daemon=True)
        with self._mu:
            self._thread = t
        t.start()
        if self._push_enabled:
            st = threading.Thread(target=self._subscribe_loop, daemon=True)
            with self._mu:
                self._subscribe_thread = st
            st.start()

    def _cancel_push(self) -> None:
        """Tear down the live push stream (stop/death): cancelling the call
        unblocks the subscribe thread AND lets the scheduler's stream
        generator observe the disconnect and unregister the subscriber."""
        with self._mu:
            call = self._push_call
        if call is not None:
            try:
                call.cancel()
            except Exception:
                pass

    def drain(self, timeout: float = 60.0) -> bool:
        """Graceful scale-in (ISSUE 15): stop accepting work, finish — and
        REPORT — every in-flight task, then return True. The poll loop
        keeps heartbeating throughout (statuses ride it; the lease stays
        fresh, so no recovery machinery fires on a draining executor), and
        the push stream is cancelled so the scheduler's pump stops
        offering credit here. Returns False when in-flight work outlives
        `timeout` — the caller decides whether to stop anyway (which would
        reintroduce the recovery path drain exists to avoid)."""
        from ballista_tpu.ops.runtime import record_fleet

        self._draining.set()
        self._cancel_push()
        self._wake.set()
        deadline = time.time() + timeout
        while time.time() < deadline and not self._stop.is_set():
            # one atomic read: pops out of _finished happen only inside
            # _drain_statuses' _inflight_mu section, so under the same
            # lock an undelivered status is in the queue OR in-delivery
            with self._inflight_mu:
                busy = (
                    bool(self._inflight)
                    or self._delivering > 0
                    or not self._finished.empty()
                )
            if not busy:
                # one synchronous flush: a racing heartbeat that failed
                # mid-delivery requeues its statuses — drain must not
                # declare victory while any are still undelivered
                try:
                    self.poll_once()
                except Exception:
                    pass
                with self._inflight_mu:
                    clean = (
                        self._delivering == 0 and self._finished.empty()
                    )
                if clean:
                    record_fleet("drain_completed")
                    return True
                continue
            # a finished task's status must leave on the NEXT poll, not a
            # decayed heartbeat
            self._wake.set()
            time.sleep(0.05)
        record_fleet("drain_timeout")
        return False

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        self._cancel_push()
        with self._mu:
            t = self._thread
            st = self._subscribe_thread
        if t:
            t.join(timeout=5)
        if st:
            st.join(timeout=5)

    def run(self) -> None:
        while not self._stop.is_set():
            self._poll_n += 1
            if self._chaos is not None and self._chaos.should_inject(
                "executor.death", f"{self.metadata.id}/poll{self._poll_n}"
            ):
                from ballista_tpu.ops.runtime import record_recovery

                record_recovery("chaos_injected")
                record_recovery("chaos_executor_death")
                log.warning(
                    "chaos[executor.death]: executor %s dying at poll %d",
                    self.metadata.id, self._poll_n,
                )
                self._stop.set()
                # a dead process's streams die with it: cancel so the
                # scheduler unregisters the subscriber and stops pushing
                self._cancel_push()
                if self.on_death is not None:
                    try:
                        self.on_death()
                    except Exception as e:
                        log.warning("on_death hook failed: %s", e)
                return
            try:
                self.poll_once()
            except Exception as e:
                # repeated poll failure only warns (ref execution_loop.rs:70-72)
                log.warning("poll failed: %s", e)
            with self._mu:
                gc_due = time.time() - self._last_gc > 60
                if gc_due:
                    self._last_gc = time.time()
            if gc_due:
                try:
                    self.gc_work_dir()
                except Exception as e:
                    log.warning("work-dir GC failed: %s", e)
            # adaptive idle backoff (ISSUE 8): while the push stream is
            # healthy the heartbeat decays toward the configured ceiling —
            # the steady-state PollWork load of an idle fleet collapses
            # without touching dispatch latency (push owns dispatch) or
            # crash tolerance (echo/lease ride whatever polls happen). The
            # subscribe loop snaps the interval back on stream loss.
            if self._stream_ok.is_set():
                with self._mu:
                    self._poll_interval = min(
                        self._poll_interval * 2.0, self._idle_poll_max
                    )
                    interval = self._poll_interval
            else:
                with self._mu:
                    self._poll_interval = POLL_INTERVAL_SECS
                    interval = POLL_INTERVAL_SECS
            if self._wake.wait(interval):
                self._wake.clear()
                with self._mu:
                    self._poll_interval = POLL_INTERVAL_SECS

    def gc_work_dir(self) -> int:
        """Delete shuffle job dirs idle longer than shuffle_ttl_seconds —
        in the private work dir AND (ISSUE 15) in this executor's
        configured shared storage root, which would otherwise grow without
        bound (a retired producer's pieces have no other owner). Every
        executor on the mount runs the same sweep; racing rmtrees of an
        expired dir are harmless (ignore_errors), and the TTL keeps live
        jobs' pieces far out of reach."""
        import shutil

        removed = 0
        cutoff = time.time() - self.shuffle_ttl_seconds
        roots = [self.work_dir]
        storage = self.config.shuffle_dir()
        if storage:
            roots.append(storage)
        for root in roots:
            if not os.path.isdir(root):
                continue
            for job_dir in os.listdir(root):
                path = os.path.join(root, job_dir)
                try:
                    if os.path.isdir(path) and os.path.getmtime(path) < cutoff:
                        shutil.rmtree(path, ignore_errors=True)
                        removed += 1
                        # the exchange registry (ISSUE 16) must not outlive
                        # the authoritative pieces it mirrors
                        from ballista_tpu.ops import exchange

                        exchange.evict_job(job_dir)
                except OSError:
                    continue
        if removed:
            log.info("gc: removed %d expired job dirs", removed)
        return removed

    # ------------------------------------------------------------------
    def _drain_statuses(self):
        """Pop every finished status AND count it in-delivery, atomically
        under _inflight_mu: drain() reads the queue and the _delivering
        counter under the same lock, so an undelivered status is ALWAYS
        visible to it — in the queue, or counted — with no window between
        the pop and the count."""
        with self._inflight_mu:
            out = []
            while True:
                try:
                    out.append(self._finished.get_nowait())
                except queue.Empty:
                    break
            self._delivering += len(out)
            return out

    def poll_once(self) -> bool:
        """One PollWork round; returns True if a task was received.

        The slot probe acquires ONCE, non-blocking, and hands the held slot
        to _run_task when a task arrives. (The previous probe-release-then-
        blocking-reacquire was a TOCTOU: concurrent completions between the
        probe and the reacquire could leave the poll thread BLOCKED on the
        semaphore, stopping heartbeats until a slot freed — long enough and
        a healthy executor got its lease lapsed and its tasks reset.)

        While the push stream is healthy this poll is a pure heartbeat:
        can_accept_task=False (dispatch belongs to the push path, and the
        latency harness asserts a healthy push cluster runs with ZERO
        poll-dispatched tasks); the moment the stream drops, polls pull
        work again — that IS the fallback."""
        slot_held = (
            False
            if self._stream_ok.is_set() or self._draining.is_set()
            else self._available.acquire(blocking=False)
        )
        # snapshot in-flight BEFORE draining statuses: a task finishing in
        # between is then reported as running (its status follows next
        # poll) rather than as neither — "neither" would read as an
        # orphaned assignment and trigger a spurious requeue
        with self._inflight_mu:
            inflight = list(self._inflight.values())
        # pops + the in-delivery count are one atomic step (see
        # _drain_statuses): a failed RPC puts them back below
        statuses = self._drain_statuses()
        try:
            params = pb.PollWorkParams(
                metadata=self.metadata, can_accept_task=slot_held
            )
            for pid, attempt in inflight:
                # both echo forms: running_tasks for wire compat with
                # pre-ISSUE-6 schedulers, running_echo (attempt-enriched)
                # for precise ledger reconciliation
                params.running_tasks.add().CopyFrom(pid)
                e = params.running_echo.add()
                e.partition_id.CopyFrom(pid)
                e.attempt = attempt
            for st in statuses:
                params.task_status.add().CopyFrom(st)
            result = self.scheduler.poll_work(params)
        except Exception:
            if slot_held:
                self._available.release()
            # the poll carried finished-task statuses; losing them would
            # wedge their jobs (the scheduler would wait forever) — requeue
            # for the next poll, which retries the delivery (BEFORE the
            # finally's _delivering decrement, so drain never observes
            # queue-empty + nothing-in-delivery while these are undelivered)
            for st in statuses:
                self._finished.put(st)
            raise
        finally:
            if statuses:
                with self._inflight_mu:
                    self._delivering -= len(statuses)
        if result.HasField("task"):
            self._register_inflight(result.task)
            # slot ownership transfers to the task thread (released in
            # _run_task's finally). A task arriving WITHOUT a held slot
            # (scheduler ignored can_accept_task=False) must not be
            # dropped — the task thread blocks for a slot itself, where
            # waiting cannot stall heartbeats
            threading.Thread(
                target=self._run_task,
                args=(result.task, slot_held),
                daemon=True,
            ).start()
            return True
        if slot_held:
            self._available.release()
        return False

    # -- push dispatch (ISSUE 8) ----------------------------------------
    def _subscribe_loop(self) -> None:
        """Keep ONE SubscribeWork stream open; run pushed tasks; on any
        drop, mark the stream unhealthy (polls snap back to 250ms and pull
        work — the automatic fallback) and re-subscribe with jittered
        backoff. A scheduler with push disabled answers UNIMPLEMENTED —
        still just a failed subscription here; the executor keeps probing
        at the backoff cap, so flipping the scheduler's config (or a
        rolling upgrade) picks the stream back up without a restart."""
        from ballista_tpu.ops.runtime import record_serving
        from ballista_tpu.scheduler.rpc import backoff_delay

        failures = 0
        while not self._stop.is_set() and not self._draining.is_set():
            params = pb.SubscribeWorkParams(slots=self.concurrent_tasks)
            params.metadata.CopyFrom(self.metadata)
            was_up = False
            try:
                call = self.scheduler.subscribe_work(params)
                with self._mu:
                    self._push_call = call
                # optimistic health: a refused/unreachable stream raises on
                # the first iteration below, within one scheduler tick
                self._stream_ok.set()
                was_up = True
                record_serving("push_subscribed")
                failures = 0
                for td in call:
                    self._on_pushed_task(td)
            except Exception as e:
                if not self._stop.is_set():
                    log.info("push stream down: %s", e)
            finally:
                self._stream_ok.clear()
                with self._mu:
                    self._push_call = None
                    self._poll_interval = POLL_INTERVAL_SECS
                if was_up:
                    record_serving("push_stream_drop")
                self._wake.set()  # fallback polling starts NOW
            if self._stop.is_set() or self._draining.is_set():
                return
            failures += 1
            self._stop.wait(backoff_delay(failures - 1, 0.05, cap=2.0))

    def _register_inflight(self, task: pb.TaskDefinition) -> None:
        """Track a received task — and every shared-scan batch sibling
        riding it (ISSUE 13) — in the running echo BEFORE execution starts,
        so the scheduler's orphaned-assignment grace never fires on a
        member whose batch is still being set up."""
        with self._inflight_mu:
            for td in (task, *task.siblings):
                pid = td.task_id
                self._inflight[(pid.job_id, pid.stage_id, pid.partition_id)] = (
                    pid, td.attempt,
                )

    def _on_pushed_task(self, task: pb.TaskDefinition) -> None:
        """One pushed TaskDefinition: exactly the poll-receive path, minus
        the held slot — the task thread blocks for its semaphore slot
        itself (the scheduler's credit keeps pushes ≈ slots; a transient
        overrun just queues on the semaphore, never drops work)."""
        from ballista_tpu.ops.runtime import record_serving

        self._register_inflight(task)
        record_serving("task_pushed")
        threading.Thread(
            target=self._run_task, args=(task, False), daemon=True
        ).start()

    def _member_setup(self, task: pb.TaskDefinition):
        """Status skeleton + confined, deserialized plan + task context for
        one member of a dispatch. Failures land in the member's OWN failed
        status (plan None) — in a shared-scan batch (ISSUE 13) a bad member
        must never take its siblings down. Returns (task, status, plan,
        ctx)."""
        import functools

        from ballista_tpu.serde.physical import phys_plan_from_proto

        pid = task.task_id
        status = pb.TaskStatus()
        status.partition_id.CopyFrom(pid)
        # echo the attempt in every reported status: the scheduler uses it
        # to drop stale reports from attempts it already reset — and the
        # speculative provenance (ISSUE 11), so a losing duplicate's drop
        # is attributable in the scheduler's logs/counters
        status.attempt = task.attempt
        status.speculative = task.speculative
        try:
            # allowlist comes from the EXECUTOR's own config; the per-job
            # settings merged below are client-controlled and must not
            # widen it. Proto check first: deserializing a parquet source
            # already reads the file footer.
            from ballista_tpu.executor.confine import (
                check_proto_scan_roots,
                check_scan_roots,
            )

            roots = self.config.data_roots()
            check_proto_scan_roots(task.plan, roots)
            plan = phys_plan_from_proto(task.plan)
            check_scan_roots(plan, roots)
            if not isinstance(plan, ShuffleWriterExec):
                plan = ShuffleWriterExec(pid.job_id, pid.stage_id, plan, None)
            cfg = self.config
            if task.settings:
                # the submitting client's per-job settings override the
                # executor's own defaults
                cfg = BallistaConfig(
                    {**cfg.to_dict(), **{kv.key: kv.value for kv in task.settings}}
                )
                # ... except the shuffle WRITE/READ home (ISSUE 15): like
                # the data_roots allowlist, an executor whose OWN config
                # pins a shuffle tier keeps it — per-job settings must not
                # steer os.replace publishes (or confine storage reads) to
                # a client-chosen host path. An unconfigured executor (the
                # standalone/local default, tier=local + no dir) lets the
                # job opt in, mirroring data_roots="" = unrestricted.
                from ballista_tpu.config import (
                    BALLISTA_SHUFFLE_DIR,
                    BALLISTA_SHUFFLE_TIER,
                )

                if (
                    self.config.shuffle_dir()
                    or self.config.shuffle_tier() != "local"
                ):
                    cfg = BallistaConfig({
                        **cfg.to_dict(),
                        BALLISTA_SHUFFLE_TIER: self.config.shuffle_tier(),
                        BALLISTA_SHUFFLE_DIR: self.config.shuffle_dir(),
                    })
            ctx = TaskContext(
                config=cfg,
                work_dir=self.work_dir,
                job_id=pid.job_id,
                # bind the merged config so fetch retries honor
                # ballista.rpc.* (incl. per-job overrides)
                shuffle_fetcher=functools.partial(
                    flight_shuffle_fetcher, config=cfg
                ),
                attempt=task.attempt,
                # keys the HBM-resident exchange registry (ISSUE 16) per
                # executor, so co-resident executors never cross-hit
                executor_id=self.metadata.id,
            )
            return task, status, plan, ctx
        except Exception as e:
            log.error("task %s setup failed: %s", pid, traceback.format_exc())
            status.failed.error = f"{type(e).__name__}: {e}"
            status.failed.executor_id = self.metadata.id
            return task, status, None, None

    def _member_execute(self, task, status, plan, ctx, shared=None) -> None:
        """Execute one member's plan, filling its status in place. `shared`
        carries a shared-scan batch's precomputed member tables (ISSUE 13);
        the splice happens inside kernels.hash_aggregate."""
        from ballista_tpu.errors import ShuffleFetchError
        from ballista_tpu.utils.chaos import chaos_from_config

        pid = task.task_id
        try:
            # chaos from the MERGED config: per-job settings can arm the
            # "task.execute" site for just their job. Keyed on the attempt
            # so a retried attempt draws a fresh deterministic verdict —
            # and applied PER MEMBER, so a faulted member of a batch fails
            # alone while its siblings complete.
            chaos = chaos_from_config(ctx.config)
            if chaos is not None:
                # keyed on plan coordinates + attempt, NOT the (random) job
                # id: the same seed faults the same tasks every run
                chaos.maybe_fail(
                    "task.execute",
                    f"{pid.stage_id}/{pid.partition_id}@a{task.attempt}",
                )
                if chaos.should_inject(
                    "task.slow",
                    f"{pid.stage_id}/{pid.partition_id}@a{task.attempt}",
                ):
                    # deterministic straggler (ISSUE 11): the task still
                    # completes correctly, just late — the seeded tail the
                    # speculation subsystem must beat. Keyed on the attempt,
                    # so a speculative duplicate (attempt N+1) draws a
                    # FRESH verdict and is not slowed with its primary.
                    from ballista_tpu.ops.runtime import record_recovery

                    delay = ctx.config.chaos_slow_ms() / 1000.0
                    record_recovery("chaos_injected")
                    record_recovery("chaos_slow_injected")
                    log.warning(
                        "chaos[task.slow]: delaying task %s/%s/%s attempt "
                        "%d by %.0fms", pid.job_id, pid.stage_id,
                        pid.partition_id, task.attempt, delay * 1000,
                    )
                    time.sleep(delay)
            if shared is not None:
                ctx.shared_scan = shared
            stats = plan.execute_shuffle_write(pid.partition_id, ctx)
            from ballista_tpu.distributed.stages import shuffle_output_base

            # the path-home the writer actually used: the shared storage
            # dir (tier=shared; storage_uri rides the completed status so
            # the piece set survives this executor, ISSUE 15) or this
            # executor's private work dir
            base, storage_uri = shuffle_output_base(
                ctx, pid.job_id, pid.stage_id, pid.partition_id
            )
            status.completed.executor_id = self.metadata.id
            status.completed.path = base
            if storage_uri:
                status.completed.storage_uri = storage_uri
            # advertise HBM residency (ISSUE 16): the scheduler folds this
            # into the consumer stage's ShuffleLocations (locality-aware
            # assignment) — a HINT only, the piece on disk stays the home
            from ballista_tpu.ops import exchange

            if exchange.stage_resident(
                self.metadata.id, pid.job_id, pid.stage_id, pid.partition_id
            ):
                status.completed.resident = True
            status.completed.stats.num_rows = stats.num_rows
            status.completed.stats.num_batches = stats.num_batches
            status.completed.stats.num_bytes = stats.num_bytes
            log.info(
                "task %s/%s/%s completed (%d rows)",
                pid.job_id, pid.stage_id, pid.partition_id, stats.num_rows,
            )
        except ShuffleFetchError as e:
            # a shuffle fetch died, not this task's own work: report
            # fetch_failed NAMING THE LOST LOCATION so the scheduler
            # recomputes just that map partition (lineage recovery)
            log.warning(
                "task %s/%s/%s fetch failed (lost %s:%s): %s",
                pid.job_id, pid.stage_id, pid.partition_id,
                e.executor_id, e.path, e,
            )
            status.fetch_failed.error = str(e)
            status.fetch_failed.executor_id = self.metadata.id
            status.fetch_failed.map_stage_id = e.stage_id
            status.fetch_failed.map_partition_id = e.map_partition
            status.fetch_failed.map_executor_id = e.executor_id
            status.fetch_failed.path = e.path
        except Exception as e:
            log.error("task %s failed: %s", pid, traceback.format_exc())
            status.failed.error = f"{type(e).__name__}: {e}"
            status.failed.executor_id = self.metadata.id

    def _run_task(self, task: pb.TaskDefinition, slot_held: bool = True) -> None:
        """Run one TaskDefinition — or a shared-scan batch group (ISSUE 13:
        the primary plus task.siblings) under ONE task slot. Each member
        gets its own status; a member failing at any point (setup, chaos,
        execution) fails alone, and compatible members' fused-aggregate
        stages are precomputed in one combined device launch over one
        shared upload before the members' plans execute."""
        if not slot_held:
            self._available.acquire()
        members = [task] + list(task.siblings)
        prepped = []
        reported = 0

        def report(td: pb.TaskDefinition, status: pb.TaskStatus) -> None:
            # enqueue the status BEFORE dropping from in-flight: a poll in
            # the gap then reports the task as still running (harmless)
            # instead of as vanished (which would look like an orphaned
            # assignment). Per member, AS IT FINISHES — member 1's job
            # completion must not wait out member 8's execution — and the
            # wake kicks the poll loop out of any decayed idle wait so no
            # status rides a multi-second heartbeat.
            self._finished.put(status)
            pid = td.task_id
            with self._inflight_mu:
                self._inflight.pop(
                    (pid.job_id, pid.stage_id, pid.partition_id), None
                )
            self._wake.set()

        try:
            for td in members:
                prepped.append(self._member_setup(td))
            shared = None
            if len(members) > 1:
                from ballista_tpu.ops import sharedscan

                try:
                    shared = sharedscan.precompute(
                        [
                            (plan, td.task_id.partition_id, ctx)
                            for td, _st, plan, ctx in prepped
                            if plan is not None
                        ],
                        max_batch=len(members),
                    )
                except Exception:
                    # the precompute is an accelerator: any failure means
                    # every member simply executes solo below
                    log.warning("shared-scan precompute failed; members "
                                "run solo", exc_info=True)
                    shared = None
            for td, status, plan, ctx in prepped:
                if plan is not None:
                    self._member_execute(td, status, plan, ctx, shared)
                report(td, status)
                reported += 1
        finally:
            self._available.release()
            # safety net: members never reached (an unexpected raise mid-
            # loop) still report — as failures, never as phantom pendings
            for td, status, _plan, _ctx in prepped[reported:]:
                if status.WhichOneof("status") is None:
                    status.failed.error = (
                        "batched execution aborted before this member ran"
                    )
                    status.failed.executor_id = self.metadata.id
                report(td, status)

"""Scan-path confinement for plans arriving over the wire.

An ExecutePartition ticket or a PollWork task carries a serialized physical
plan from an unauthenticated peer; deserialized scan nodes name host file
paths. The reference executes whatever the plan says
(rust/executor/src/flight_service.rs:90-192) — any readable file on the
executor host is fair game. Here the executor's OWN configuration (never
the per-job client settings, which a peer controls) may pin a data-root
allowlist, enforced in two layers:

  1. check_proto_scan_roots runs on the RAW proto before deserialization —
     constructing a ParquetTableSource already reads the file footer, so
     even building the plan from a hostile path would hand the peer an
     existence/readability oracle for arbitrary host files.
  2. check_scan_roots runs on the constructed plan — source file discovery
     resolves directories and symlinks, so the resolved file list is
     re-checked against the roots.
"""

from __future__ import annotations

import os
from typing import Iterator, List

from ballista_tpu.errors import PlanError


def _real_roots(roots: List[str]) -> List[str]:
    return [os.path.realpath(r) for r in roots]


def _contained_real(p: str, r: str) -> bool:
    """The single containment comparison for every trust boundary here —
    both arguments must already be realpath'd; a hardening fix to the rule
    itself lands everywhere at once."""
    return os.path.commonpath([r, p]) == r


def resolve_contained(path: str, root: str):
    """Returns the RESOLVED path when it lies inside root (symlinks
    followed), else None — callers must use the returned string, never
    re-resolve (a second realpath of a swapped symlink could escape the
    check)."""
    p = os.path.realpath(path)
    return p if _contained_real(p, os.path.realpath(root)) else None


def contained(path: str, root: str) -> bool:
    return resolve_contained(path, root) is not None


def _under(path: str, real_roots: List[str]) -> bool:
    p = os.path.realpath(path)
    return any(_contained_real(p, r) for r in real_roots)


def _walk_messages(msg) -> Iterator:
    yield msg
    for fd, val in msg.ListFields():
        if fd.type != fd.TYPE_MESSAGE:
            continue
        for v in (val if fd.is_repeated else [val]):
            yield from _walk_messages(v)


def check_proto_scan_roots(plan_proto, roots: List[str]) -> None:
    """Refuse scan paths outside the allowlist BEFORE the plan (and with it
    any table source that touches disk at construction) is deserialized."""
    if not roots:
        return
    from ballista_tpu.proto import ballista_pb2 as pb

    real = _real_roots(roots)
    for node in _walk_messages(plan_proto):
        if isinstance(node, pb.TableSourceDesc):
            # fail CLOSED: anything that is not the in-memory type is
            # treated as file-backed, so a future disk-backed table type
            # cannot silently bypass the check
            if node.table_type != "memory" and node.path:
                if not _under(node.path, real):
                    raise PlanError(
                        "scan path outside configured data roots refused: "
                        f"{node.path!r}"
                    )


def check_scan_roots_path(path: str, roots: List[str]) -> None:
    """Single-path form, for CREATE EXTERNAL TABLE locations and
    GetFileMetadata requests."""
    if roots and not _under(path, _real_roots(roots)):
        raise PlanError(
            f"scan path outside configured data roots refused: {path!r}"
        )


def check_scan_files(files, roots: List[str]) -> None:
    """Resolved-file-list form: discovery follows symlinks, so the files a
    source actually resolved to are re-checked against the roots."""
    if not roots:
        return
    real = _real_roots(roots)
    for f in files:
        if not _under(f, real):
            raise PlanError(
                f"scan path outside configured data roots refused: {f!r}"
            )


def check_scan_roots(plan, roots: List[str]) -> None:
    """Raise PlanError if a file-backed scan leaf escapes the allowlist.

    roots == [] means unrestricted (the standalone/local default, where the
    client and executor are the same trust domain).
    """
    if not roots:
        return

    def walk(node):
        src = getattr(node, "source", None)
        files = getattr(src, "files", None)
        if files:
            check_scan_files(files, roots)
        for c in node.children():
            walk(c)
        # stage wrappers that deliberately hide their subtree from planner
        # recursion (SpmdAggregateExec) still carry scans
        sub = getattr(node, "subplan", None)
        if sub is not None:
            walk(sub)

    walk(plan)

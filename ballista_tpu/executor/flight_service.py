"""Executor Flight data plane.

Arrow Flight do_get keyed on a protobuf Action ticket, like the reference
(rust/executor/src/flight_service.rs:80-230):

- FetchPartition: stream a materialized shuffle piece (schema-first framing
  comes with Flight itself) — serves peers (ShuffleReaderExec) and clients.
- ExecutePartition: execute a plan's partitions and materialize them
  (the push-based path; the pull-based poll loop executes tasks in-process
  instead — the reference's loopback-Flight-to-itself indirection
  (execution_loop.rs:93-101) is dropped deliberately).
"""

from __future__ import annotations

import logging
import os
import re
from typing import Iterator, Optional

import pyarrow as pa
import pyarrow.flight as flight

from ballista_tpu.config import BallistaConfig
from ballista_tpu.distributed.stages import ShuffleLocation
from ballista_tpu.physical.plan import TaskContext
from ballista_tpu.proto import ballista_pb2 as pb

log = logging.getLogger("ballista.executor.flight")

# job ids are 7-char alphanumeric (scheduler/state.py); anything path-like
# is hostile
_JOB_ID_RE = re.compile(r"[A-Za-z0-9_-]{1,64}")


class BallistaFlightService(flight.FlightServerBase):
    def __init__(self, location: str, work_dir: str, config: BallistaConfig) -> None:
        super().__init__(location)
        self.work_dir = work_dir
        self.config = config

    # ------------------------------------------------------------------
    def do_get(self, context, ticket: flight.Ticket) -> flight.RecordBatchStream:
        action = pb.Action()
        action.ParseFromString(ticket.ticket)
        which = action.WhichOneof("action_type")
        if which == "fetch_partition":
            path = self._resolve_work_path(action.fetch_partition.path)
            if self.config.tpu_exchange():
                # HBM-resident exchange (ISSUE 16): serve a registered
                # piece straight from memory instead of re-reading it off
                # disk — the same batches the authoritative IPC file holds,
                # so the stream is bit-identical to the file read. Confined
                # FIRST (_resolve_work_path above): the registry only ever
                # indexes paths this executor published itself, so a miss
                # falls through to the ordinary confined file read.
                from ballista_tpu.ops import exchange
                from ballista_tpu.ops.runtime import record_exchange

                hit = exchange.resolve_path(path) or exchange.resolve_path(
                    action.fetch_partition.path
                )
                if hit is not None:
                    schema, batches, nbytes = hit
                    record_exchange("served_from_registry")
                    record_exchange("d2h_bytes_saved", nbytes)
                    return flight.GeneratorStream(schema, iter(batches))
            if not os.path.isfile(path):
                raise flight.FlightServerError(f"no such shuffle piece: {path}")
            # batch-at-a-time so a fetch never materializes the whole
            # partition in executor memory (ref streams through a channel,
            # rust/executor/src/flight_service.rs:315-333)
            reader = pa.ipc.open_file(path)
            batches = (
                reader.get_batch(i) for i in range(reader.num_record_batches)
            )
            return flight.GeneratorStream(reader.schema, batches)
        if which == "execute_partition":
            return self._execute_partition(action.execute_partition, action.settings)
        raise flight.FlightServerError(f"unsupported action {which!r}")

    def _resolve_work_path(self, raw: str) -> str:
        """Confine ticket paths to this executor's work_dir — or, with the
        shared shuffle tier configured (ISSUE 15), to ITS OWN configured
        storage root (never a per-job override: the ticket comes from an
        unauthenticated peer, and self.config is the only trust anchor).
        The storage fallback is what makes Flight a real backup transport
        for storage-homed pieces: a reader without the mount can fetch them
        through any live executor that has it. Without either check
        FetchPartition would serve any readable file on the host
        (ADVICE r1, high)."""
        from ballista_tpu.executor.confine import resolve_contained

        resolved = resolve_contained(raw, self.work_dir)
        if resolved is None:
            storage = self.config.shuffle_dir()
            if storage:
                resolved = resolve_contained(raw, storage)
        if resolved is None:
            raise flight.FlightServerError(
                f"path outside work_dir refused: {raw!r}"
            )
        return resolved

    def _execute_partition(self, req: pb.ExecutePartition, settings) -> flight.RecordBatchStream:
        from ballista_tpu.serde.physical import phys_plan_from_proto
        from ballista_tpu.distributed.stages import ShuffleWriterExec

        # job_id is joined into work_dir paths by the shuffle writer; an
        # unauthenticated peer must not steer writes outside work_dir
        if not _JOB_ID_RE.fullmatch(req.job_id):
            raise flight.FlightServerError(f"invalid job id {req.job_id!r}")
        # allowlist comes from the EXECUTOR's own config; per-job client
        # settings (attacker-controlled) must not widen it. The proto-level
        # check runs BEFORE deserialization (which already opens parquet
        # footers); the plan-level check covers resolved files.
        from ballista_tpu.executor.confine import (
            check_proto_scan_roots,
            check_scan_roots,
        )

        roots = self.config.data_roots()
        check_proto_scan_roots(req.plan, roots)
        plan = phys_plan_from_proto(req.plan)
        check_scan_roots(plan, roots)
        import functools

        from ballista_tpu.config import BALLISTA_SHUFFLE_DIR, BALLISTA_SHUFFLE_TIER

        # like the scan-root allowlist above, the shuffle WRITE home comes
        # from the EXECUTOR's own config: an unauthenticated peer's
        # settings must not steer execute_shuffle_write's os.replace
        # publish to an arbitrary host path (pre-ISSUE-15 every write was
        # confined to work_dir by construction)
        cfg = BallistaConfig({
            **self.config.to_dict(),
            **{kv.key: kv.value for kv in settings},
            BALLISTA_SHUFFLE_TIER: self.config.shuffle_tier(),
            BALLISTA_SHUFFLE_DIR: self.config.shuffle_dir(),
        })
        ctx = TaskContext(config=cfg, work_dir=self.work_dir, job_id=req.job_id,
                          shuffle_fetcher=functools.partial(
                              flight_shuffle_fetcher, config=cfg))
        from ballista_tpu.distributed.stages import shuffle_output_base

        rows = []
        for p in req.partition_ids:
            if not isinstance(plan, ShuffleWriterExec):
                plan = ShuffleWriterExec(req.job_id, req.stage_id, plan, None)
            stats = plan.execute_shuffle_write(p, ctx)
            # the base the writer actually used (work dir, or the shared
            # storage dir when the merged config selects the shared tier)
            base, _storage = shuffle_output_base(ctx, req.job_id, req.stage_id, p)
            rows.append((base, stats.num_rows, stats.num_batches, stats.num_bytes))
        # 1-row-per-partition result batch (path, stats), ref flight_service.rs:135-160
        table = pa.table(
            {
                "path": pa.array([r[0] for r in rows]),
                "num_rows": pa.array([r[1] for r in rows], type=pa.int64()),
                "num_batches": pa.array([r[2] for r in rows], type=pa.int64()),
                "num_bytes": pa.array([r[3] for r in rows], type=pa.int64()),
            }
        )
        return flight.RecordBatchStream(table)


def flight_shuffle_fetcher(
    loc: ShuffleLocation, partition: int, config: Optional[BallistaConfig] = None
) -> Iterator[pa.RecordBatch]:
    """ShuffleReaderExec's remote path: Flight do_get(FetchPartition) against
    the executor owning the piece (ref client.rs:123-169). Bind `config`
    (functools.partial at TaskContext construction) so the data plane honors
    ballista.rpc.retries/backoff_ms like the control plane does."""
    from ballista_tpu.client.flight import BallistaClient

    action = pb.Action()
    action.fetch_partition.path = os.path.join(loc.path, f"{partition}.arrow")
    cfg = config or BallistaConfig()
    client = BallistaClient(
        loc.host, loc.port,
        retries=cfg.rpc_retries(), backoff_s=cfg.rpc_backoff_s(),
    )
    try:
        yield from client.stream_action(action)
    finally:
        client.close()

"""Executor process assembly + standalone (local) cluster.

BallistaExecutor ties together the Flight data plane and the poll loop
(reference rust/executor/src/main.rs). start_standalone_cluster is the
`--local` mode equivalent (ref main.rs:101-138): an in-process scheduler on
an embedded KV backend plus N executors, all in one process.
"""

from __future__ import annotations

import logging
import socket
import tempfile
import threading
import uuid
from typing import List, Optional, Tuple

import grpc

from ballista_tpu.config import BallistaConfig
from ballista_tpu.executor.execution_loop import PollLoop
from ballista_tpu.executor.flight_service import BallistaFlightService
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import KvBackend, MemoryBackend
from ballista_tpu.scheduler.rpc import SchedulerGrpcClient
from ballista_tpu.scheduler.server import SchedulerServer, serve

log = logging.getLogger("ballista.executor")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class BallistaExecutor:
    """One executor: Flight server + poll loop + work dir
    (ref BallistaExecutor/ExecutorConfig, rust/executor/src/lib.rs:20-49)."""

    def __init__(
        self,
        scheduler_host: str,
        scheduler_port: int,
        external_host: str = "127.0.0.1",
        port: Optional[int] = None,
        work_dir: Optional[str] = None,
        concurrent_tasks: int = 4,
        config: Optional[BallistaConfig] = None,
        executor_id: Optional[str] = None,
    ) -> None:
        self.id = executor_id or str(uuid.uuid4())
        self.host = external_host
        self.port = port or _free_port()
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-executor-")
        self.config = config or BallistaConfig()
        self.flight = BallistaFlightService(
            f"grpc://0.0.0.0:{self.port}", self.work_dir, self.config
        )
        self._flight_thread = threading.Thread(target=self.flight.serve, daemon=True)
        from ballista_tpu.utils.chaos import chaos_from_config

        self.scheduler_client = SchedulerGrpcClient(
            scheduler_host,
            scheduler_port,
            retries=self.config.rpc_retries(),
            backoff_s=self.config.rpc_backoff_s(),
            chaos=chaos_from_config(self.config),
        )
        meta = pb.ExecutorMetadata(id=self.id, host=self.host, port=self.port)
        self.poll_loop = PollLoop(
            self.scheduler_client,
            meta,
            self.work_dir,
            config=self.config,
            concurrent_tasks=concurrent_tasks,
            # chaos executor.death must be a TOTAL death: heartbeats stop
            # AND the data plane goes away, so completed shuffle outputs
            # really become unreachable and lineage recovery is exercised
            on_death=self.flight.shutdown,
        )

    def start(self) -> None:
        if self.config.tpu_prewarm():
            # AOT pre-warm BEFORE serving (ISSUE 8): compile every persisted
            # program so the first small query pays zero trace/compile. A
            # stale cache must never block executor start.
            from ballista_tpu.ops import aotcache

            try:
                aotcache.prewarm(self.config)
            except Exception as e:
                log.warning("aot prewarm failed: %s", e)
        self._flight_thread.start()
        self.poll_loop.start()
        log.info("executor %s serving flight on port %s", self.id, self.port)

    def stop(self) -> None:
        self.poll_loop.stop()
        self.flight.shutdown()
        self.scheduler_client.close()


class StandaloneCluster:
    """In-process scheduler + N executors (ref --local mode)."""

    def __init__(
        self,
        n_executors: int = 2,
        kv: Optional[KvBackend] = None,
        config: Optional[BallistaConfig] = None,
        concurrent_tasks: int = 4,
    ) -> None:
        self.config = config or BallistaConfig()
        self.kv = kv or MemoryBackend()
        self.scheduler_impl = SchedulerServer(self.kv, config=self.config)
        self.port = _free_port()
        self.grpc_server = serve(self.scheduler_impl, "127.0.0.1", self.port)
        self.executors: List[BallistaExecutor] = []
        for i in range(n_executors):
            ex = BallistaExecutor(
                "127.0.0.1",
                self.port,
                config=self.config,
                concurrent_tasks=concurrent_tasks,
                # stable ids: chaos keys (executor.death) and test
                # assertions address executors deterministically
                executor_id=f"local-{i}",
            )
            ex.start()
            self.executors.append(ex)

    @property
    def scheduler_addr(self) -> Tuple[str, int]:
        return ("127.0.0.1", self.port)

    def restart_scheduler(self) -> SchedulerServer:
        """Simulate scheduler process death + restart on the same KV store
        (ISSUE 6): stop the gRPC server, build a FRESH SchedulerServer over
        the same backend (its __init__ runs restart recovery — torn-job
        sweep + durable-ledger reload), and serve again on the same port so
        executors and clients ride their transient-UNAVAILABLE retry loops
        across the gap. All in-memory scheduler state (task index, ledger
        timestamps, planning threads) dies with the old instance — exactly
        what a real restart loses."""
        old = self.scheduler_impl
        # fence the old instance FIRST: its still-running planning threads
        # must not publish into the store the successor is recovering
        old.crashed = True
        # unblock the push-stream generators NOW (sentinel close) so the
        # stop below drains without waiting out their 0.25s tick — the gap
        # must stay inside retrying clients' backoff budget
        old.close_push_streams()
        # wait for the listening socket to actually close before rebinding
        # the same port (so_reuseport is not guaranteed everywhere)
        self.grpc_server.stop(grace=None).wait()
        self.scheduler_impl = SchedulerServer(self.kv, config=self.config)
        # test harness tuning survives the restart (a redeployed scheduler
        # keeps its deployment config)
        self.scheduler_impl.lost_task_check_interval = old.lost_task_check_interval
        self.grpc_server = serve(self.scheduler_impl, "127.0.0.1", self.port)
        return self.scheduler_impl

    def shutdown(self) -> None:
        for ex in self.executors:
            ex.stop()
        self.scheduler_impl.close_push_streams()
        self.grpc_server.stop(grace=None)

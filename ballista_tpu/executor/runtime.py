"""Executor process assembly + standalone (local) cluster.

BallistaExecutor ties together the Flight data plane and the poll loop
(reference rust/executor/src/main.rs). start_standalone_cluster is the
`--local` mode equivalent (ref main.rs:101-138): an in-process scheduler on
an embedded KV backend plus N executors, all in one process.
"""

from __future__ import annotations

import logging
import socket
import tempfile
import threading
import uuid
from typing import List, Optional, Tuple

import grpc

from ballista_tpu.config import BallistaConfig
from ballista_tpu.executor.execution_loop import PollLoop
from ballista_tpu.executor.flight_service import BallistaFlightService
from ballista_tpu.proto import ballista_pb2 as pb
from ballista_tpu.scheduler.kv import KvBackend, MemoryBackend
from ballista_tpu.scheduler.rpc import SchedulerGrpcClient
from ballista_tpu.scheduler.server import SchedulerServer, serve

log = logging.getLogger("ballista.executor")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class BallistaExecutor:
    """One executor: Flight server + poll loop + work dir
    (ref BallistaExecutor/ExecutorConfig, rust/executor/src/lib.rs:20-49)."""

    def __init__(
        self,
        scheduler_host: str,
        scheduler_port: int,
        external_host: str = "127.0.0.1",
        port: Optional[int] = None,
        work_dir: Optional[str] = None,
        concurrent_tasks: int = 4,
        config: Optional[BallistaConfig] = None,
        executor_id: Optional[str] = None,
        scheduler_endpoints: Optional[List[Tuple[str, int]]] = None,
    ) -> None:
        self.id = executor_id or str(uuid.uuid4())
        self.host = external_host
        self.port = port or _free_port()
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="ballista-executor-")
        self.config = config or BallistaConfig()
        self.flight = BallistaFlightService(
            f"grpc://0.0.0.0:{self.port}", self.work_dir, self.config
        )
        self._flight_thread = threading.Thread(target=self.flight.serve, daemon=True)
        from ballista_tpu.utils.chaos import chaos_from_config

        # replicated control plane (ISSUE 20): the extra endpoints let the
        # client rotate to a peer replica when its home scheduler dies —
        # failed polls rotate, and the ownership-redirect abort names the
        # new owner so re-homing converges in one hop
        self.scheduler_client = SchedulerGrpcClient(
            scheduler_host,
            scheduler_port,
            retries=self.config.rpc_retries(),
            backoff_s=self.config.rpc_backoff_s(),
            chaos=chaos_from_config(self.config),
            endpoints=scheduler_endpoints,
        )
        meta = pb.ExecutorMetadata(id=self.id, host=self.host, port=self.port)
        self.poll_loop = PollLoop(
            self.scheduler_client,
            meta,
            self.work_dir,
            config=self.config,
            concurrent_tasks=concurrent_tasks,
            # chaos executor.death must be a TOTAL death: heartbeats stop
            # AND the data plane goes away, so completed shuffle outputs
            # really become unreachable and lineage recovery is exercised
            on_death=self.flight.shutdown,
        )

    def drain(self, timeout: float = 60.0) -> bool:
        """Graceful scale-in (ISSUE 15): stop offering slots, finish and
        report every in-flight task. See PollLoop.drain."""
        return self.poll_loop.drain(timeout)

    def start(self) -> None:
        if self.config.tpu_prewarm():
            # AOT pre-warm BEFORE serving (ISSUE 8): compile every persisted
            # program so the first small query pays zero trace/compile. A
            # stale cache must never block executor start.
            from ballista_tpu.ops import aotcache

            try:
                aotcache.prewarm(self.config)
            except Exception as e:
                log.warning("aot prewarm failed: %s", e)
        self._flight_thread.start()
        self.poll_loop.start()
        log.info("executor %s serving flight on port %s", self.id, self.port)

    def stop(self) -> None:
        self.poll_loop.stop()
        self.flight.shutdown()
        self.scheduler_client.close()


class StandaloneCluster:
    """In-process scheduler + N executors (ref --local mode).

    Elastic fleet (ISSUE 15): with ballista.fleet.max > 0 an autoscaler
    thread re-sizes the fleet every ballista.fleet.interval_s against the
    admission queue's cost-model-predicted backlog seconds
    (SchedulerState.predicted_backlog_seconds) — scale-OUT spawns
    executors while the backlog exceeds ballista.fleet.target_backlog_s,
    scale-IN gracefully drains one executor per idle evaluation (stop
    offering slots, finish running tasks, retire) down to
    ballista.fleet.min. On the shared shuffle tier a retired executor's
    completed outputs stay readable from storage, so scale-in completes
    running jobs with zero task retries."""

    def __init__(
        self,
        n_executors: int = 2,
        kv: Optional[KvBackend] = None,
        config: Optional[BallistaConfig] = None,
        concurrent_tasks: int = 4,
        n_schedulers: int = 1,
    ) -> None:
        from ballista_tpu.utils.chaos import chaos_from_config
        from ballista_tpu.utils.locks import make_lock

        self.config = config or BallistaConfig()
        self.kv = kv or MemoryBackend()
        # replicated control plane (ISSUE 20): n_schedulers > 1 runs peer
        # SchedulerServer replicas over the SAME KV store, each with a
        # stable replica id and an advertised address (the ownership hint
        # clients/executors re-home on). n_schedulers == 1 keeps the legacy
        # anonymous single scheduler (replica_id "" — a restart reclaims
        # its predecessor's leases instead of adopting them as a peer).
        self.n_schedulers = max(1, n_schedulers)
        self.scheduler_impls: List[SchedulerServer] = []
        self.ports: List[int] = []
        self.grpc_servers: List[grpc.Server] = []
        for i in range(self.n_schedulers):
            port = _free_port()
            replica_id = f"replica-{i}" if self.n_schedulers > 1 else ""
            impl = SchedulerServer(
                self.kv,
                config=self.config,
                replica_id=replica_id,
                advertise_addr=f"127.0.0.1:{port}" if replica_id else "",
            )
            self.scheduler_impls.append(impl)
            self.ports.append(port)
            self.grpc_servers.append(serve(impl, "127.0.0.1", port))
        self._concurrent_tasks = concurrent_tasks
        # fleet membership: mutated by the autoscaler thread, read by
        # shutdown/tests. Executors are constructed and started OUTSIDE
        # the lock (their own threads take their own locks); only the
        # list/counter mutations sit under it.
        self._fleet_mu = make_lock("executor.runtime._fleet_mu")
        self.executors: List[BallistaExecutor] = []  # guarded-by: self._fleet_mu
        self._next_executor_idx = 0  # guarded-by: self._fleet_mu
        # fleet.scale chaos (ISSUE 15): a per-process decision sequence —
        # a torn verdict skips that evaluation's scale action, the next
        # evaluation draws fresh. Autoscaler-thread-only.
        self._fleet_chaos = chaos_from_config(self.config)
        self._fleet_seq = 0
        self._fleet_stop = threading.Event()
        self._fleet_thread: Optional[threading.Thread] = None
        for _ in range(n_executors):
            self._spawn_executor()
        if self.config.fleet_max() > 0:
            self._fleet_thread = threading.Thread(
                target=self._autoscale_loop, daemon=True
            )
            self._fleet_thread.start()

    def _spawn_executor(self) -> BallistaExecutor:
        """Start one executor with the next stable local-N id (chaos keys
        and test assertions address executors deterministically; ids are
        never reused across scale-in/out within one cluster)."""
        with self._fleet_mu:
            idx = self._next_executor_idx
            self._next_executor_idx += 1
        # round-robin home replica; the full (rotated) endpoint list rides
        # along so a dead home rotates to a live peer instead of stranding
        home = idx % self.n_schedulers
        endpoints = [
            ("127.0.0.1", self.ports[(home + k) % self.n_schedulers])
            for k in range(self.n_schedulers)
        ]
        ex = BallistaExecutor(
            "127.0.0.1",
            self.ports[home],
            config=self.config,
            concurrent_tasks=self._concurrent_tasks,
            executor_id=f"local-{idx}",
            scheduler_endpoints=endpoints,
        )
        ex.start()
        with self._fleet_mu:
            self.executors.append(ex)
        return ex

    def fleet_size(self) -> int:
        with self._fleet_mu:
            return len(self.executors)

    def _autoscale_loop(self) -> None:
        interval = self.config.fleet_interval_s()
        while not self._fleet_stop.wait(interval):
            try:
                self.autoscale_once()
            except Exception:
                log.warning("autoscaler evaluation failed", exc_info=True)

    def autoscale_once(self) -> int:
        """One autoscaler evaluation; returns the executor delta applied
        (+n grown, -1 drained, 0 no action). Public so tests and the bench
        harness can drive evaluations deterministically.

        Policy: desired = clamp(ceil(backlog / target_backlog_s),
        [min, max]) on a loaded queue — a deep backlog grows the fleet in
        ONE evaluation; an idle cluster (zero predicted backlog, nothing
        running) drains one executor per evaluation toward the floor, so
        scale-in stays gradual and each drain completes before the next
        starts."""
        import math

        from ballista_tpu.ops.runtime import (
            record_fleet,
            record_fleet_gauge,
            record_recovery,
        )

        fmin, fmax = self.config.fleet_min(), self.config.fleet_max()
        if fmax <= 0:
            return 0
        state = self.scheduler_impl.state
        with self.kv.lock():
            backlog = state.predicted_backlog_seconds()
            running = state.has_running_tasks()
        size = self.fleet_size()
        record_fleet("evaluations")
        record_fleet_gauge("backlog_ms", backlog * 1000.0)
        record_fleet_gauge("fleet_size", float(size))
        target = self.config.fleet_target_backlog_s()
        desired = size
        if backlog > target and size < fmax:
            desired = min(
                fmax, max(size + 1, math.ceil(backlog / target))
            )
        elif backlog <= 0.0 and not running and size > fmin:
            desired = size - 1
        if desired == size:
            return 0
        if self._fleet_chaos is not None:
            self._fleet_seq += 1
            if self._fleet_chaos.should_inject(
                "fleet.scale", f"scale{self._fleet_seq}"
            ):
                # torn BEFORE any executor is touched: the fleet keeps its
                # size this evaluation; the next draws a fresh verdict
                record_recovery("chaos_injected")
                record_fleet("scale_chaos_skipped")
                log.warning(
                    "chaos[fleet.scale]: scale %d -> %d skipped",
                    size, desired,
                )
                return 0
        if desired > size:
            for _ in range(desired - size):
                self._spawn_executor()
            record_fleet("scale_up", desired - size)
            record_fleet_gauge("fleet_size", float(desired))
            log.info("fleet scaled out %d -> %d (backlog %.2fs)",
                     size, desired, backlog)
            return desired - size
        return -1 if self.scale_in_one(floor=fmin) else 0

    def scale_in_one(self, timeout: float = 60.0, floor: int = 1) -> bool:
        """Gracefully retire the newest executor: drain (stop offering
        slots, finish — and report — running tasks), stop, remove. The ONE
        scale-in mechanism, shared by the autoscaler and operator-driven
        scale-in (tests/bench drive it mid-job: on the shared shuffle tier
        the retiree's completed outputs stay readable from storage, so a
        running job finishes with zero task retries). The drain runs
        outside the fleet lock — it can take as long as the executor's
        in-flight work. Returns False when the fleet is already at
        `floor`."""
        from ballista_tpu.ops.runtime import record_fleet, record_fleet_gauge

        with self._fleet_mu:
            if len(self.executors) <= max(1, floor):
                return False
            size = len(self.executors)
            ex = self.executors[-1]
        if not ex.drain(timeout=timeout):
            # capacity must actually shrink, so the retire proceeds — but
            # loudly: in-flight work dies with the executor and rides the
            # normal lease/orphan recovery (a retry), which is exactly what
            # a completed drain avoids. drain_timeout is already counted.
            log.warning(
                "scale-in drain of %s timed out after %.0fs; retiring with "
                "in-flight work (recovery will retry it)", ex.id, timeout,
            )
        ex.stop()
        with self._fleet_mu:
            if ex in self.executors:
                self.executors.remove(ex)
            size2 = len(self.executors)
        record_fleet("scale_down")
        record_fleet_gauge("fleet_size", float(size2))
        log.info("fleet scaled in: retired %s (%d -> %d)", ex.id, size, size2)
        return True

    # -- single-scheduler compat surface (replica 0) -------------------
    @property
    def scheduler_impl(self) -> SchedulerServer:
        return self.scheduler_impls[0]

    @property
    def port(self) -> int:
        return self.ports[0]

    @property
    def grpc_server(self) -> grpc.Server:
        return self.grpc_servers[0]

    @property
    def scheduler_addr(self) -> Tuple[str, int]:
        return ("127.0.0.1", self.port)

    @property
    def scheduler_addrs(self) -> List[str]:
        return [f"127.0.0.1:{p}" for p in self.ports]

    @property
    def scheduler_endpoints(self) -> List[Tuple[str, int]]:
        return [("127.0.0.1", p) for p in self.ports]

    def kill_scheduler(self, i: int) -> SchedulerServer:
        """Kill replica `i` PERMANENTLY (ISSUE 20 failover): fence its
        in-flight work, tear down its push streams and listening socket,
        and do NOT restart it. Its `leases/{job}` entries stop renewing;
        within one lease TTL an idle peer's housekeeping scan adopts the
        orphaned jobs via a scoped recovery run, and the dead replica's
        executors rotate to peer endpoints on their next failed poll."""
        impl = self.scheduler_impls[i]
        impl.crashed = True
        impl.stop_housekeeping()
        impl.close_push_streams()
        self.grpc_servers[i].stop(grace=None).wait()
        log.info("killed scheduler replica %d (%s)", i, impl.state.replica_id)
        return impl

    def restart_scheduler(self, i: int = 0) -> SchedulerServer:
        """Simulate scheduler process death + restart on the same KV store
        (ISSUE 6): stop the gRPC server, build a FRESH SchedulerServer over
        the same backend (its __init__ runs restart recovery — torn-job
        sweep + durable-ledger reload), and serve again on the same port so
        executors and clients ride their transient-UNAVAILABLE retry loops
        across the gap. All in-memory scheduler state (task index, ledger
        timestamps, planning threads) dies with the old instance — exactly
        what a real restart loses. The successor keeps the predecessor's
        replica identity, so it reclaims (not adopts) its own leases."""
        old = self.scheduler_impls[i]
        # fence the old instance FIRST: its still-running planning threads
        # must not publish into the store the successor is recovering
        old.crashed = True
        old.stop_housekeeping()
        # unblock the push-stream generators NOW (sentinel close) so the
        # stop below drains without waiting out their 0.25s tick — the gap
        # must stay inside retrying clients' backoff budget
        old.close_push_streams()
        # wait for the listening socket to actually close before rebinding
        # the same port (so_reuseport is not guaranteed everywhere)
        self.grpc_servers[i].stop(grace=None).wait()
        fresh = SchedulerServer(
            self.kv,
            config=self.config,
            replica_id=old.state.replica_id,
            advertise_addr=old.state.replica_addr,
        )
        # test harness tuning survives the restart (a redeployed scheduler
        # keeps its deployment config)
        fresh.lost_task_check_interval = old.lost_task_check_interval
        self.scheduler_impls[i] = fresh
        self.grpc_servers[i] = serve(fresh, "127.0.0.1", self.ports[i])
        return fresh

    def shutdown(self) -> None:
        self._fleet_stop.set()
        t = self._fleet_thread
        if t is not None:
            t.join(timeout=5)
        with self._fleet_mu:
            executors = list(self.executors)
        for ex in executors:
            ex.stop()
        for impl, srv in zip(self.scheduler_impls, self.grpc_servers):
            impl.stop_housekeeping()
            impl.close_push_streams()
            srv.stop(grace=None)

"""Executor daemon: python -m ballista_tpu.executor [--local ...]

(ref rust/executor/src/main.rs: config parse; --local spins an in-process
scheduler first, main.rs:101-138; start Flight server; run the poll loop.)
"""

from __future__ import annotations

import logging
import tempfile
import time

from ballista_tpu.config import BallistaConfig
from ballista_tpu.daemon_config import EXECUTOR_SPEC, load_config
from ballista_tpu.executor.runtime import BallistaExecutor
from ballista_tpu.scheduler.kv import SqliteBackend
from ballista_tpu.scheduler.server import SchedulerServer, serve


def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    log = logging.getLogger("ballista.executor")
    cfg = load_config(
        EXECUTOR_SPEC,
        "BALLISTA_EXECUTOR_",
        "/etc/ballista/executor.toml",
        prog="ballista-executor",
    )
    scheduler_host, scheduler_port = cfg["scheduler_host"], cfg["scheduler_port"]
    if cfg["local"]:
        kv = SqliteBackend(tempfile.mktemp(prefix="ballista-local-", suffix=".db"))
        impl = SchedulerServer(kv, namespace=cfg["namespace"])
        serve(impl, "127.0.0.1", cfg["scheduler_port"])
        scheduler_host = "127.0.0.1"
        log.info("in-process scheduler on port %s", scheduler_port)

    executor = BallistaExecutor(
        scheduler_host,
        scheduler_port,
        external_host=cfg["external_host"],
        port=cfg["port"],
        work_dir=cfg["work_dir"] or None,
        concurrent_tasks=cfg["concurrent_tasks"],
        config=BallistaConfig(
            {
                "ballista.executor.backend": cfg["backend"],
                "ballista.executor.data_roots": cfg["data_roots"],
                # disaggregated tier (ISSUE 15): a daemon-configured tier
                # is PINNED — per-job settings cannot redirect shuffle
                # writes/reads elsewhere (execution_loop re-pins both keys,
                # and the Flight data plane always uses this config) — and
                # the daemon's GC sweep owns this root's TTL
                "ballista.shuffle.tier": cfg["shuffle_tier"],
                "ballista.shuffle.dir": cfg["shuffle_dir"],
            }
        ),
    )
    executor.start()
    log.info(
        "Ballista-TPU executor up (id=%s, flight=%s:%s, backend=%s)",
        executor.id, cfg["external_host"], executor.port, cfg["backend"],
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        executor.stop()


if __name__ == "__main__":
    main()
